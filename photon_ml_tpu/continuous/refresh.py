"""The incremental refresh loop: warm-start everything, re-solve only
touched random-effect entities, carry the rest forward.

Why this is not just ``GameEstimator.fit(initial_models=...)``: coordinate
descent's residual accounting replaces a coordinate's WHOLE score vector
when the coordinate trains, so a random-effect coordinate restricted to
touched entities would lose the carried entities' score contribution.
The refresh loop keeps CD's residual discipline — ``total = offsets +
Σ scores[c]``, train against ``total - scores[c]`` — but merges per
coordinate: touched entities' rows take the fresh solve's scores, carried
entities' rows keep the prior model's (seeded once from
``model.score(data)``, exactly how CD seeds ``initial_models``).

The touched-only solve IS the full path: the touched entities' rows are
re-bucketed by :meth:`photon_ml_tpu.game.data.RandomEffectDataset.build`
(the untouched entities are masked to ``-1`` — the reader's "missing id"
convention — so they contribute no rows, no buckets and no solves) and
solved by the same :class:`~photon_ml_tpu.game.coordinate.
RandomEffectCoordinate` / vmapped-bucket machinery as cold training, warm
started from the prior model's coefficient table through the solver's
existing key join. Refresh cost is O(touched entities) compute plus one
O(n) scoring pass per coordinate for the seed.

Observability: ``photon_refresh_*`` counters (touched / carried / solved
entities per coordinate, patch bytes at publish) and ``refresh.*`` spans
(``refresh.sweep`` → ``refresh.step``). The publish side's fault site is
``io.delta_publish`` (io/pipeline.py + serving/registry.py).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Mapping, Optional, Sequence

import numpy as np

from photon_ml_tpu.evaluation import evaluate_all
from photon_ml_tpu.game.coordinate import (
    FixedEffectCoordinate,
    RandomEffectCoordinate,
)
from photon_ml_tpu.game.data import (
    FixedEffectDataset,
    GameData,
    RandomEffectDataset,
)
from photon_ml_tpu.game.estimator import (
    FixedEffectCoordinateConfig,
    GameOptimizationConfiguration,
    RandomEffectCoordinateConfig,
)
from photon_ml_tpu.game.model import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.telemetry import metrics as tmetrics
from photon_ml_tpu.telemetry import tracing
from photon_ml_tpu.types import TaskType

logger = logging.getLogger(__name__)


def _touched_counter():
    return tmetrics.counter(
        "photon_refresh_touched_entities_total",
        "Entities whose training data changed since the parent model "
        "(refit candidates), per refresh run", labels=("coordinate",))


def _carried_counter():
    return tmetrics.counter(
        "photon_refresh_carried_entities_total",
        "Entities whose coefficients carried forward untouched (unchanged "
        "or absent data)", labels=("coordinate",))


def _solved_counter():
    return tmetrics.counter(
        "photon_refresh_solved_entities_total",
        "Random-effect entities actually re-solved by the incremental "
        "refit (== touched entities surviving the active-data bounds, "
        "once per refresh sweep)", labels=("coordinate",))


def patch_bytes_counter():
    return tmetrics.counter(
        "photon_refresh_patch_bytes_total",
        "Bytes of published entity-level coefficient patches")


@dataclasses.dataclass
class CoordinateRefreshStats:
    """Per-coordinate accounting of one refresh run."""

    touched: int = 0
    carried: int = 0
    solved: int = 0


@dataclasses.dataclass
class RefreshResult:
    """One refresh run's outputs.

    ``model`` is the merged full model (touched entities fresh, carried
    entities bit-identical to the parent) — the next refresh's parent and
    the source of the full published directory. ``patch`` holds only what
    changed: every fixed-effect coordinate's (small, always-retrained)
    model plus, per touched random-effect coordinate, a partial
    :class:`RandomEffectModel` of just the re-solved entities.
    ``removed`` lists DENSE entity ids whose models vanished (touched
    entities that no longer clear the active-data bounds) — the driver
    maps them to raw ids for the patch metadata, which must communicate
    the removal or a stale serving row would keep scoring.
    """

    model: GameModel
    patch: dict[str, object]
    removed: dict[str, list[str]]
    stats: dict[str, CoordinateRefreshStats]
    validation_history: list[dict]
    final_evaluation: object = None


def partition_patch_by_shard(patch: Mapping[str, object],
                             removed_raw: Mapping[str, Sequence[str]],
                             vocabs: Mapping[str, Mapping[str, int]],
                             n_shards: int) -> list:
    """Split one refresh's coefficient patch into N per-host patches for
    an entity-sharded serving fleet (``refresh_game --fleet-shards N``).

    Shard ``i``'s patch carries: every fixed-effect coordinate's model IN
    FULL (the fixed effect is replicated on every host — all hosts must
    take the retrained one), and each random-effect coordinate's partial
    model restricted to the re-solved entities whose raw ids hash to
    shard ``i`` (``fleet/sharding.py::shard_of_id`` — the SAME function
    the serving store packed by, so a host is offered exactly the rows it
    owns and nothing else). ``removed_raw`` raw ids partition the same
    way. Returns ``[(patch_models, removed), ...]`` indexed by shard.

    The partition is exact: every touched entity lands in exactly one
    shard's patch, and concatenating the N patches reproduces the global
    one — per-host activation equals global activation, host by host.
    """
    from photon_ml_tpu.fleet.sharding import shard_of_id

    out = []
    for shard in range(int(n_shards)):
        models: dict[str, object] = {}
        removed: dict[str, list] = {}
        for cid, model in patch.items():
            if not isinstance(model, RandomEffectModel):
                models[cid] = model  # fixed effect: replicated everywhere
                continue
            reverse = {int(d): raw
                       for raw, d in vocabs[model.random_effect_type].items()}
            keys = np.asarray(model.keys, np.int64)
            ent = keys // model.dim
            mask = np.fromiter(
                (shard_of_id(reverse[int(e)], n_shards) == shard
                 for e in ent), bool, count=len(ent)) \
                if len(ent) else np.zeros(0, bool)
            models[cid] = dataclasses.replace(
                model, keys=keys[mask],
                coeffs=np.asarray(model.coeffs)[mask],
                variances=(None if model.variances is None
                           else np.asarray(model.variances)[mask]),
                coeffs_device=None)
        for cid, raws in (removed_raw or {}).items():
            mine = [raw for raw in raws
                    if shard_of_id(raw, n_shards) == shard]
            if mine:
                removed[cid] = mine
        out.append((models, removed))
    return out


def _masked_view(data: GameData, re_type: str,
                 touched: np.ndarray) -> tuple[GameData, np.ndarray]:
    """A view of ``data`` where every entity NOT in ``touched`` reads as
    absent (id ``-1``): the dataset build then buckets only touched
    entities, with untouched rows contributing nothing. Shares the
    original's device cache — same shards, same labels/weights, so the
    dense shard image and label uploads are reused, not re-shipped."""
    ids = data.id_columns[re_type]
    keep = np.isin(ids, touched)
    view = dataclasses.replace(
        data, id_columns={**data.id_columns,
                          re_type: np.where(keep, ids, np.int64(-1))})
    object.__setattr__(view, "_device_cache", data._device_cache)
    return view, keep


def refresh_game_model(
    task: TaskType,
    coordinate_configs: Mapping[str, object],
    update_sequence: Sequence[str],
    data: GameData,
    configuration: GameOptimizationConfiguration,
    initial_models: Mapping[str, object],
    touched_entities: Mapping[str, np.ndarray],
    *,
    n_sweeps: int = 1,
    validation=None,  # (GameData, evaluators) | zero-arg callable -> same
) -> RefreshResult:
    """Run ``n_sweeps`` incremental refresh sweeps.

    ``initial_models`` must cover EVERY coordinate in the update sequence
    (a refresh warm-starts an existing deployment; a coordinate without a
    parent model needs a full retrain, not a refresh).
    ``touched_entities`` maps random-effect coordinate ids to the DENSE
    entity ids whose data changed; a missing/empty entry means the whole
    coordinate carries forward without a single solve. Fixed-effect
    coordinates always retrain (the global data changed by definition when
    anything did; the solve is one warm-started GLM).
    """
    seq = list(update_sequence)
    missing = [cid for cid in seq if cid not in initial_models]
    if missing:
        raise ValueError(
            f"refresh needs a prior model for every coordinate; missing "
            f"{missing} — run a full train_game for new coordinates")
    models: dict[str, object] = {cid: initial_models[cid] for cid in seq}
    prior_entities: dict[str, np.ndarray] = {}

    # --- build coordinates once (touched-only datasets for REs) -----------
    coords: dict[str, object] = {}
    touched_masks: dict[str, np.ndarray] = {}
    stats = {cid: CoordinateRefreshStats() for cid in seq}
    for cid in seq:
        cfg = coordinate_configs.get(cid)
        if isinstance(cfg, FixedEffectCoordinateConfig):
            ds = FixedEffectDataset.build(cid, data, cfg.feature_shard_id)
            coords[cid] = FixedEffectCoordinate(
                coordinate_id=cid, dataset=ds, task=task,
                config=cfg.optimization, lam=configuration.lam(cid),
                downsampler=cfg.downsampler)
        elif isinstance(cfg, RandomEffectCoordinateConfig):
            prior = models[cid]
            prior_entities[cid] = (
                np.unique(prior.keys // prior.dim) if len(prior.keys)
                else np.zeros(0, np.int64))
            touched = np.asarray(touched_entities.get(cid, ()), np.int64)
            stats[cid].touched = len(touched)
            if not len(touched):
                continue  # whole coordinate carries forward
            view, keep = _masked_view(
                data, cfg.dataset.random_effect_type, touched)
            ds = RandomEffectDataset.build(cid, view, cfg.dataset)
            coords[cid] = RandomEffectCoordinate(
                coordinate_id=cid, dataset=ds, data=view, task=task,
                config=cfg.optimization, lam=configuration.lam(cid),
                design_dtype=cfg.design_dtype)
            touched_masks[cid] = keep
        else:
            raise ValueError(
                f"refresh does not support coordinate {cid!r} of type "
                f"{type(cfg).__name__} (factored coordinates re-learn a "
                f"projection — run a full retrain)")

    # --- seed the score decomposition from the prior model ----------------
    # (exactly how coordinate descent seeds initial_models: each
    # coordinate's full-data margin, so carried entities' contributions
    # are present in the residual from sweep 0)
    scores = {cid: np.asarray(models[cid].score(data), np.float32)
              for cid in seq}
    total = data.offsets.astype(np.float32)
    for cid in seq:
        total = total + scores[cid]

    patch: dict[str, object] = {}
    history: list[dict] = []
    final_evaluation = None
    for sweep in range(n_sweeps):
        with tracing.span("refresh.sweep", sweep=sweep):
            for cid in seq:
                coord = coords.get(cid)
                if coord is None:
                    continue  # carried random-effect coordinate
                with tracing.span("refresh.step", coordinate=cid,
                                  sweep=sweep):
                    residual = total - scores[cid]
                    model, new_scores = coord.train(
                        residual, models.get(cid), sweep=sweep)
                    new_scores = np.asarray(new_scores, np.float32)
                    if isinstance(coord, RandomEffectCoordinate):
                        _solved_counter().labels(coordinate=cid).inc(
                            model.n_entities)
                        stats[cid].solved += model.n_entities
                        mask = touched_masks[cid]
                        new_scores = np.where(mask, new_scores,
                                              scores[cid])
                        patch[cid] = model
                        model = models[cid].merge(
                            model,
                            drop_entities=touched_entities.get(cid, ()))
                    else:
                        patch[cid] = model
                    models[cid] = model
                    scores[cid] = new_scores
                    total = residual + new_scores
            if validation is not None:
                if callable(validation):
                    validation = validation()
                vdata, evaluators = validation
                with tracing.span("refresh.validate", sweep=sweep):
                    gm = GameModel(
                        coordinates={c: models[c] for c in seq}, task=task)
                    results = evaluate_all(
                        evaluators, gm.score(vdata), vdata.labels,
                        weights=vdata.weights, id_tags=vdata.id_columns)
                history.append(results.as_dict())
                final_evaluation = results
                logger.info("refresh sweep %d validation: %s", sweep,
                            results)

    # carried accounting + removals (touched entities that fell below the
    # active-data bounds: their prior model rows were dropped by merge and
    # the patch must tell serving to zero them)
    removed: dict[str, list[str]] = {}
    for cid in seq:
        cfg = coordinate_configs.get(cid)
        if not isinstance(cfg, RandomEffectCoordinateConfig):
            continue
        touched = np.asarray(touched_entities.get(cid, ()), np.int64)
        merged = models[cid]
        kept = (np.unique(merged.keys // merged.dim) if len(merged.keys)
                else np.zeros(0, np.int64))
        stats[cid].carried = int(
            len(np.setdiff1d(prior_entities[cid], touched,
                             assume_unique=False)))
        gone = np.setdiff1d(
            np.intersect1d(touched, prior_entities[cid]), kept)
        if len(gone):
            removed[cid] = [int(e) for e in gone]
        _touched_counter().labels(coordinate=cid).inc(len(touched))
        _carried_counter().labels(coordinate=cid).inc(stats[cid].carried)
    return RefreshResult(
        model=GameModel(coordinates={cid: models[cid] for cid in seq},
                        task=task),
        patch=patch, removed=removed, stats=stats,
        validation_history=history, final_evaluation=final_evaluation)
