"""Continuous training: warm-start refresh, incremental refit, delta publish.

Photon-ML's production story is a continuously refreshing GLMix deployment
(PAPER.md §0, §GAME): periodic retrains warm-started from the previous
model, where per-entity random effects are millions of tiny independent
solves and MOST entities see no new data between refreshes. This package
closes the train→serve loop the repo already has both ends of:

- :mod:`~photon_ml_tpu.continuous.delta` — per-entity data fingerprints
  and the ``data-manifest.json`` recorded with every published model, so a
  refresh can tell exactly which entities' training data changed since the
  model it warm-starts from.
- :mod:`~photon_ml_tpu.continuous.refresh` — the refresh loop itself:
  every optimizer seeded from the prior model (GLM solves start from the
  prior coefficient vector; GAME coordinates through the estimator's
  ``initial_models`` machinery), random-effect coordinates re-solve ONLY
  the touched entities (bucketed exactly like the full path in
  ``game/random_effect.py``) and every untouched entity's coefficients
  carry forward — refresh cost O(touched entities), not O(all entities).

The refresh output is both a full model directory (the next refresh's
warm-start parent) and an *entity-level coefficient patch*
(``io/model_io.py::save_game_model_patch``) that serving activates by
overwriting only the touched rows of its dense device tables
(``serving/store.py::EntityCoefficientStore.apply_patch`` via
``serving/registry.py::ModelRegistry.load_patch``) instead of rebuilding
them. See CONTINUOUS.md for the loop architecture, the patch format, and
the failure semantics around the ``io.delta_publish`` fault site.
"""

from photon_ml_tpu.continuous.delta import (  # noqa: F401
    MANIFEST_NAME,
    EntityDelta,
    build_manifest,
    entity_delta,
    entity_fingerprints,
    load_manifest,
    manifest_digest,
    save_manifest,
)
from photon_ml_tpu.continuous.refresh import (  # noqa: F401
    RefreshResult,
    refresh_game_model,
)
