"""GAME coordinates: one trainable block of the additive model.

Re-design of ``photon-api/.../algorithm/{Coordinate, FixedEffectCoordinate,
RandomEffectCoordinate}.scala``. A coordinate owns its dataset and
optimization problem; ``train(offsets, warm_start)`` fits against the
residual offsets coordinate descent supplies and returns (model, scores)
where ``scores`` is this coordinate's margin contribution per global sample.
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache, partial
from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.game.data import (
    FixedEffectDataset,
    GameData,
    RandomEffectDataset,
)
from photon_ml_tpu.game.model import (
    FixedEffectModel,
    RandomEffectModel,
)
from photon_ml_tpu.game.random_effect import RandomEffectSolver
from photon_ml_tpu.glm.problem import GLMOptimizationConfiguration, OptimizationProblem
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.ops.losses import loss_for_task
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.sampling import DownSampler
from photon_ml_tpu.telemetry import profiling
from photon_ml_tpu.types import TaskType

CoordinateModel = Union[FixedEffectModel, RandomEffectModel]


@lru_cache(maxsize=None)
def _fixed_train_fn(task: TaskType, config: GLMOptimizationConfiguration):
    """One compiled fixed-effect train step per (task, config).

    ``fused=True`` engages the one-pass Pallas value+grad (and Hvp) kernels
    on TPU for dense designs (transparent fallback otherwise —
    ops/pallas_glm.py). The mesh-sharded variant below enables them inside
    its shard_map bodies too, both validated on-chip through a mesh.
    ``profile_jit`` (vs a bare ``jax.jit``) adds the compile/execute
    accounting the flat-recompile contract asserts on — the solve program
    must compile once per (task, config, shapes) and never again across
    sweeps or grid points."""
    problem = OptimizationProblem(
        GLMObjective(loss=loss_for_task(task), fused=True), config)

    def train(data, w0, lam):
        result = problem.run(data, w0, lam)
        variances = problem.compute_variances(result.w, data, lam)
        scores = data.design.matvec(result.w)
        return result, variances, scores

    return profiling.profile_jit(train, "game.fixed_effect")


@lru_cache(maxsize=None)
def _fixed_train_fn_dist(task: TaskType, config: GLMOptimizationConfiguration,
                         mesh):
    """Mesh-sharded variant: the same OptimizationProblem drives the
    shard_map/psum objective (the collapse of the reference's Distributed vs
    SingleNode class split — SURVEY.md §2.3). ``data`` is the stacked
    per-device layout from ``shard_glm_data``. ``fused=True``: the one-pass
    Pallas value+grad kernel runs inside the shard_map body too (validated
    on-chip through a mesh: 1.31x over the XLA closed form per shard; the
    kernel's out_shapes carry the block's vma so the checker accepts it)."""
    from photon_ml_tpu.parallel.distributed import DistributedGLMObjective

    dist = DistributedGLMObjective(
        objective=GLMObjective(loss=loss_for_task(task), fused=True),
        mesh=mesh)
    problem = OptimizationProblem(dist, config)

    def train(data, w0, lam):
        result = problem.run(data, w0, lam)
        variances = problem.compute_variances(result.w, data, lam)
        # offset-free margins: CD owns the additive-score accounting
        no_off = dataclasses.replace(
            data, offsets=jnp.zeros_like(data.offsets))
        scores = dist.margins(result.w, no_off)  # (n_shards, per)
        return result, variances, scores

    return profiling.profile_jit(train, "game.fixed_effect.dist")


@lru_cache(maxsize=None)
def _factored_projection_cache(task: TaskType,
                               config: GLMOptimizationConfiguration, mesh):
    """One compiled distributed projection solve per (task, config, mesh)
    for the multi-process factored coordinate: the implicit Khatri-Rao
    design shards over the data axis and the solve psums — the same
    machinery as the distributed fixed effect, driving ``vec(P)``."""
    from photon_ml_tpu.parallel.distributed import DistributedGLMObjective

    dist = DistributedGLMObjective(
        objective=GLMObjective(loss=loss_for_task(task)), mesh=mesh)
    problem = OptimizationProblem(dist, config)

    def run(data, w0, lam):
        return problem.run(data, w0, lam)

    return profiling.profile_jit(run, "game.factored_projection")


@dataclasses.dataclass(frozen=True)
class FixedEffectCoordinate:
    """Cluster-wide GLM solve for the global coordinate
    (reference ``algorithm/FixedEffectCoordinate.scala``).

    The solve is a single compiled on-device optimizer run; per-CD-iteration
    down-sampling (reference behavior for dominant-class data) reweights via
    the coordinate's :class:`DownSampler`, applied to a fresh weight vector
    each sweep.
    """

    coordinate_id: str
    dataset: FixedEffectDataset
    task: TaskType
    config: GLMOptimizationConfiguration
    lam: float = 0.0
    downsampler: Optional[DownSampler] = None

    def __post_init__(self):
        self.config.regularization.check_weight(self.lam)

    def train(self, offsets,
              warm_start: Optional[FixedEffectModel] = None,
              sweep: int = 0) -> tuple[FixedEffectModel, jax.Array]:
        """``offsets`` may be host numpy or a device array (coordinate
        descent keeps the residual accounting on device); the returned
        ``scores`` is a device vector."""
        data = self.dataset.glm_data(offsets)
        if self.downsampler is not None:
            # uids = global row ids in the data's layout (the stacked dp
            # layout is contiguous row blocks, so a plain arange reshape is
            # the id map; padded tail rows draw too but carry weight 0).
            # Keyed draws make the sample identical across 1-chip, dp, and
            # multi-process runs of the same data.
            labels_np = np.asarray(data.labels)
            uids = np.arange(labels_np.size, dtype=np.int64).reshape(
                labels_np.shape)
            weights = self.downsampler.downsample(
                labels_np, np.asarray(data.weights), sweep=sweep, uids=uids)
            data = dataclasses.replace(data, weights=jnp.asarray(weights))
        w0 = (jnp.zeros((self.dataset.dim,), jnp.float32)
              if warm_start is None
              else jnp.asarray(warm_start.model.coefficients.means))
        if self.dataset.n_shards > 1:
            train_fn = _fixed_train_fn_dist(self.task, self.config,
                                            self.dataset.mesh)
        else:
            train_fn = _fixed_train_fn(self.task, self.config)
        result, variances, scores = train_fn(
            data, w0, jnp.asarray(self.lam, jnp.float32))
        from photon_ml_tpu.telemetry import tracing

        if tracing.enabled():
            # the reference's OptimizationStatesTracker table, folded into
            # trace.jsonl + the metrics registry. Gated: reading the trace
            # arrays syncs the device, which a bare run's async dispatch
            # must not pay.
            from photon_ml_tpu.telemetry import record_optimizer_trace

            record_optimizer_trace(self.coordinate_id, result, sweep=sweep)
        scores = scores.reshape(-1)
        if self.dataset.n_shards > 1:
            scores = scores[:self.dataset.n_samples]  # drop tail padding
        model = FixedEffectModel(
            model=GeneralizedLinearModel(
                coefficients=Coefficients(means=result.w, variances=variances),
                task=self.task),
            feature_shard_id=self.dataset.feature_shard_id)
        return model, scores


@dataclasses.dataclass(frozen=True)
class RandomEffectCoordinate:
    """Per-entity solves for one random-effect coordinate
    (reference ``algorithm/RandomEffectCoordinate.scala``).

    Active samples are scored in the bucket layout on device; passive
    samples score on device too via the cached static key-table join
    (:meth:`_passive_scores_device`), with the model's host-side join as
    the fallback for projected/loaded models. Unseen future data goes
    through the model/transformer host path.
    """

    coordinate_id: str
    dataset: RandomEffectDataset
    data: GameData  # for passive scoring
    task: TaskType
    config: GLMOptimizationConfiguration
    lam: float = 0.0
    #: optional mesh with an ``"entity"`` axis → entity-parallel solves
    #: (reference ``RandomEffectDatasetPartitioner`` sharding).
    mesh: Optional[object] = None
    #: "float32" or "bfloat16" — see RandomEffectCoordinateConfig
    design_dtype: str = "float32"

    def __post_init__(self):
        self.config.regularization.check_weight(self.lam)

    @property
    def solver(self) -> RandomEffectSolver:
        return RandomEffectSolver(task=self.task, config=self.config,
                                  mesh=self.mesh,
                                  design_dtype=self.design_dtype)

    def train(self, offsets,
              warm_start: Optional[RandomEffectModel] = None,
              sweep: int = 0) -> tuple[RandomEffectModel, jax.Array]:
        shard_dim = self.data.shards[self.dataset.config.feature_shard_id].dim
        model, scores = self.solver.train(
            self.dataset, offsets, self.lam, warm_start, dim=shard_dim)
        passive = self.dataset.passive_sample_idx
        if len(passive):
            # reference passiveData scoring: trained model, scored-only rows
            if (model.coeffs_device is not None and len(model.keys)
                    and model.projector is None):
                scores = self._passive_scores_device(model, scores)
            else:
                # host join fallback (projected / loaded / empty models)
                scores = scores.at[passive].set(
                    jnp.asarray(model.score(self.data, sample_idx=passive)))
        return model, scores

    def _passive_scores_device(self, model: RandomEffectModel,
                               scores: jax.Array) -> jax.Array:
        """Passive rows scored on device: the (entity, feature) → table-slot
        join is STATIC across sweeps (the model's key set is determined by
        the dataset, not the coefficients), so the searchsorted positions,
        found-masks and per-row segment ids are computed once on host and
        cached; each sweep is then one gather from the model's device
        coefficient table + a segment-sum — no host join, no per-sweep H2D
        of O(passive) scores."""
        cache = self.dataset._device_cache
        entry = cache.get(("passive",))
        if entry is not None:
            # the join is only static for THIS model's key table — a model
            # trained from a different dataset in-process must not reuse it
            # (mirrors the warm-start cache's key-table guard)
            keys_cached, ctx = entry
            if not np.array_equal(keys_cached, model.keys):
                entry = None
        if entry is None:
            from photon_ml_tpu.game.model import key_join

            passive = self.dataset.passive_sample_idx
            shard = self.data.shards[self.dataset.config.feature_shard_id]
            sub = shard.take(passive)
            rows = sub.rows()
            ents = self.data.id_columns[
                self.dataset.config.random_effect_type][passive][rows]
            pos, found = key_join(model.keys, model.dim, ents, sub.cols)
            ctx = (jnp.asarray(sub.vals), jnp.asarray(pos),
                   jnp.asarray(found), jnp.asarray(rows),
                   jnp.asarray(passive), len(passive))
            cache[("passive",)] = (np.array(model.keys, copy=True), ctx)
        vals_d, pos_d, found_d, rows_d, passive_d, n_passive = ctx
        sc = _passive_segment_scores(
            model.coeffs_device, vals_d, pos_d, found_d, rows_d, n_passive)
        return scores.at[passive_d].set(sc)


@partial(jax.jit, static_argnames=("n_passive",))
def _passive_segment_scores(coeffs_device, vals_d, pos_d, found_d, rows_d,
                            n_passive: int):
    coeff = jnp.where(found_d,
                      jnp.take(coeffs_device, pos_d, mode="clip"), 0.0)
    return jax.ops.segment_sum(
        (vals_d * coeff).astype(jnp.float32), rows_d,
        num_segments=n_passive, indices_are_sorted=True)


Coordinate = Union[FixedEffectCoordinate, RandomEffectCoordinate]
