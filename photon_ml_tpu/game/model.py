"""GAME model layer: fixed-effect, random-effect, and composite GAME models.

Re-design of the reference's model layer
(``photon-api/.../model/{GameModel, FixedEffectModel, RandomEffectModel,
DatumScoringModel}.scala``). A ``GameModel`` is an ordered map
coordinateId → per-coordinate model; total score of a sample is the sum of
coordinate scores plus the data offset — the invariant coordinate descent's
residual bookkeeping relies on (SURVEY.md §7 hard-parts #6).

The reference keeps the fixed effect as broadcast coefficients and random
effects as ``RDD[(REId, GLM)]``. Here the fixed effect is a single device
coefficient vector, and a random-effect model is a flat **(entity, feature) →
coefficient** table in host numpy: per-entity coefficient blocks from the
bucketed solves, flattened and key-sorted so scoring any dataset is one
searchsorted join — the vectorized equivalent of the reference's
score-time RDD join (``model/RandomEffectModel.scala``).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Mapping, Optional, Sequence

import numpy as np

from photon_ml_tpu.game.data import FeatureShard, GameData
from photon_ml_tpu.game.projector import RandomProjector
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.types import TaskType
from photon_ml_tpu.util import materialize_thunk

#: guards lazy-thunk materialization (RandomEffectModel coeffs/variances,
#: GameModel.materialize's batched pull) — see util.materialize_thunk.
#: Materialization is rare — one global lock is enough.
_THUNK_LOCK = threading.Lock()


@dataclasses.dataclass(frozen=True)
class FixedEffectModel:
    """Global coefficients for one fixed-effect coordinate
    (reference ``model/FixedEffectModel.scala``)."""

    model: GeneralizedLinearModel
    feature_shard_id: str

    def score(self, data: GameData) -> np.ndarray:
        """Raw margins w·x per sample (no offset; CD owns the accounting)."""
        shard = data.shards[self.feature_shard_id]
        w = np.asarray(self.model.coefficients.means, np.float64)
        out = np.zeros(data.n_samples, np.float64)
        np.add.at(out, shard.rows(),
                  shard.vals.astype(np.float64) * w[shard.cols])
        return out.astype(np.float32)


def sum_coordinate_margins(offsets, margins, xp=np):
    """THE GAME score-summation contract: ``f32(f64(offset) + Σ f64(mᵢ))``
    accumulated in coordinate order.

    Single home of the total-score arithmetic, shared by the batch path
    (:meth:`GameModel.score`, ``GameTransformer``'s per-coordinate
    breakdown total) and the online serving engine
    (:mod:`photon_ml_tpu.serving.engine`) — the online/batch bit-parity
    guarantee rests on both paths running THIS reduction. ``xp`` is numpy
    for the host batch path or ``jax.numpy`` inside the jitted online path
    (where float64 requires ``jax_enable_x64``; without it the engine
    degrades to f32 accumulation and parity is approximate).
    """
    total = xp.asarray(offsets).astype(xp.float64)
    for m in margins:
        total = total + xp.asarray(m).astype(xp.float64)
    return total.astype(xp.float32)


def key_join(keys: np.ndarray, dim: int, entity_ids: np.ndarray,
             feature_ids: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Sorted-table join for (entity, feature) pairs: ``(pos, found)``.

    The single home of the ``entity·dim + feature`` searchsorted-and-verify
    idiom (model lookup, the passive-scoring cache, the device warm-start
    cache). ``found`` is False for negative entity/feature ids and for pairs
    absent from ``keys``; ``pos`` is clipped in-range everywhere so it is
    always safe to gather with.
    """
    valid = (np.asarray(entity_ids) >= 0) & (np.asarray(feature_ids) >= 0)
    q = (np.maximum(entity_ids, 0).astype(np.int64) * np.int64(dim)
         + np.maximum(feature_ids, 0).astype(np.int64))
    pos = np.searchsorted(keys, q)
    pos = np.minimum(pos, max(len(keys) - 1, 0))
    found = (valid & (keys[pos] == q) if len(keys)
             else np.zeros(q.shape, bool))
    return pos, found


@dataclasses.dataclass(frozen=True)
class RandomEffectModel:
    """Per-entity coefficient table for one random-effect coordinate.

    ``keys`` are ``entity_id * dim + feature_id`` (int64, sorted);
    ``coeffs`` the matching coefficient values; entities absent from the
    table score 0 (the reference's behavior for entities dropped by the
    active-data lower bound). ``variances`` is optional, aligned with
    ``coeffs``.

    With a ``projector`` (reference ``projector/RandomProjection.scala``),
    the table lives in the projected space: ``dim`` is the projected dim,
    feature ids index projected coordinates, and scoring projects shard
    features through the shared matrix first. ``to_shard_space`` exports the
    equivalent original-space model (reference behavior: models projected
    back after training).
    """

    random_effect_type: str
    feature_shard_id: str
    task: TaskType
    dim: int  # key modulus: shard vocabulary size, or projected dim
    keys: np.ndarray  # (k,) int64, sorted
    #: (k,) float32 — the solver may install a zero-arg THUNK returning
    #: ``(coeffs, variances)`` instead of the arrays: the device→host pull
    #: of the coefficient table then happens on first ACCESS, not at
    #: construction, so coordinate descent can dispatch the next
    #: coordinate's programs while this one's are still executing (each
    #: eager pull was a full pipeline barrier). ``__getattribute__``
    #: materializes transparently; everything downstream sees ndarrays.
    coeffs: np.ndarray
    variances: Optional[np.ndarray] = None
    projector: Optional["RandomProjector"] = None
    #: same values as ``coeffs`` still resident on device (set by the
    #: solver; None after IO round-trips) — lets coordinate descent's
    #: passive scoring run on-device instead of re-uploading the table
    coeffs_device: Optional[object] = dataclasses.field(
        default=None, compare=False, repr=False)

    def __getattribute__(self, name):
        if name in ("coeffs", "variances"):
            val = object.__getattribute__(self, name)
            if callable(val):
                materialize_thunk(self, ("coeffs", "variances"), _THUNK_LOCK)
                return object.__getattribute__(self, name)
            return val
        return object.__getattribute__(self, name)

    @property
    def n_entities(self) -> int:
        return int(np.unique(self.keys // self.dim).shape[0]) if len(self.keys) else 0

    def lookup(self, entity_ids: np.ndarray, feature_ids: np.ndarray) -> np.ndarray:
        """Coefficient for each (entity, feature) pair; 0 where absent."""
        pos, found = key_join(self.keys, self.dim, entity_ids, feature_ids)
        out = np.zeros(found.shape, np.float32)
        out[found] = self.coeffs[pos[found]]
        return out

    def entity_coefficients(self, entity_id: int) -> dict[int, float]:
        """Sparse coefficient dict of one entity (for inspection/IO)."""
        lo = np.searchsorted(self.keys, entity_id * self.dim)
        hi = np.searchsorted(self.keys, (entity_id + 1) * self.dim)
        return {int(k % self.dim): float(v)
                for k, v in zip(self.keys[lo:hi], self.coeffs[lo:hi])}

    def score(self, data: GameData,
              sample_idx: Optional[np.ndarray] = None) -> np.ndarray:
        """Margins from this coordinate: sum_j x_j * w[entity, j] per sample.

        With ``sample_idx``, scores only those rows (returned in that order)
        — the passive-data scoring path of coordinate descent.
        """
        shard = data.shards[self.feature_shard_id]
        entities = data.id_columns[self.random_effect_type]
        if sample_idx is not None:
            shard = shard.take(sample_idx)
            entities = entities[sample_idx]
        if self.projector is not None:
            return self._score_projected(shard, entities)
        rows = shard.rows()
        ent_per_nnz = entities[rows]
        valid = ent_per_nnz >= 0
        w = np.zeros(shard.nnz, np.float32)
        if valid.any():
            w[valid] = self.lookup(ent_per_nnz[valid], shard.cols[valid])
        out = np.zeros(shard.n_samples, np.float64)
        np.add.at(out, rows, shard.vals.astype(np.float64) * w)
        return out.astype(np.float32)

    def _score_projected(self, shard: FeatureShard,
                         entities: np.ndarray) -> np.ndarray:
        """Margin v·(Px) per sample: project features to the shared space
        (dense MXU-friendly block), then join per-entity coefficients."""
        z = self.projector.project_rows(
            shard.cols, shard.vals, shard.rows(), shard.n_samples)
        valid = np.flatnonzero(entities >= 0)
        out = np.zeros(shard.n_samples, np.float32)
        if len(valid):
            d = self.dim
            # coefficient table per *unique* entity, then gather per sample —
            # O(u·d) lookups instead of O(n·d)
            uniq, inv = np.unique(entities[valid], return_inverse=True)
            ent = np.repeat(uniq, d)
            feat = np.tile(np.arange(d, dtype=np.int64), len(uniq))
            table = self.lookup(ent, feat).reshape(len(uniq), d)
            out[valid] = np.einsum("nd,nd->n", z[valid], table[inv])
        return out

    def merge(self, update: "RandomEffectModel",
              drop_entities: Sequence[int] = ()) -> "RandomEffectModel":
        """Entity-level patch merge: entities present in ``update`` (or
        listed in ``drop_entities``) have their rows REPLACED by (resp.
        dropped in favor of) the update's; every other entity's rows carry
        forward bit-identically. The continuous-training loop's model-side
        counterpart of :meth:`photon_ml_tpu.serving.store.
        EntityCoefficientStore.apply_patch` — both sides must agree on the
        replace-whole-entity semantics or a patched serving table and the
        published merged model would drift.

        Both models must live in the same key space (same ``dim``, same
        dense entity-id universe, no projector). Variances survive only
        when BOTH sides carry them (a mixed merge would leave the variance
        table misaligned with the coefficients).
        """
        if update.random_effect_type != self.random_effect_type:
            raise ValueError(
                f"merge across random-effect types "
                f"{self.random_effect_type!r} != {update.random_effect_type!r}")
        if update.dim != self.dim:
            raise ValueError(f"merge across dims {self.dim} != {update.dim}")
        if self.projector is not None or update.projector is not None:
            raise ValueError("merge expects shard-space models "
                             "(call to_shard_space() first)")
        upd_entities = (np.unique(update.keys // self.dim)
                        if len(update.keys) else np.zeros(0, np.int64))
        drop = np.union1d(np.asarray(list(drop_entities), np.int64),
                          upd_entities)
        keep = (~np.isin(self.keys // self.dim, drop) if len(self.keys)
                else np.zeros(0, bool))
        keys = np.concatenate([self.keys[keep], update.keys])
        coeffs = np.concatenate([
            np.asarray(self.coeffs, np.float32)[keep],
            np.asarray(update.coeffs, np.float32)])
        variances = None
        if self.variances is not None and update.variances is not None:
            variances = np.concatenate([
                np.asarray(self.variances, np.float32)[keep],
                np.asarray(update.variances, np.float32)])
        order = np.argsort(keys, kind="stable")
        return RandomEffectModel(
            random_effect_type=self.random_effect_type,
            feature_shard_id=self.feature_shard_id, task=self.task,
            dim=self.dim, keys=keys[order], coeffs=coeffs[order],
            variances=None if variances is None else variances[order])

    def remap_entities(self, new_of_old: Mapping[int, int]
                       ) -> "RandomEffectModel":
        """The same coefficients under a different dense entity-id
        universe (``old dense id → new dense id``). Dense ids are a
        per-run artifact of vocabulary order; a patch loaded under its own
        vocabulary must be remapped into the serving store's universe
        before :meth:`merge`. Every entity must be mapped — a silent drop
        here would silently lose a patched entity."""
        if not len(self.keys):
            return self
        ent = self.keys // self.dim
        feat = self.keys % self.dim
        lut = np.full(int(ent.max()) + 1, -1, np.int64)
        for old, new in new_of_old.items():
            if 0 <= int(old) < len(lut):
                lut[int(old)] = int(new)
        new_ent = lut[ent]
        if (new_ent < 0).any():
            missing = np.unique(ent[new_ent < 0])[:5]
            raise KeyError(
                f"remap_entities: no mapping for dense entities "
                f"{missing.tolist()}")
        keys = new_ent * np.int64(self.dim) + feat
        order = np.argsort(keys, kind="stable")
        return dataclasses.replace(
            self, keys=keys[order],
            coeffs=np.asarray(self.coeffs, np.float32)[order],
            variances=(None if self.variances is None
                       else np.asarray(self.variances, np.float32)[order]),
            coeffs_device=None)

    def entity_rows(self, dense_ids: Sequence[int]) -> np.ndarray:
        """Dense ``(len(dense_ids), dim)`` coefficient rows for the given
        entities (0 where absent) — the layout a serving table patch
        overwrites rows with."""
        ids = np.asarray(list(dense_ids), np.int64)
        out = np.zeros((len(ids), self.dim), np.float32)
        if not len(self.keys) or not len(ids):
            return out
        ent = self.keys // self.dim
        feat = self.keys % self.dim
        pos_of = {int(e): i for i, e in enumerate(ids)}
        mask = np.isin(ent, ids)
        rows = np.fromiter((pos_of[int(e)] for e in ent[mask]), np.int64,
                           count=int(mask.sum()))
        out[rows, feat[mask]] = np.asarray(self.coeffs, np.float32)[mask]
        return out

    def to_shard_space(self) -> "RandomEffectModel":
        """Back-project a RANDOM-projected model to original feature space
        (``w = Pᵀ v`` — exact for scoring since margins are linear). The
        result is dense per entity; used for Avro export parity."""
        if self.projector is None:
            return self
        p = self.projector
        d, full = p.projected_dim, p.shard_dim
        if not len(self.keys):
            return dataclasses.replace(self, dim=full, projector=None)
        ent = np.unique(self.keys // d)
        v = np.zeros((len(ent), d), np.float32)
        pos = np.searchsorted(ent, self.keys // d)
        v[pos, self.keys % d] = self.coeffs
        w = p.project_back(v)
        keys = (ent[:, None] * np.int64(full)
                + np.arange(full, dtype=np.int64)).ravel()
        variances = None
        if self.variances is not None:
            var_v = np.zeros((len(ent), d), np.float32)
            var_v[pos, self.keys % d] = self.variances
            variances = p.project_back_variances(var_v).ravel()
        return RandomEffectModel(
            random_effect_type=self.random_effect_type,
            feature_shard_id=self.feature_shard_id, task=self.task,
            dim=full, keys=keys, coeffs=w.ravel().astype(np.float32),
            variances=variances, projector=None)


@dataclasses.dataclass(frozen=True)
class GameModel:
    """Ordered coordinateId → model map (reference ``model/GameModel.scala``)."""

    coordinates: Mapping[str, FixedEffectModel | RandomEffectModel]
    task: TaskType

    def device_wait(self) -> None:
        """Block until every pending device program behind this model's
        tables has finished, WITHOUT pulling the tables host-side: one
        1-element transfer from the last coordinate's device payload.  The
        per-coordinate solve programs are chained by data dependencies
        (each consumes the previous sweep's score state), so that single
        pull transitively drains them all.  Gives stage walls the
        reference's synchronous-stage semantics (GameTrainingDriver's
        ``Timed`` blocks): train = compute, save = IO plus one batched
        transfer.  ``jax.block_until_ready`` is not a reliable barrier on
        tunneled PJRT platforms — a device→host pull is (bench.py's timing
        discipline)."""
        import jax

        last = None
        for m in self.coordinates.values():
            if isinstance(m, RandomEffectModel):
                thunk = object.__getattribute__(m, "coeffs")
                dev = getattr(thunk, "device_payload", None) \
                    if callable(thunk) else None
                if dev is not None:
                    last = dev
            elif isinstance(m, FixedEffectModel):
                arr = m.model.coefficients.means
                if isinstance(arr, jax.Array):
                    last = arr
        if last is not None:
            np.asarray(last.reshape(-1)[:1])

    def materialize(self) -> None:
        """Pull every coordinate's device-resident table host-side in ONE
        concatenated transfer (each individual pull pays a full host↔device
        round trip — ~0.1 s apiece through a tunneled device). Random-effect
        models expose their pending sweep payload on the lazy-coeffs thunk;
        fixed-effect coefficients are jax arrays. No-op when everything is
        already host-resident."""
        import jax

        import jax.numpy as jnp

        # same lock as __getattribute__: a thread touching m.coeffs while
        # the driver materializes must not run a thunk twice
        with _THUNK_LOCK:
            self._materialize_locked(jax, jnp)

    def _materialize_locked(self, jax, jnp) -> None:
        jobs = []  # (install_fn, flat_device_array)
        for m in self.coordinates.values():
            if isinstance(m, RandomEffectModel):
                thunk = object.__getattribute__(m, "coeffs")
                dev = getattr(thunk, "device_payload", None) \
                    if callable(thunk) else None
                if dev is None:
                    continue

                def install_re(flat, m=m, thunk=thunk):
                    c, v = thunk(flat)
                    object.__setattr__(m, "coeffs", c)
                    object.__setattr__(m, "variances", v)

                jobs.append((install_re, dev))
            elif isinstance(m, FixedEffectModel):
                coeffs = m.model.coefficients
                for field in ("means", "variances"):
                    arr = getattr(coeffs, field)
                    if isinstance(arr, jax.Array):

                        def install_fe(flat, coeffs=coeffs, field=field,
                                       shape=arr.shape):
                            # copy out of the shared transfer buffer: a
                            # reshape view would let in-place mutation of
                            # one coordinate's array silently alter
                            # another's (RE installs already build fresh
                            # arrays via mask-indexing — no copy needed)
                            object.__setattr__(coeffs, field,
                                               flat.reshape(shape).copy())

                        jobs.append((install_fe, arr.reshape(-1)))
        if not jobs:
            return
        sizes = [int(d.shape[0]) for _, d in jobs]
        flat = np.asarray(
            jnp.concatenate([d.astype(jnp.float32) for _, d in jobs]))
        bounds = np.cumsum([0] + sizes)
        for (install, _), lo, hi in zip(jobs, bounds[:-1], bounds[1:]):
            install(flat[lo:hi])

    def score(self, data: GameData) -> np.ndarray:
        """Total margin per sample: offsets + sum of coordinate scores."""
        return sum_coordinate_margins(
            data.offsets,
            (m.score(data) for m in self.coordinates.values()))

    def score_by_coordinate(self, data: GameData) -> dict[str, np.ndarray]:
        return {cid: m.score(data) for cid, m in self.coordinates.items()}
