"""Factored random-effect coordinate: per-entity latent factors through a
LEARNED shared projection (matrix-factorization flavor).

Re-design of the reference's legacy ``FactoredRandomEffectCoordinate``
(``photon-api/.../algorithm/FactoredRandomEffectCoordinate.scala`` — present
in the 2017-era fork per SURVEY.md §2.4; removed in later upstream): the
coordinate's margin contribution for sample ``i`` of entity ``e`` is

    ``score_i = v_eᵀ (P x_i)``

with a shared projection ``P`` (``latent_dim × shard_dim``) and per-entity
latent coefficients ``v_e``. Training alternates, per factored iteration:

1. **latent solve** — fix ``P``; project features ``z = P x`` and train the
   latent random effect exactly like a RANDOM-projected coordinate (vmapped
   bucketed solves — :mod:`photon_ml_tpu.game.random_effect`);
2. **projection solve** — fix all ``v_e``; ``P`` is a GLM in ``vec(P)``
   because margins are bilinear: ``score_i = Σ_{l,d} P[l,d]·v_{e_i,l}·x_{i,d}``.
   The design "matrix" is the implicit Khatri–Rao product ``v_{e_i} ⊗ x_i``;
   :class:`FactoredDesign` computes its matvec/rmatvec as two dense matmuls
   (MXU path), never materializing the ``n × (L·D)`` features.

The trained model is an ordinary projected :class:`RandomEffectModel` whose
projector wraps the learned ``P`` — scoring, warm starts, back-projection
(``to_shard_space``) and Avro export all reuse the RANDOM-projection paths.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.game.data import (
    GameData,
    RandomEffectDataset,
    RandomEffectDatasetConfig,
)
from photon_ml_tpu.game.model import RandomEffectModel
from photon_ml_tpu.game.projector import ProjectorType, RandomProjector
from photon_ml_tpu.game.random_effect import RandomEffectSolver
from photon_ml_tpu.glm.problem import (
    GLMOptimizationConfiguration,
    OptimizationProblem,
)
from photon_ml_tpu.ops.losses import loss_for_task
from photon_ml_tpu.ops.objective import GLMData, GLMObjective
from photon_ml_tpu.types import TaskType

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class FactoredDesign:
    """Implicit design for the projection solve: row ``i`` is
    ``vec(v_i ⊗ x_i)`` of dim ``L·D``, applied as two matmuls."""

    x: Array  # (n, D) raw features
    v: Array  # (n, L) each sample's entity latent coefficients
    latent_dim: int = dataclasses.field(metadata=dict(static=True))

    @property
    def n_samples(self) -> int:
        return self.x.shape[-2]

    @property
    def dim(self) -> int:
        return self.latent_dim * self.x.shape[-1]

    def matvec(self, w: Array) -> Array:
        p = w.reshape(self.latent_dim, self.x.shape[-1])
        z = jnp.einsum("nd,ld->nl", self.x, p,
                       preferred_element_type=jnp.float32)
        return jnp.sum(z * self.v, axis=-1)

    def rmatvec(self, g: Array) -> Array:
        p = jnp.einsum("nl,nd->ld", self.v * g[:, None], self.x,
                       preferred_element_type=jnp.float32)
        return p.reshape(-1)


@dataclasses.dataclass(frozen=True)
class FactoredRandomEffectCoordinate:
    """Alternating latent/projection training for one factored coordinate.

    Same coordinate-descent contract as the other coordinates:
    ``train(offsets, warm_start) -> (RandomEffectModel, scores)``.
    """

    coordinate_id: str
    data: GameData
    dataset_config: RandomEffectDatasetConfig  # projector_type must be RANDOM
    task: TaskType
    #: latent-space random-effect solve settings
    config: GLMOptimizationConfiguration
    #: projection-matrix solve settings
    projection_config: GLMOptimizationConfiguration = GLMOptimizationConfiguration()
    lam: float = 0.0
    #: L2 on vec(P) during the projection solve
    lam_projection: float = 0.0
    #: alternations per call (reference numberOfFactoredIterations)
    n_factored_iterations: int = 2
    mesh: Optional[object] = None

    def __post_init__(self):
        if self.dataset_config.projector_type is not ProjectorType.RANDOM:
            raise ValueError(
                "factored coordinate requires a RANDOM-type dataset config "
                "(the projection is the trained object)")
        if self.dataset_config.projected_dim is None:
            raise ValueError("dataset_config.projected_dim (the latent dim) "
                             "is required")

    @property
    def latent_dim(self) -> int:
        return int(self.dataset_config.projected_dim)

    @property
    def _ds_config(self) -> RandomEffectDatasetConfig:
        """Per-alternation datasets are single-use — caching their bucket
        device placements would pin ALL buckets in HBM for zero reuse."""
        return dataclasses.replace(self.dataset_config,
                                   cache_device_buckets=False)

    def _latent_table(self, latent: RandomEffectModel,
                      entities: np.ndarray) -> np.ndarray:
        """Per-sample latent coefficients from the entity table (0 for
        entities without a model — their rows contribute nothing)."""
        l = self.latent_dim
        uniq, inv = np.unique(np.maximum(entities, 0), return_inverse=True)
        ent = np.repeat(uniq, l)
        feat = np.tile(np.arange(l, dtype=np.int64), len(uniq))
        table = latent.lookup(ent, feat).reshape(len(uniq), l)
        v = table[inv]
        v[entities < 0] = 0.0
        return v

    def _projection_solve(self, run_jit, x_dev, latent: RandomEffectModel,
                          offsets_dev, p0: np.ndarray) -> np.ndarray:
        """Fix v, solve P over ALL samples with a usable entity model.

        ``run_jit``/``x_dev``/``offsets_dev`` are built ONCE in :meth:`train`
        (one compilation + one densify/transfer per call, reused across the
        alternations — the ``glm/training.py`` single-wrapper pattern)."""
        entities = self.data.id_columns[self.dataset_config.random_effect_type]
        v = self._latent_table(latent, entities)
        design = FactoredDesign(x=x_dev, v=jnp.asarray(v),
                                latent_dim=self.latent_dim)
        glm_data = GLMData(
            design=design, labels=jnp.asarray(self.data.labels),
            offsets=offsets_dev, weights=jnp.asarray(self.data.weights))
        result = run_jit(
            glm_data, jnp.asarray(p0.reshape(-1)),
            jnp.asarray(self.lam_projection, jnp.float32))
        return np.asarray(result.w, np.float32).reshape(
            self.latent_dim, x_dev.shape[1])

    def train(self, offsets,
              warm_start: Optional[RandomEffectModel] = None,
              sweep: int = 0) -> tuple[RandomEffectModel, jax.Array]:
        shard = self.data.shards[self.dataset_config.feature_shard_id]
        if warm_start is not None and warm_start.projector is not None:
            p = warm_start.projector.matrix
        else:
            p = RandomProjector.build(
                shard.dim, self.latent_dim,
                self.dataset_config.seed).matrix

        solver = RandomEffectSolver(
            task=self.task, config=self.config, mesh=self.mesh)
        # one compiled projection solve + one densified design for all
        # alternations of this call
        problem = OptimizationProblem(
            GLMObjective(loss=loss_for_task(self.task)), self.projection_config)
        run_jit = jax.jit(problem.run)
        x_dev = jnp.asarray(shard.to_dense())
        offsets_dev = jnp.asarray(offsets, jnp.float32)
        latent = warm_start
        for _ in range(max(1, self.n_factored_iterations)):
            projector = RandomProjector(matrix=p)
            dataset = RandomEffectDataset.build(
                self.coordinate_id, self.data, self._ds_config,
                projector=projector)
            latent, _scores = solver.train(
                dataset, offsets, self.lam, warm_start=latent)
            p = self._projection_solve(run_jit, x_dev, latent, offsets_dev, p)

        # final latent solve so the returned (v, P) pair is consistent
        projector = RandomProjector(matrix=p)
        dataset = RandomEffectDataset.build(
            self.coordinate_id, self.data, self._ds_config,
            projector=projector)
        latent, _ = solver.train(dataset, offsets, self.lam, warm_start=latent)
        # active+passive scoring via the host model table; scores return to
        # device per the Coordinate contract (CD's accounting is on-device)
        scores = jnp.asarray(latent.score(self.data), jnp.float32)
        return latent, scores
