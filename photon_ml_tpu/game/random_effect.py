"""Random-effect training: vmapped per-entity solves over fixed-shape buckets.

TPU-native replacement for the reference's per-entity training
(``photon-api/.../algorithm/RandomEffectCoordinate.scala`` +
``optimization/game/{RandomEffectOptimizationProblem,
SingleNodeOptimizationProblem}.scala``): where the reference zips an RDD of
per-entity breeze problems with per-entity local datasets and runs millions of
scalar-loop solves inside executors, here every size bucket is ONE
``vmap``-batched compiled solve — entities are lanes of a batched L-BFGS /
OWLQN / TRON ``lax.while_loop`` (convergence is per-lane masked inside the
optimizers; a converged lane simply stops changing). One compilation serves
every bucket of the same (samples, features) shape across all CD sweeps.

Padding correctness: padded sample rows carry weight 0 (contribute nothing);
padded feature columns are all-zero in x, so with zero init their gradient
component is 0 and coefficients stay exactly 0.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.game.data import RandomEffectDataset, REBucket
from photon_ml_tpu.game.model import RandomEffectModel
from photon_ml_tpu.glm.problem import GLMOptimizationConfiguration, OptimizationProblem
from photon_ml_tpu.ops.design import DenseDesign
from photon_ml_tpu.ops.losses import loss_for_task
from photon_ml_tpu.ops.objective import GLMData, GLMObjective
from photon_ml_tpu.types import TaskType, VarianceComputationType


@dataclasses.dataclass(frozen=True)
class RandomEffectSolver:
    """Per-coordinate solver configuration bound to a task type."""

    task: TaskType
    config: GLMOptimizationConfiguration

    def __post_init__(self):
        if self.config.optimizer_config.track_states:
            # traces would be carried per entity lane; force off
            object.__setattr__(self, "config", dataclasses.replace(
                self.config, optimizer_config=dataclasses.replace(
                    self.config.optimizer_config, track_states=False)))

    def _problem(self) -> OptimizationProblem:
        objective = GLMObjective(loss=loss_for_task(self.task))
        return OptimizationProblem(objective, self.config)

    @partial(jax.jit, static_argnames=("self",))
    def _solve_bucket(self, x, labels, offsets, weights, w0, lam):
        """Batched solve: x (E,S,D), labels/offsets/weights (E,S), w0 (E,D)."""
        problem = self._problem()

        def solve_one(xe, ye, oe, we, w0e):
            data = GLMData(design=DenseDesign(x=xe), labels=ye,
                           offsets=oe, weights=we)
            result = problem.run(data, w0e, lam)
            variances = problem.compute_variances(result.w, data, lam)
            if variances is None:
                variances = jnp.zeros((0,), xe.dtype)
            return result.w, variances, result.converged

        return jax.vmap(solve_one)(x, labels, offsets, weights, w0)

    @partial(jax.jit, static_argnames=("self",))
    def _margins_bucket(self, x, w):
        return jnp.einsum("esd,ed->es", x, w,
                          preferred_element_type=jnp.float32)

    def train(
        self,
        dataset: RandomEffectDataset,
        offsets: np.ndarray,
        lam: float,
        warm_start: Optional[RandomEffectModel] = None,
        dim: Optional[int] = None,
    ) -> tuple[RandomEffectModel, np.ndarray]:
        """Train all buckets; returns (model, per-sample active scores).

        ``offsets`` is the global residual-offset vector coordinate descent
        supplies; ``scores`` is this coordinate's margin on every active
        sample (0 elsewhere — passive scoring is the model's job).
        """
        cfg = dataset.config
        if dataset.projector is not None:
            # projected space: keys/coefficients live in projected_dim
            shard_dim = dataset.projector.projected_dim
        else:
            shard_dim = dim if dim is not None else _shard_dim(dataset)
        keys_parts: list[np.ndarray] = []
        coef_parts: list[np.ndarray] = []
        var_parts: list[np.ndarray] = []
        scores = np.zeros(offsets.shape[0], np.float32)
        want_var = self.config.variance_type != VarianceComputationType.NONE

        for bucket in dataset.buckets:
            safe_idx = np.maximum(bucket.sample_idx, 0)
            boff = offsets[safe_idx].astype(np.float32) * (bucket.weights > 0)
            w0 = _gather_warm_start(bucket, warm_start, shard_dim)
            w, variances, _conv = self._solve_bucket(
                jnp.asarray(bucket.x), jnp.asarray(bucket.labels),
                jnp.asarray(boff), jnp.asarray(bucket.weights),
                jnp.asarray(w0), jnp.asarray(lam, jnp.float32))
            w = np.asarray(w)
            margins = np.asarray(self._margins_bucket(
                jnp.asarray(bucket.x), jnp.asarray(w)))

            live = bucket.sample_idx >= 0
            scores[bucket.sample_idx[live]] = margins[live]

            fmask = bucket.feature_index >= 0
            ent = np.broadcast_to(bucket.entity_ids[:, None],
                                  bucket.feature_index.shape)
            keys_parts.append(
                ent[fmask] * np.int64(shard_dim) + bucket.feature_index[fmask])
            coef_parts.append(w[fmask].astype(np.float32))
            if want_var and np.asarray(variances).size:
                var_parts.append(np.asarray(variances)[fmask].astype(np.float32))

        keys = (np.concatenate(keys_parts) if keys_parts
                else np.zeros((0,), np.int64))
        coeffs = (np.concatenate(coef_parts) if coef_parts
                  else np.zeros((0,), np.float32))
        variances = (np.concatenate(var_parts)
                     if want_var and var_parts else None)
        order = np.argsort(keys, kind="stable")
        model = RandomEffectModel(
            random_effect_type=cfg.random_effect_type,
            feature_shard_id=cfg.feature_shard_id,
            task=self.task, dim=shard_dim, keys=keys[order],
            coeffs=coeffs[order],
            variances=None if variances is None else variances[order],
            projector=dataset.projector)
        return model, scores


def _shard_dim(dataset: RandomEffectDataset) -> int:
    top = 0
    for b in dataset.buckets:
        if b.feature_index.size:
            top = max(top, int(b.feature_index.max()) + 1)
    return top


def _gather_warm_start(bucket: REBucket, warm: Optional[RandomEffectModel],
                       shard_dim: int) -> np.ndarray:
    """Previous sweep's coefficients for each (entity, local feature) slot."""
    w0 = np.zeros(bucket.feature_index.shape, np.float32)
    if warm is None or not len(warm.keys):
        return w0
    fmask = bucket.feature_index >= 0
    ent = np.broadcast_to(bucket.entity_ids[:, None],
                          bucket.feature_index.shape)
    w0[fmask] = warm.lookup(ent[fmask], bucket.feature_index[fmask])
    return w0
