"""Random-effect training: vmapped per-entity solves over fixed-shape buckets.

TPU-native replacement for the reference's per-entity training
(``photon-api/.../algorithm/RandomEffectCoordinate.scala`` +
``optimization/game/{RandomEffectOptimizationProblem,
SingleNodeOptimizationProblem}.scala``): where the reference zips an RDD of
per-entity breeze problems with per-entity local datasets and runs millions of
scalar-loop solves inside executors, here every size bucket is ONE
``vmap``-batched compiled solve — entities are lanes of a batched L-BFGS /
OWLQN / TRON ``lax.while_loop`` (convergence is per-lane masked inside the
optimizers; a converged lane simply stops changing). One compilation serves
every bucket of the same (samples, features) shape across all CD sweeps.

Padding correctness: padded sample rows carry weight 0 (contribute nothing);
padded feature columns are all-zero in x, so with zero init their gradient
component is 0 and coefficients stay exactly 0.

Entity parallelism (the reference's ``RandomEffectDatasetPartitioner``
hash-sharding of entities over executors): pass a mesh with an ``"entity"``
axis and the bucket's entity lanes shard over it via ``shard_map`` — every
chip solves its slice of entities with ZERO communication (the solves are
independent by construction), the direct analog of the reference's
executor-local ``mapValues`` solves.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from photon_ml_tpu.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from photon_ml_tpu.game.data import RandomEffectDataset, REBucket
from photon_ml_tpu.game.model import RandomEffectModel
from photon_ml_tpu.glm.problem import GLMOptimizationConfiguration, OptimizationProblem
from photon_ml_tpu.ops.design import DenseDesign
from photon_ml_tpu.ops.losses import loss_for_task
from photon_ml_tpu.ops.objective import GLMData, GLMObjective
from photon_ml_tpu.parallel.mesh import ENTITY_AXIS
from photon_ml_tpu.telemetry import profiling
from photon_ml_tpu.types import TaskType, VarianceComputationType


# Sweep-program signatures this PROCESS has already compiled+executed once.
# _warm_compile's zero-data warm run exists to pay the XLA compile (and its
# jit-dispatch-cache insertion) off the critical path — but a driver called
# twice in one process (bench warm runs, sweeps over configs, notebooks)
# would re-EXECUTE the whole zero sweep on device per call: ~0.9 s of the
# warm e2e wall was train() joining a background thread that was re-running
# an already-compiled program on zeros. Holds HASHES of (solver, sample
# count, bucket shapes, warm-table length) signatures — storing the tuples
# themselves would retain solvers/meshes forever in a long sweep process; a
# hash collision merely skips one warm-up (jit compiles at first real call).
_PRECOMPILED: set[int] = set()


def _bucket_keys(bucket: REBucket, shard_dim: int) -> np.ndarray:
    """Model-table keys for one bucket's kept (entity, feature) slots —
    ``entity_id * shard_dim + shard_feature_id`` over ``feature_index >= 0``,
    in bucket slot order. The single home of the key layout: the host table
    assembly and the dataset-static key cache must agree exactly."""
    fmask = bucket.feature_index >= 0
    ent = np.broadcast_to(bucket.entity_ids[:, None],
                          bucket.feature_index.shape)
    return ent[fmask] * np.int64(shard_dim) + bucket.feature_index[fmask]


@dataclasses.dataclass(frozen=True)
class RandomEffectSolver:
    """Per-coordinate solver configuration bound to a task type.

    ``mesh``/``entity_axis`` opt into entity-parallel solves: bucket entity
    lanes are padded to a multiple of the axis size and sharded over it.
    """

    task: TaskType
    config: GLMOptimizationConfiguration
    mesh: Optional[Mesh] = None
    entity_axis: str = ENTITY_AXIS
    #: "float32" or "bfloat16" — per-entity design dtype on device and on
    #: the wire (labels/weights/coefficients stay f32; margins accumulate
    #: f32 via preferred_element_type)
    design_dtype: str = "float32"
    #: engage the single-pass Pallas entity kernel inside the bucket solves
    #: (ops/pallas_re.py): each L-BFGS evaluation then reads the (E, S, D)
    #: design ONCE instead of XLA's margins-then-gradient double pass.
    #: Inert off-TPU (without ``fused_interpret``) and for projected /
    #: streaming datasets and VMEM-oversized lanes — those keep the XLA
    #: closed form transparently, same gate discipline as the fixed
    #: effect's ``GLMObjective(fused=True)``.
    fused: bool = True
    #: testing only: run the entity kernel through the Pallas interpreter
    #: on non-TPU backends (orders of magnitude slower than XLA)
    fused_interpret: bool = False

    @property
    def _x_dtype(self):
        return jnp.bfloat16 if self.design_dtype == "bfloat16" \
            else jnp.float32

    def __post_init__(self):
        if (self.mesh is not None
                and self.entity_axis not in getattr(self.mesh, "shape", {})):
            # a data-only (or feature-only) mesh has no entity lanes to
            # shard over — solve unsharded rather than KeyError
            object.__setattr__(self, "mesh", None)
        if self.config.optimizer_config.track_states:
            # traces would be carried per entity lane; force off
            object.__setattr__(self, "config", dataclasses.replace(
                self.config, optimizer_config=dataclasses.replace(
                    self.config.optimizer_config, track_states=False)))

    def _problem(self) -> OptimizationProblem:
        objective = GLMObjective(loss=loss_for_task(self.task),
                                 fused_entity=self.fused,
                                 fused_interpret=self.fused_interpret)
        return OptimizationProblem(objective, self.config)

    def _lane_axes(self) -> tuple[str, ...]:
        """Every mesh axis name, entity last — bucket lanes shard over ALL
        of them, for two reasons. Correctness: the lane shard_map runs with
        ``check_vma=False`` (the while_loop carries defeat the checker), so
        an out_spec that left a mesh axis unmentioned would make the
        output's replication over that axis UNVERIFIED — and GSPMD
        consumers then disagree about it (a gather takes one replica, a
        reshape/concatenate sums them: the exact-``data``-width inflation
        the 2D-mesh estimator tests pinned). Mentioning every axis leaves
        nothing unverified. Parallelism: the per-entity solves have no
        cross-lane communication at all, so a 2D ``(data, entity)`` mesh
        solves ``data*entity`` lanes at once instead of idling the data
        groups."""
        names = [a for a in self.mesh.axis_names if a != self.entity_axis]
        return tuple(names) + (self.entity_axis,)

    def _solve_bucket(self, x, labels, offsets, weights, w0, lam):
        """Batched solve: x (E,S,D), labels/offsets/weights (E,S), w0 (E,D).

        Dispatches the module-level profiled jit (compile/execute
        accounting under ``fn="game.re.solve_bucket"``); inside the fused
        sweep trace it inlines instead (tracer passthrough)."""
        return _solve_bucket_jit(self, x, labels, offsets, weights, w0, lam)

    def _put(self, a, pad_value=0):
        """Pad the entity dim to the mesh axis size and shard lanes over it.

        Padded lanes carry all-zero data and weights (``pad_value=0``), so
        their gradient is exactly the L2 term at w=0 (zero) — they converge
        immediately and their coefficients stay 0; :meth:`train` slices them
        off. The compact index arrays pad with ``-1`` instead: their masks
        (row/col >= 0) then treat padded lanes as fully absent.
        """
        a = np.asarray(a)
        if self.mesh is None:
            return jnp.asarray(a)
        # lanes shard over EVERY mesh axis (see _lane_axes): pad to the full
        # device count so each device owns a whole number of lanes
        n_dev = int(np.prod([self.mesh.shape[ax]
                             for ax in self._lane_axes()]))
        e = a.shape[0]
        e_pad = -(-e // n_dev) * n_dev
        if e_pad != e:
            a = np.concatenate(
                [a, np.full((e_pad - e,) + a.shape[1:], pad_value, a.dtype)])
        return jax.device_put(a, NamedSharding(self.mesh,
                                               P(self._lane_axes())))

    def _static_arrays(self, dataset: RandomEffectDataset, i: int,
                       bucket: REBucket, n: int):
        """Device placements of the per-sweep-invariant bucket arrays,
        cached on the dataset so each CD sweep re-uploads only the small
        dynamic inputs (warm starts). Two index arrays ride along: the
        clipped gather index (entity-padded with 0 — harmless, padded rows
        are weight-0) for the residual-offset gather, and the scatter index
        (dead rows → ``n``, dropped by the ``mode="drop"`` scatter;
        deliberately NOT entity-padded, since zero-padding a scatter index
        would alias sample 0). With ``config.cache_device_buckets`` off,
        reverts to upload-and-drop (peak HBM = one bucket instead of all).

        When the dataset carries source data and the shard densifies
        (:meth:`_compact_shared`), the fat tensors are materialized ON
        DEVICE by one gather through the compact index maps instead of
        being filled on host and shipped over the wire — the wire is
        ~35 MB/s here and the padded tensors are 3-4x the compact form.
        The gather runs ONCE per dataset (cached), so repeated sweeps pay
        nothing: leaving the gathers INSIDE the sweep program instead
        measured 3x on the 10M-row RE bench (re-gathering per solve)."""

        def build():
            shared = self._compact_shared(dataset)
            if shared is not None:
                perm_d, counts_d, fi_d = self._compact_arrays(
                    dataset, i, bucket)
                fi = bucket.feature_index
                identity = (fi.shape[1] == shared[0].shape[1]
                            and bool((fi == np.arange(fi.shape[1])).all()))
                return _materialize_fat(
                    *shared, perm_d, counts_d, fi_d, n=n,
                    S=int(bucket.sample_idx.shape[1]),
                    identity_cols=identity)
            return (self._put(bucket.x.astype(self._x_dtype)
                              if self.design_dtype != "float32"
                              else bucket.x),
                    self._put(bucket.labels),
                    self._put(bucket.weights),
                    self._put(np.maximum(bucket.sample_idx, 0)),
                    jnp.asarray(np.where(bucket.sample_idx >= 0,
                                         bucket.sample_idx, n)))

        if not dataset.config.cache_device_buckets:
            return build()
        # n (the dead-row scatter sentinel) is baked into the built index,
        # so it must key the cache: the same dataset reused with a
        # different-length offsets vector gets a fresh sentinel. The design
        # dtype keys it too — the built x tensors land in _x_dtype, and a
        # dataset reused across solvers with different dtypes must not hit
        # the other's cache (device_dense_shard keys by dtype for the same
        # reason).
        key = (i, n, self.mesh, self.entity_axis, self.design_dtype)
        cached = dataset._device_cache.get(key)
        if cached is None:
            cached = build()
            dataset._device_cache[key] = cached
        return cached

    def _compact_shared(self, dataset: RandomEffectDataset):
        """Per-run shared device arrays for the compact-upload sweep:
        ``(dense shard image, labels, weights)`` — or None when the dataset
        carries no source data or the shard is too wide to densify.

        The padded ``(E, S, D)`` bucket tensors are pure gathers of these
        through the bucket's sample/feature index maps, so shipping the
        indices and gathering ON DEVICE replaces ~3-4x-inflated bucket
        uploads with one compact CSR upload shared by every coordinate on
        the same shard — decisive on a ~35 MB/s host↔device link, and
        fewer bytes moved on any hardware."""
        data = dataset.source_data
        if data is None or dataset.projector is not None:
            return None
        if not dataset.config.cache_device_buckets:
            # upload-and-drop mode exists to BOUND peak HBM at ~one bucket;
            # the materialize path would pin the dense shard image (+ index
            # maps) on device for the dataset's lifetime — keep streaming
            # on the host-upload path
            return None
        if self.mesh is not None:
            # entity-mesh runs keep the fat path: its per-bucket tensors
            # shard 1/n_dev per device, whereas the shared dense image would
            # be REPLICATED into every device's HBM by GSPMD — near the
            # densify byte cap that regresses peak memory by n_dev x
            return None
        shard_x = data.device_dense_shard(dataset.config.feature_shard_id,
                                          dtype=self._x_dtype)
        if shard_x is None:
            return None
        return shard_x, data.device_labels(), data.device_weights()

    def _sweep_statics(self, dataset: RandomEffectDataset, n: int):
        """Fat statics for the fused sweep (single home, shared by train()
        and _warm_compile() so they can never pre-compile different
        layouts). :meth:`_static_arrays` materializes them ON DEVICE from
        the compact uploads when the dataset allows — the sweep program
        itself always consumes the fat layout (gathering inside the
        program instead re-paid the gather every solve: 3x on the 10M-row
        RE bench)."""
        return tuple(self._static_arrays(dataset, i, b, n)
                     for i, b in enumerate(dataset.buckets))

    def _compact_arrays(self, dataset: RandomEffectDataset, i: int,
                        bucket: REBucket):
        """Device placements of one bucket's index maps (the ONLY per-bucket
        upload in compact mode), shipped PADDING-FREE: the (E, S) sample_idx
        tensor is ~4–5x its information content (histogram buckets pad S to
        the bucket cap), so it rides as ``perm`` (the active sample rows in
        entity order — the native fill packs each entity's slots at the
        front) plus per-entity ``counts``; :func:`_materialize_fat`
        rebuilds the padded index on device. feature_index (E, D) is small
        and uploads directly. Through the ~35 MB/s wire this cut the
        1M-row driver's index upload from 36 MB to ~10 MB."""
        key = ("compact", i, self.mesh, self.entity_axis)
        cached = dataset._device_cache.get(key)
        if cached is None:
            si = bucket.sample_idx
            mask = si >= 0
            counts = mask.sum(axis=1).astype(np.int32)
            perm = si[mask].astype(np.int32)
            cached = (
                jnp.asarray(perm),
                jnp.asarray(counts),
                self._put(bucket.feature_index.astype(np.int32),
                          pad_value=-1))
            dataset._device_cache[key] = cached
        return cached

    @partial(jax.jit, static_argnames=("self",))
    def _margins_bucket(self, x, w):
        return jnp.einsum("esd,ed->es", x, w,
                          preferred_element_type=jnp.float32)

    def _sweep_fused(self, offsets_dev, lam, statics, warm_ctxs, coeffs_warm,
                     cidxs, e_reals, out_sharding=None):
        """One program for the WHOLE coordinate sweep (dispatched through
        the module-level profiled jit, ``fn="game.re.sweep_fused"`` — the
        per-coordinate compile counter the flat-recompile contract watches):
        per bucket, gather
        residual offsets, gather warm starts from the previous sweep's
        coefficient table, solve, compute margins, scatter into the score
        vector; plus the flat coefficient/variance table for the single
        model D2H and the device coefficient mirror (passive scoring).

        The per-bucket formulation dispatched ~6 programs per bucket per
        sweep; through the axon tunnel each program costs a fixed ~0.1–1 s
        of dispatch+execute overhead, which made an 8-bucket coordinate's
        sweep ~10 s of wall for ~1 s of device work. One fused program pays
        the overhead once (and on any hardware saves launch+sync cost).
        ``coeffs_warm`` is sized to the dataset's full key-table length from
        sweep 0 (zeros — every ``found`` is False), so a single compilation
        serves the cold sweep and every warm sweep.

        Statics are the fat 5-tuple per bucket — ``(x, labels, weights,
        gather_idx, scatter_idx)`` — either uploaded from host fills or
        materialized on device from the compact index maps
        (:func:`_materialize_fat`); the sweep program is identical either
        way, and gathering inside the program instead re-paid the gather
        every solve (measured 3x on the 10M-row RE bench).
        """
        return _sweep_fused_jit(self, offsets_dev, lam, statics, warm_ctxs,
                                coeffs_warm, cidxs, e_reals,
                                out_sharding=out_sharding)

    def _warm_ctx(self, dataset: RandomEffectDataset, i: int,
                  bucket: REBucket, warm: Optional[RandomEffectModel],
                  shard_dim: int):
        """(pos, found) join of bucket slots into the model key table — the
        single home of the warm-join cache (used by the fused sweep's
        in-program gather AND the per-bucket _warm_start_device path).
        With no usable warm model the cached zero-join (found all-False)
        keeps the program signature — and so the compilation — identical to
        warm sweeps."""
        if (warm is not None and len(warm.keys) and warm.dim == shard_dim
                and warm.projector is None):
            key = ("warmidx", i, self.mesh, self.entity_axis)
            ctx = dataset._device_cache.get(key)
            # validate against the cached key TABLE, not just its shape: a
            # warm model keyed differently (trained on another dataset
            # in-process) would otherwise gather wrong coefficients through
            # a stale join. In the production CD chain keys are identical
            # every sweep, so this is one memcmp per bucket per sweep.
            if ctx is not None and not (
                    len(ctx[0]) == len(warm.keys)
                    and np.array_equal(ctx[0], warm.keys)):
                ctx = None
            if ctx is None:
                from photon_ml_tpu.game.model import key_join

                fi = bucket.feature_index  # (E, D_local)
                ent = np.broadcast_to(bucket.entity_ids[:, None], fi.shape)
                pos, found = key_join(warm.keys, shard_dim, ent, fi)
                # _put entity-pads with zeros: found pads False, so padded
                # lanes warm-start at exactly 0
                ctx = (warm.keys, self._put(pos), self._put(found))
                dataset._device_cache[key] = ctx
            return ctx[1], ctx[2]
        key = ("zeroctx", i, self.mesh, self.entity_axis)
        ctx = dataset._device_cache.get(key)
        if ctx is None:
            shape = bucket.feature_index.shape
            ctx = (self._put(np.zeros(shape, np.int64)),
                   self._put(np.zeros(shape, bool)))
            dataset._device_cache[key] = ctx
        return ctx

    def _coef_idx(self, dataset: RandomEffectDataset, i: int,
                  bucket: REBucket):
        ck = ("coeffidx", i)
        cidx = dataset._device_cache.get(ck)
        if cidx is None:
            cidx = jnp.asarray(
                np.flatnonzero(bucket.feature_index >= 0).astype(np.int32))
            dataset._device_cache[ck] = cidx
        return cidx

    def _key_table_len(self, dataset: RandomEffectDataset) -> int:
        """Length of the model key table this dataset will produce (one key
        per kept (entity, feature) slot) — the warm-coefficient arg size."""
        return sum(int((b.feature_index >= 0).sum()) for b in dataset.buckets)

    def _zero_coeffs(self, dataset: RandomEffectDataset):
        """All-zero warm-coefficient table sized like the real one, so the
        cold sweep shares the warm sweeps' compilation (cached: the fused
        program's cache also keys on argument identity-ish placement)."""
        key = ("zerocoeffs",)
        z = dataset._device_cache.get(key)
        if z is None:
            z = jnp.zeros((max(self._key_table_len(dataset), 1),),
                          jnp.float32)
            dataset._device_cache[key] = z
        return z

    @staticmethod
    def _join_warm(dataset: RandomEffectDataset) -> None:
        """Wait for a background pre-compile started at estimator
        prepare() time (so its cache loads overlap the fixed-effect
        stage)."""
        import threading

        th = getattr(dataset, "_warm_thread", None)
        if th is not None and th is not threading.current_thread():
            th.join()

    def _warm_start_device(self, dataset: RandomEffectDataset, i: int,
                           bucket: REBucket,
                           warm: Optional[RandomEffectModel],
                           shard_dim: int):
        """Warm-start coefficients gathered ON DEVICE from the previous
        sweep's coefficient table, or None for the host fallback.

        Symmetric with the passive-scoring join: the (bucket slot →
        model-table position) map is static across sweeps (both the bucket's
        feature layout and the model's key set are dataset-determined), so
        it's computed once; each sweep is then one device gather — no host
        lookup and no (entities × local-dim) H2D per bucket per sweep."""
        if (warm is None or warm.coeffs_device is None
                or warm.projector is not None or not len(warm.keys)
                or warm.dim != shard_dim):
            return None
        pos_d, found_d = self._warm_ctx(dataset, i, bucket, warm, shard_dim)
        return _warm_gather(warm.coeffs_device, pos_d, found_d)

    def _warm_compile(self, dataset: RandomEffectDataset,
                      n: Optional[int] = None) -> None:
        """Pre-compile the dataset's solver programs.

        With ``n`` (the sample count) and a fused-eligible dataset
        (device-cached buckets, no projector) this compiles THE fused sweep
        program itself on the real static arrays — which also performs the
        bucket uploads and join builds train() will reuse — against an
        all-zero offsets/warm signature that matches every later sweep.
        Otherwise falls back to per-bucket-shape compiles (streaming and
        projected datasets keep the per-bucket dispatch path).

        Each distinct (entities, samples, features) bucket shape is its own
        XLA program; compiling lazily inside the bucket loop serializes the
        compiles because the model-table D2H after each solve blocks until
        that bucket finishes. XLA compilation releases the GIL, so a thread
        pool can overlap the compiles up to the backend compiler's own
        concurrency — sweep-0 on an 8-shape power-law coordinate measured
        81 s → 69 s on the axon remote compiler (which serializes most of
        the work server-side); a host-local libtpu compile parallelizes
        properly. Keyed per dataset; later sweeps hit jit's own cache and
        skip this entirely.
        """
        import threading

        # a background pre-compile started at estimator prepare() time (so
        # cache loads overlap the fixed-effect stage) finishes first; train
        # then finds the flag set and skips
        th = getattr(dataset, "_warm_thread", None)
        if th is not None and th is not threading.current_thread():
            th.join()
        if getattr(dataset, "_warm_compiled", None) == (self.mesh,):
            return
        if (n is not None and dataset.config.cache_device_buckets
                and dataset.projector is None and dataset.buckets):
            buckets = dataset.buckets
            # the uploads/joins below are per-DATASET work train() reuses —
            # always worth doing here (overlapped with the fixed-effect
            # stage); only the zero-data execution is skippable when this
            # process already compiled the program
            statics = self._sweep_statics(dataset, n)
            warm_ctxs = tuple(self._warm_ctx(dataset, i, b, None, 0)
                              for i, b in enumerate(buckets))
            cidxs = tuple(self._coef_idx(dataset, i, b)
                          for i, b in enumerate(buckets))
            sig = hash((self, n,
                        tuple((b.tensor_shape, b.n_entities)
                              for b in buckets),
                        self._key_table_len(dataset)))
            if sig not in _PRECOMPILED:
                out = self._sweep_fused(
                    jnp.zeros((n,), jnp.float32), jnp.zeros((), jnp.float32),
                    statics, warm_ctxs, self._zero_coeffs(dataset), cidxs,
                    tuple(b.n_entities for b in buckets))
                np.asarray(out[1][:1])  # D2H: the only reliable barrier on axon
                _PRECOMPILED.add(sig)
            object.__setattr__(dataset, "_warm_compiled", (self.mesh,))
            return
        shapes = sorted({(bucket.tensor_shape, bucket.tensor_shape[:2])
                         for bucket in dataset.buckets})
        shapes = [s for s in shapes if hash((self, s)) not in _PRECOMPILED]
        if not shapes:
            object.__setattr__(dataset, "_warm_compiled", (self.mesh,))
            return

        def compile_one(shape_pair):
            # the NORMAL call path on all-zero dummies: lower().compile()
            # would build an AOT executable that the jit dispatch cache never
            # sees (it would recompile on first real call). Dummies go
            # through the same _put placement as the real arguments — the
            # jit cache keys on sharding, so a differently-placed dummy
            # would compile a program the real call never uses. Zero data
            # makes the wasted execution converge immediately (gradient =
            # L2 at w=0 = 0 for every lane).
            xs, ls = shape_pair
            f32 = np.float32
            args = (self._put(np.zeros(xs, f32)), self._put(np.zeros(ls, f32)),
                    self._put(np.zeros(ls, f32)), self._put(np.zeros(ls, f32)),
                    self._put(np.zeros((xs[0], xs[2]), f32)),
                    jnp.zeros((), jnp.float32))
            jax.block_until_ready(self._solve_bucket(*args))
            _PRECOMPILED.add(hash((self, shape_pair)))

        import concurrent.futures as cf

        # upload-and-drop mode bounds peak HBM to ~one bucket; concurrent
        # dummy placements would hold one design per worker, so serialize
        workers = (1 if not dataset.config.cache_device_buckets
                   else min(8, len(shapes)))
        with cf.ThreadPoolExecutor(max_workers=workers) as pool:
            list(pool.map(compile_one, shapes))
        object.__setattr__(dataset, "_warm_compiled", (self.mesh,))

    def train(
        self,
        dataset: RandomEffectDataset,
        offsets,
        lam: float,
        warm_start: Optional[RandomEffectModel] = None,
        dim: Optional[int] = None,
    ) -> tuple[RandomEffectModel, jnp.ndarray]:
        """Train all buckets; returns (model, per-sample active scores).

        ``offsets`` is the global residual-offset vector coordinate descent
        supplies — host numpy or a device array; it stays on device either
        way (bucket gathers use device-cached sample indices, so a CD sweep
        moves no O(n_samples) data host→device). ``scores`` is a DEVICE
        vector of this coordinate's margin on every active sample
        (0 elsewhere — passive scoring is the model's job).
        """
        cfg = dataset.config
        if dataset.projector is not None:
            # projected space: keys/coefficients live in projected_dim
            shard_dim = dataset.projector.projected_dim
        else:
            shard_dim = dim if dim is not None else _shard_dim(dataset)
        keys_parts: list[np.ndarray] = []
        coef_parts: list[np.ndarray] = []
        var_parts: list[np.ndarray] = []
        n = offsets.shape[0]
        offsets_dev = jnp.asarray(offsets, jnp.float32)
        scores = jnp.zeros(n, jnp.float32)
        want_var = self.config.variance_type != VarianceComputationType.NONE
        self._join_warm(dataset)
        if not cfg.cache_device_buckets or dataset.projector is not None:
            # per-bucket dispatch path: overlap the per-shape compiles
            # (the fused path is one program — compiling it right before
            # calling it would gain nothing)
            self._warm_compile(dataset)

        # Phase 1 — dispatch every bucket's solve/margins/scatter without a
        # single device sync: jax dispatch is async, so all bucket programs
        # queue back-to-back on the device while the host runs ahead. A D2H
        # inside the loop (the old structure) would block bucket i+1's
        # dispatch on bucket i's completion. EXCEPT in upload-and-drop mode
        # (cache_device_buckets=False): queued programs pin every bucket's
        # design in HBM, which is exactly what that flag bounds — there the
        # loop syncs per bucket so bucket i's x frees before i+1 uploads.
        streaming = not cfg.cache_device_buckets
        lam_dev = jnp.asarray(lam, jnp.float32)
        pending = []
        dev_coeff_parts: list[jnp.ndarray] = []
        fused = (not streaming and dataset.projector is None
                 and len(dataset.buckets) > 0)

        def collect(bucket, e_real, w_dev, variances):
            # one D2H of the (entities, local-dim) coefficients — the model
            # itself — then host table assembly (streaming mode only; the
            # cached-bucket path batches all buckets into a single D2H)
            collect_host(bucket, np.asarray(w_dev)[:e_real],
                         np.asarray(variances)[:e_real])

        def collect_host(bucket, w, variances):
            fmask = bucket.feature_index >= 0
            keys_parts.append(_bucket_keys(bucket, shard_dim))
            coef_parts.append(w[fmask].astype(np.float32))
            if want_var and np.asarray(variances).size:
                var_parts.append(np.asarray(variances)[fmask].astype(np.float32))

        if fused:
            # One program for the whole sweep + one D2H for the model table
            # (see _sweep_fused). The per-bucket path below survives for the
            # streaming (upload-and-drop) and projected modes.
            buckets = dataset.buckets
            statics = self._sweep_statics(dataset, n)
            warm_ctxs = tuple(
                self._warm_ctx(dataset, i, b, warm_start, shard_dim)
                for i, b in enumerate(buckets))
            usable_warm = (warm_start is not None and len(warm_start.keys)
                           and warm_start.dim == shard_dim
                           and warm_start.projector is None)
            if usable_warm:
                coeffs_warm = (warm_start.coeffs_device
                               if warm_start.coeffs_device is not None
                               else jnp.asarray(
                                   np.asarray(warm_start.coeffs, np.float32)))
            else:
                coeffs_warm = self._zero_coeffs(dataset)
            cidxs = tuple(self._coef_idx(dataset, i, b)
                          for i, b in enumerate(buckets))
            e_reals = tuple(b.n_entities for b in buckets)
            # preserve a caller-supplied data sharding on the score vector
            # (sharded-score prototype; None = default single-layout path)
            from jax.sharding import NamedSharding as _NS

            off_sharding = getattr(offsets_dev, "sharding", None)
            out_sharding = (off_sharding if isinstance(off_sharding, _NS)
                            and tuple(off_sharding.spec) else None)
            scores, batched_dev, coeffs_unsorted = self._sweep_fused(
                offsets_dev, lam_dev, statics, warm_ctxs, coeffs_warm,
                cidxs, e_reals, out_sharding=out_sharding)
            d_of = [b.tensor_shape[2] for b in buckets]
            w_sizes = [b.n_entities * d for b, d in zip(buckets, d_of)]
            v_sizes = [b.n_entities * (d if want_var else 0)
                       for b, d in zip(buckets, d_of)]
            bounds = np.cumsum([0] + w_sizes + v_sizes)
            nb = len(buckets)
            # the key table and its sort order are DATASET-static (derived
            # from bucket entity/feature indexes, not coefficients) — cached
            hk_key = ("hostkeys", shard_dim)
            hk = dataset._device_cache.get(hk_key)
            if hk is None:
                kp = [_bucket_keys(b, shard_dim) for b in buckets]
                keys_all = (np.concatenate(kp) if kp
                            else np.zeros((0,), np.int64))
                order0 = np.argsort(keys_all, kind="stable")
                hk = (keys_all[order0], order0)
                dataset._device_cache[hk_key] = hk
            keys_sorted, order = hk

            def host_tables(injected=None, batched_dev=batched_dev,
                            buckets=buckets, bounds=bounds, nb=nb,
                            order=order, want_var=want_var):
                # the sweep's single D2H, deferred to first coeffs access:
                # coordinate descent can dispatch the NEXT coordinate while
                # this one's programs are still executing (the eager pull
                # was a full pipeline barrier per coordinate).
                # ``injected`` lets GameModel.materialize batch this pull
                # with every other coordinate's into one transfer.
                batched = (np.asarray(batched_dev) if injected is None
                           else np.asarray(injected))
                cp, vp = [], []
                for k, bucket in enumerate(buckets):
                    fmask = bucket.feature_index >= 0
                    w_np = batched[bounds[k]:bounds[k + 1]].reshape(
                        bucket.n_entities, -1)
                    cp.append(w_np[fmask].astype(np.float32))
                    if want_var:
                        v_np = batched[bounds[nb + k]:bounds[nb + k + 1]
                                       ].reshape(bucket.n_entities, -1)
                        if v_np.size:
                            vp.append(v_np[fmask].astype(np.float32))
                coeffs = (np.concatenate(cp) if cp
                          else np.zeros((0,), np.float32))
                variances = (np.concatenate(vp)[order]
                             if want_var and vp else None)
                return coeffs[order], variances

            host_tables.device_payload = batched_dev
            ok = ("order",)
            order_dev = dataset._device_cache.get(ok)
            if order_dev is None:
                order_dev = jnp.asarray(np.asarray(order, np.int32))
                dataset._device_cache[ok] = order_dev
            coeffs_device = coeffs_unsorted[order_dev]
            model = RandomEffectModel(
                random_effect_type=cfg.random_effect_type,
                feature_shard_id=cfg.feature_shard_id,
                task=self.task, dim=shard_dim, keys=keys_sorted,
                coeffs=host_tables,
                variances=host_tables if want_var else None,
                projector=dataset.projector,
                coeffs_device=coeffs_device)
            return model, scores

        for i, bucket in enumerate(dataset.buckets):  # non-fused modes only
            e_real = bucket.n_entities
            x_d, lab_d, wt_d, idx_d, store_d = self._static_arrays(
                dataset, i, bucket, n)
            boff = _bucket_offsets(offsets_dev, idx_d, wt_d)
            w0_d = self._warm_start_device(dataset, i, bucket, warm_start,
                                           shard_dim)
            if w0_d is None:
                w0_d = self._put(
                    _gather_warm_start(bucket, warm_start, shard_dim))
            w_dev, variances, _conv = self._solve_bucket(
                x_d, lab_d, boff, wt_d, w0_d, lam_dev)
            # margins from the already-placed design (x is the dominant
            # payload; avoid a second host→device copy of it), scattered
            # into the device score vector — dead rows carry index n, which
            # mode="drop" discards (negative indices would WRAP, not drop)
            margins = self._margins_bucket(x_d, w_dev)[:e_real]
            scores = scores.at[store_d].set(margins, mode="drop")
            # device copy of this bucket's model coefficients, in the same
            # host-table order (the flat kept-feature index is static):
            # feeds the model's coeffs_device for on-device passive scoring.
            # Projected datasets never consume it (their passive scoring
            # projects through the host path) — skip the work.
            if dataset.projector is not None:
                if streaming:
                    jax.block_until_ready(scores)
                    collect(bucket, e_real, w_dev, variances)
                else:
                    pending.append((bucket, e_real, w_dev, variances))
                continue
            dev_coeff_parts.append(
                w_dev[:e_real].reshape(-1)[self._coef_idx(dataset, i, bucket)]
                .astype(jnp.float32))
            if streaming:
                # force completion so this bucket's buffers can be dropped
                jax.block_until_ready(scores)
                collect(bucket, e_real, w_dev, variances)
            else:
                pending.append((bucket, e_real, w_dev, variances))

        # Phase 2 — collect (cached-bucket mode): every pending bucket's
        # coefficient (and variance) table rides ONE concatenated
        # device→host transfer, split on host — per-bucket D2H syncs cost
        # ~100 ms each through a tunneled device and serialized the tail
        # of the sweep
        if pending:
            flat_w = [w_dev[:e_real].reshape(-1)
                      for (_b, e_real, w_dev, _v) in pending]
            flat_v = [jnp.asarray(v)[:e_real].reshape(-1)
                      for (_b, e_real, _w, v) in pending]
            w_sizes = [int(a.shape[0]) for a in flat_w]
            v_sizes = [int(a.shape[0]) for a in flat_v]
            batched = np.asarray(jnp.concatenate(flat_w + flat_v))
            bounds = np.cumsum([0] + w_sizes + v_sizes)
            nb = len(pending)
            for k, (bucket, e_real, _w, _v) in enumerate(pending):
                w_np = batched[bounds[k]:bounds[k + 1]].reshape(e_real, -1)
                v_np = batched[bounds[nb + k]:bounds[nb + k + 1]].reshape(
                    e_real, -1)
                collect_host(bucket, w_np, v_np)

        keys = (np.concatenate(keys_parts) if keys_parts
                else np.zeros((0,), np.int64))
        coeffs = (np.concatenate(coef_parts) if coef_parts
                  else np.zeros((0,), np.float32))
        variances = (np.concatenate(var_parts)
                     if want_var and var_parts else None)
        order = np.argsort(keys, kind="stable")
        # device mirror of the sorted coefficient table (static permutation,
        # cached) — consumed by the coordinate's on-device passive scoring
        coeffs_device = None
        if dev_coeff_parts:
            ok = ("order",)
            order_dev = dataset._device_cache.get(ok)
            if order_dev is None:
                order_dev = jnp.asarray(np.asarray(order, np.int32))
                dataset._device_cache[ok] = order_dev
            coeffs_device = jnp.concatenate(dev_coeff_parts)[order_dev]
        model = RandomEffectModel(
            random_effect_type=cfg.random_effect_type,
            feature_shard_id=cfg.feature_shard_id,
            task=self.task, dim=shard_dim, keys=keys[order],
            coeffs=coeffs[order],
            variances=None if variances is None else variances[order],
            projector=dataset.projector,
            coeffs_device=coeffs_device)
        return model, scores


def _solve_bucket_impl(solver, x, labels, offsets, weights, w0, lam):
    """Batched bucket solve body (the traced program behind
    :meth:`RandomEffectSolver._solve_bucket`)."""
    problem = solver._problem()
    objective = problem.objective

    def solve_one(xe, ye, oe, we, w0e, lam_):
        data = GLMData(design=DenseDesign(x=xe), labels=ye,
                       offsets=oe, weights=we)
        result = problem.run(data, w0e, lam_)
        variances = problem.compute_variances(result.w, data, lam_)
        if variances is None:
            variances = jnp.zeros((0,), xe.dtype)
        return result.w, variances, result.converged

    def batch(x, labels, offsets, weights, w0, lam):
        # Pre-pad the entity batch to the Pallas kernel's block plan with
        # weight-0 lanes (zero data ⇒ gradient = L2 at w0=0 = 0: they
        # converge immediately, exactly like _put's mesh padding). Padding
        # INSIDE the traced objective instead would copy the full
        # (E, S, D) design on every L-BFGS evaluation — the measured
        # regression pallas_glm's auto mode exists to avoid. Zero when the
        # kernel is not engaged (non-TPU, oversized lanes) or the plan
        # already divides; under shard_map this runs per shard, so each
        # device pads its own slice.
        e_real = x.shape[0]
        pad = 0
        if objective.fused_entity and (jax.default_backend() == "tpu"
                                       or objective.fused_interpret):
            from photon_ml_tpu.ops.pallas_re import entity_pad

            pad = entity_pad(e_real, x.shape[1], x.shape[2], x.dtype)
        if pad:
            x = jnp.pad(x, ((0, pad), (0, 0), (0, 0)))
            labels = jnp.pad(labels, ((0, pad), (0, 0)))
            offsets = jnp.pad(offsets, ((0, pad), (0, 0)))
            weights = jnp.pad(weights, ((0, pad), (0, 0)))
            w0 = jnp.pad(w0, ((0, pad), (0, 0)))
        w_out, variances, conv = jax.vmap(
            solve_one, in_axes=(0, 0, 0, 0, 0, None))(
                x, labels, offsets, weights, w0, lam)
        if pad:
            w_out, variances, conv = (w_out[:e_real], variances[:e_real],
                                      conv[:e_real])
        return w_out, variances, conv

    if solver.mesh is None:
        return batch(x, labels, offsets, weights, w0, lam)
    # Entity-parallel: each device solves its contiguous slice of lanes.
    # No collectives in the body — independence is the whole point. The
    # lane specs mention EVERY mesh axis (solver._lane_axes): with
    # check_vma off, an unmentioned axis would leave the outputs'
    # replication unverified and downstream GSPMD consumers disagree on it
    # (gather takes one replica, concatenate sums them).
    s = P(solver._lane_axes())
    # check_vma off: the body is collective-free by construction, and the
    # optimizers' constant-initialized while_loop carries would otherwise
    # trip the varying-axis check against lane-varying outputs.
    return shard_map(
        batch, mesh=solver.mesh,
        in_specs=(s, s, s, s, s, P()),
        out_specs=(s, s, s), check_vma=False,
    )(x, labels, offsets, weights, w0, lam)


def _sweep_fused_impl(solver, offsets_dev, lam, statics, warm_ctxs,
                      coeffs_warm, cidxs, e_reals, out_sharding=None):
    """Fused whole-coordinate sweep body (the traced program behind
    :meth:`RandomEffectSolver._sweep_fused`; semantics documented there)."""
    scores = jnp.zeros_like(offsets_dev)
    flat_w: list[jnp.ndarray] = []
    flat_v: list[jnp.ndarray] = []
    coef_parts: list[jnp.ndarray] = []
    for statics_k, (pos_d, found_d), cidx, \
            e_real in zip(statics, warm_ctxs, cidxs, e_reals):
        x_d, lab_d, wt_d, idx_d, store_d = statics_k
        boff = jnp.take(offsets_dev, idx_d.reshape(-1),
                        mode="clip").reshape(idx_d.shape) * (wt_d > 0)
        w0 = jnp.where(
            found_d,
            jnp.take(coeffs_warm, pos_d.reshape(-1),
                     mode="clip").reshape(pos_d.shape),
            0.0).astype(jnp.float32)
        w_dev, variances, _conv = solver._solve_bucket(
            x_d, lab_d, boff, wt_d, w0, lam)
        margins = solver._margins_bucket(x_d, w_dev)[:e_real]
        scores = scores.at[store_d].set(margins, mode="drop")
        flat_w.append(w_dev[:e_real].reshape(-1))
        flat_v.append(jnp.asarray(variances)[:e_real].reshape(-1))
        coef_parts.append(
            w_dev[:e_real].reshape(-1)[cidx].astype(jnp.float32))
    if out_sharding is not None:
        # keep the score vector in the caller's (e.g. data-axis) layout:
        # without the constraint GSPMD replicates the scatter output,
        # silently un-sharding the CD score decomposition
        # (tests/test_sharded_scores.py — ROADMAP item 5 prototype)
        scores = jax.lax.with_sharding_constraint(scores, out_sharding)
    batched = jnp.concatenate(flat_w + flat_v)
    return scores, batched, jnp.concatenate(coef_parts)


#: the profiled executables behind the solver methods: module-level so the
#: per-signature compiled cache (and its compile accounting) is shared by
#: every solver instance of a process — RandomEffectSolver is a frozen
#: value-equal dataclass, so the ``solver`` static keys by configuration,
#: exactly like the old per-method jit cache
_solve_bucket_jit = profiling.profile_jit(
    _solve_bucket_impl, "game.re.solve_bucket", static_argnames=("solver",))
_sweep_fused_jit = profiling.profile_jit(
    _sweep_fused_impl, "game.re.sweep_fused",
    static_argnames=("solver", "e_reals", "out_sharding"))


@partial(jax.jit, static_argnames=("n", "S", "identity_cols"))
def _materialize_fat(shard_x, labels_g, weights_g, perm_d, counts_d, fi_d,
                     *, n: int, S: int, identity_cols: bool = False):
    """One device-side program turning compact index maps into the fat
    bucket tensors ``(x, labels, weights, gather_idx, scatter_idx)`` — the
    exact 5-tuple the host-fill path uploads, built from the shared dense
    shard image instead of shipped over the wire. Runs once per bucket per
    dataset (the caller caches the result). The (E, S) sample index is
    itself derived on device from the padding-free ``perm``/``counts``
    upload (active rows are front-packed per entity — bucket_pack.cc).
    ``identity_cols`` marks a bucket whose local feature map is exactly
    ``arange(shard_dim)`` for every entity (the common small-dim case:
    every feature observed) — the (E, S, D) element gather then collapses
    to a plain ROW gather, which the TPU executes several times faster."""
    starts = jnp.cumsum(counts_d) - counts_d  # (E,) exclusive prefix
    slot = jnp.arange(S, dtype=jnp.int32)
    valid = slot[None, :] < counts_d[:, None]
    if perm_d.shape[0]:
        pos = starts[:, None] + slot[None, :]
        idx_d = jnp.where(valid, jnp.take(perm_d, pos, mode="clip"), -1)
    else:  # bucket of only zero-row (padding) entities
        idx_d = jnp.full(valid.shape, -1, jnp.int32)
    clip = jnp.maximum(idx_d, 0)
    rmask = idx_d >= 0
    if identity_cols:
        x = shard_x[clip] * rmask[:, :, None]
    else:
        fclip = jnp.maximum(fi_d, 0)
        cmask = fi_d >= 0
        x = (shard_x[clip[:, :, None], fclip[:, None, :]]
             * rmask[:, :, None] * cmask[:, None, :])
    labels = labels_g[clip] * rmask
    weights = weights_g[clip] * rmask
    store = jnp.where(rmask, idx_d, n)
    return x, labels, weights, clip, store


@jax.jit
def _warm_gather(coeffs_device, pos_d, found_d):
    flat = jnp.take(coeffs_device, pos_d.reshape(-1), mode="clip")
    return jnp.where(found_d, flat.reshape(pos_d.shape), 0.0
                     ).astype(jnp.float32)


@jax.jit
def _bucket_offsets(offsets_dev, idx_d, wt_d):
    """Gather each bucket row's residual offset on device (zero for padded
    rows — their weight is 0, and the margin must stay finite)."""
    flat = jnp.take(offsets_dev, idx_d.reshape(-1), mode="clip")
    return flat.reshape(idx_d.shape) * (wt_d > 0)


def _shard_dim(dataset: RandomEffectDataset) -> int:
    top = 0
    for b in dataset.buckets:
        if b.feature_index.size:
            top = max(top, int(b.feature_index.max()) + 1)
    return top


def _gather_warm_start(bucket: REBucket, warm: Optional[RandomEffectModel],
                       shard_dim: int) -> np.ndarray:
    """Previous sweep's coefficients for each (entity, local feature) slot."""
    w0 = np.zeros(bucket.feature_index.shape, np.float32)
    if warm is None or not len(warm.keys):
        return w0
    fmask = bucket.feature_index >= 0
    ent = np.broadcast_to(bucket.entity_ids[:, None],
                          bucket.feature_index.shape)
    w0[fmask] = warm.lookup(ent[fmask], bucket.feature_index[fmask])
    return w0
