"""GameTransformer: the scoring facade over a trained GAME model.

Re-design of ``photon-client``'s scoring pipeline facade
(``photon-api/.../transformers/GameTransformer.scala``): apply a
:class:`~photon_ml_tpu.game.model.GameModel` to a dataset → total scores
(offset + sum of coordinate margins), optional per-coordinate breakdown
(reference's per-coordinate score output in ``GameScoringDriver``), optional
response-scale predictions through the task's inverse link, and optional
evaluation of the scored output (``ModelDataScores`` + evaluator join in the
reference).

Where the reference joins a score RDD against model RDDs per coordinate
(broadcast dot for the fixed effect, shuffle join for random effects —
``scoring/ModelDataScores.scala``), here scoring is the models' vectorized
host-side join; the transformer only owns orchestration and bookkeeping.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np

from photon_ml_tpu.evaluation import EvaluationResults, Evaluator, evaluate_all
from photon_ml_tpu.game.data import GameData
from photon_ml_tpu.game.model import GameModel, sum_coordinate_margins
from photon_ml_tpu.ops.losses import loss_for_task


@dataclasses.dataclass(frozen=True)
class ModelDataScores:
    """Scored dataset (reference ``scoring/ModelDataScores.scala``):
    total margins, optional response-scale predictions, optional
    per-coordinate breakdown, and the evaluation computed on them."""

    scores: np.ndarray  # (n,) float32 total margins incl. offsets
    predictions: Optional[np.ndarray] = None  # inverse-link(scores)
    by_coordinate: Optional[dict[str, np.ndarray]] = None
    evaluation: Optional[EvaluationResults] = None


@dataclasses.dataclass(frozen=True)
class GameTransformer:
    """Applies a GAME model to data (reference ``GameTransformer.scala``).

    Spark-ML-transformer-shaped: configure once (model + what to compute),
    call :meth:`transform` per dataset.
    """

    model: GameModel
    evaluators: Sequence[Evaluator] = ()
    #: also return per-coordinate margins (reference per-coordinate output)
    score_breakdown: bool = False
    #: also return response-scale predictions (probability / rate / value
    #: via the task's inverse link — ``PointwiseLoss.mean``)
    predict_response: bool = False

    def transform(self, data: GameData) -> ModelDataScores:
        by_coordinate = None
        if self.score_breakdown:
            by_coordinate = self.model.score_by_coordinate(data)
            # same reduction as GameModel.score (and the online serving
            # engine): breakdown totals are bit-identical to plain scores
            scores = sum_coordinate_margins(data.offsets,
                                            by_coordinate.values())
        else:
            scores = self.model.score(data)

        predictions = None
        if self.predict_response:
            mean = loss_for_task(self.model.task).mean
            predictions = np.asarray(mean(scores), np.float32)

        evaluation = None
        if self.evaluators:
            evaluation = evaluate_all(
                self.evaluators, scores, data.labels,
                weights=data.weights, id_tags=data.id_columns)
        return ModelDataScores(scores=scores, predictions=predictions,
                               by_coordinate=by_coordinate,
                               evaluation=evaluation)
