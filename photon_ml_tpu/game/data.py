"""GAME data layer: global columnar data, fixed-effect and random-effect datasets.

Re-design of the reference's GAME data layer
(``photon-api/.../data/{GameDatum, FixedEffectDataset, RandomEffectDataset,
LocalDataset, RandomEffectDatasetPartitioner}.scala``).

The reference represents data as ``RDD[(UniqueSampleId, GameDatum)]`` and
builds per-coordinate datasets by Spark shuffles (keyBy entity → frequency-
balanced partitioner → groupByKey → per-entity ``LocalDataset``). Here the
global dataset is host-resident columnar numpy (labels / offsets / weights /
per-shard CSR features / per-entity-type id columns), and the "shuffle" is a
vectorized argsort-by-entity. The random-effect dataset then departs from the
reference entirely — instead of millions of ragged per-entity iterables it
builds **fixed-shape size buckets**: entities are grouped by (padded sample
count, padded per-entity feature count), each bucket a dense
``(entities, samples, features)`` tensor ready for a ``vmap``-batched
on-device solve (SURVEY.md §7 "hard parts" #1/#2). Per-entity feature-space
reduction (the reference's ``projector/IndexMapProjector``) happens here too:
each entity's observed feature ids become a compact local index map, so the
bucket feature dim is the max *observed* dim, not the shard vocabulary dim.

Active/passive split follows the reference: an upper bound subsamples an
entity's training rows (reservoir-style), a lower bound drops entities with
too few rows from training entirely; all rows excluded from training remain
"passive" — scored with the trained entity model during coordinate descent.
"""

from __future__ import annotations

import dataclasses
import threading
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.game.projector import ProjectorType, RandomProjector
from photon_ml_tpu.ops.design import CsrDesign, DenseDesign
from photon_ml_tpu.ops.objective import GLMData
from photon_ml_tpu.util import group_starts as _group_starts
from photon_ml_tpu.util import hash_uniform as _hash_uniform
from photon_ml_tpu.util import materialize_thunk

#: guards lazy-thunk materialization (REBucket deferred native fills) —
#: see util.materialize_thunk. Materialization is rare — one lock is enough.
_THUNK_LOCK = threading.Lock()

#: Fixed-effect designs at or below this width always densify (MXU path)
#: when they fit the byte cap; above it the measured crossover rule decides.
DENSE_DESIGN_MAX_DIM = 4096
#: largest measured dim/(nnz-per-row) ratio at which the dense layout still
#: beat the chunked-sparse one on-chip (tools/layout_crossover.py).
DENSE_CROSSOVER_NNZ_MULT = 512
#: per-device byte cap for a densified design — a wide-but-dense shard must
#: not densify itself into an OOM (v5e HBM is 16 GiB; the solve also holds
#: gradients, scores and, under GAME, the RE buckets).
DENSE_DESIGN_MAX_BYTES = 4 << 30
#: HOST byte cap for the densified design: the build materializes the full
#: (n, d) float32 array in host RAM before any device split, so the
#: per-device cap alone would let an 8-shard build allocate 8x it on host.
DENSE_DESIGN_MAX_HOST_BYTES = 8 << 30
#: cap on a random-effect coordinate's device-RESIDENT fat bucket tensors
#: (f32 estimate: x (E,S,D) + labels/weights/gather/scatter (E,S) each);
#: past it the build degrades to upload-and-drop streaming instead of
#: OOMing. 6 GiB of a v5e's 16 GiB HBM: the sweep also holds the shared
#: dense shard image (≤4 GiB by its own cap), score vectors and solver
#: temporaries. Measured (tools/re_scaling_probe.py, power-law entities,
#: dim 8, 5 histogram buckets): 10M rows ≈ 1.9 GiB fat, 30M rows ≈ 8.3 GiB
#: — so the cap admits ~20M resident rows per chip at dim 8 and trips
#: beyond, where entity sharding (--multihost / --mesh entity=K) is the
#: intended scale-out.
RE_FAT_CACHE_MAX_BYTES = 6 << 30


@dataclasses.dataclass(frozen=True)
class FeatureShard:
    """Host CSR feature block over all samples for one feature shard.

    The reference assembles per-shard ``SparseVector`` columns in
    ``data/avro/AvroDataReader.scala``; this is the columnar equivalent.
    Rows are samples; ``dim`` is the shard vocabulary size (intercept
    included if the shard config adds one).
    """

    indptr: np.ndarray  # (n_samples + 1,) int64
    cols: np.ndarray  # (nnz,) int32
    vals: np.ndarray  # (nnz,) float32
    dim: int

    @property
    def n_samples(self) -> int:
        return self.indptr.shape[0] - 1

    @property
    def nnz(self) -> int:
        return int(self.cols.shape[0])

    def row_counts(self) -> np.ndarray:
        return np.diff(self.indptr)

    def rows(self) -> np.ndarray:
        """Expand indptr to one row id per nnz."""
        return np.repeat(np.arange(self.n_samples, dtype=np.int64),
                         self.row_counts())

    def take(self, sample_idx: np.ndarray) -> "FeatureShard":
        """Row-subset (and reorder) by sample indices (vectorized — this
        runs per CD sweep on the passive-scoring path)."""
        sample_idx = np.asarray(sample_idx, np.int64)
        counts = self.row_counts()[sample_idx]
        new_indptr = np.zeros(len(sample_idx) + 1, np.int64)
        np.cumsum(counts, out=new_indptr[1:])
        total = int(new_indptr[-1])
        # gather[k] = old nnz position: per-row arange built flat
        row_of_nnz = np.repeat(np.arange(len(sample_idx)), counts)
        offset_in_row = np.arange(total) - np.repeat(new_indptr[:-1], counts)
        gather = self.indptr[sample_idx][row_of_nnz] + offset_in_row
        return FeatureShard(indptr=new_indptr, cols=self.cols[gather],
                            vals=self.vals[gather], dim=self.dim)

    @staticmethod
    def from_coo(rows, cols, vals, n_samples: int, dim: int) -> "FeatureShard":
        """OWNERSHIP: when the inputs are already row-sorted AND in the
        target dtypes, the returned shard ALIASES them (the sorted fast
        path deliberately avoids the copy) — and FREEZES the aliased
        ``cols``/``vals`` buffers via ``writeable=False``, so a caller's
        later in-place write raises ``ValueError`` instead of silently
        corrupting the shard (and any device image derived from it).
        Callers that need to keep mutating their arrays must pass a copy.
        Unsorted inputs are copied by the sort and stay writable."""
        rows = np.asarray(rows, np.int64)
        if rows.size and (np.diff(rows) < 0).any():
            order = np.argsort(rows, kind="stable")
            rows = rows[order]
            cols = np.asarray(cols, np.int32)[order]
            vals = np.asarray(vals, np.float32)[order]
        else:
            # already row-grouped (the native decoder emits nnz in record
            # order; masking a shard's columns preserves it) — the O(nnz)
            # monotonicity check is ~10x cheaper than the argsort+gathers
            cols = np.ascontiguousarray(cols, np.int32)
            vals = np.ascontiguousarray(vals, np.float32)
            # freeze the aliased buffers: a caller mutating them later would
            # silently corrupt this frozen shard and any device image derived
            # from it — make the write raise instead
            cols.flags.writeable = False
            vals.flags.writeable = False
        indptr = np.zeros(n_samples + 1, np.int64)
        np.cumsum(np.bincount(rows, minlength=n_samples), out=indptr[1:])
        return FeatureShard(indptr=indptr, cols=cols, vals=vals, dim=dim)

    def to_dense(self) -> np.ndarray:
        x = np.zeros((self.n_samples, self.dim), np.float32)
        np.add.at(x, (self.rows(), self.cols.astype(np.int64)), self.vals)
        return x


@dataclasses.dataclass(frozen=True)
class GameData:
    """The global host-resident dataset: one row per sample.

    Counterpart of the reference's ``RDD[(UniqueSampleId, GameDatum)]``
    (``data/GameDatum.scala`` + ``data/GameConverters.scala``): response,
    additive offset, weight, per-shard feature vectors, and per-entity-type
    integer id columns (entity ids are pre-indexed into ``[0, n_entities)``
    by ingest; ``-1`` marks a missing id).
    """

    labels: np.ndarray  # (n,) float32
    offsets: np.ndarray  # (n,) float32
    weights: np.ndarray  # (n,) float32
    shards: dict[str, FeatureShard]
    id_columns: dict[str, np.ndarray]  # entity-type -> (n,) int64
    #: device placements derived from this data (dense shard images, label/
    #: weight vectors) — shared by every coordinate built over it. The
    #: host→device wire is the measured bottleneck of a driver run (~30-40
    #: MB/s through the axon tunnel), so everything device-side is built
    #: from COMPACT uploads exactly once per dataset. ``init=False``:
    #: ``dataclasses.replace`` must NOT share the cache with the copy — the
    #: copy's fields (shards, labels) may differ and would be served stale
    #: device tensors.
    _device_cache: dict = dataclasses.field(
        default_factory=dict, compare=False, repr=False, init=False)

    def __post_init__(self):
        n = self.labels.shape[0]
        if self.offsets.shape[0] != n or self.weights.shape[0] != n:
            raise ValueError(
                f"offsets/weights length ({self.offsets.shape[0]}/"
                f"{self.weights.shape[0]}) != labels length ({n})")
        for name, shard in self.shards.items():
            if shard.n_samples != n:
                raise ValueError(f"shard {name!r}: {shard.n_samples} rows != {n}")
        for name, ids in self.id_columns.items():
            if ids.shape[0] != n:
                raise ValueError(f"id column {name!r}: {ids.shape[0]} != {n}")

    @property
    def n_samples(self) -> int:
        return int(self.labels.shape[0])

    def device_labels(self):
        out = self._device_cache.get("labels")
        if out is None:
            out = jnp.asarray(self.labels)
            self._device_cache["labels"] = out
        return out

    def device_weights(self):
        out = self._device_cache.get("weights")
        if out is None:
            w = self.weights
            # unweighted data (the common case: weight column absent) needs
            # no 4 B/row transfer — build the ones on device. The host scan
            # is ~0.5 ms/1M rows vs ~0.1 s of wire.
            if w.size and w[0] == 1.0 and np.all(w == 1.0):
                out = jnp.ones(w.shape[0], jnp.float32)
            else:
                out = jnp.asarray(w)
            self._device_cache["weights"] = out
        return out

    def device_dense_shard(self, shard_id: str,
                           max_bytes: Optional[int] = None,
                           dtype=jnp.float32):
        """Dense ``(n, dim)`` device image of a feature shard, materialized
        ON DEVICE from a compact CSR upload (per-row counts + narrow column
        ids + values ≈ nnz*5–9 bytes instead of n*dim*4): through a
        ~35 MB/s host↔device link the dense upload of a 200k×33 design
        costs ~0.7 s where the CSR upload costs ~0.2 s.  With
        ``dtype=bfloat16`` the VALUES ride the wire at 2 bytes too (cast on
        host) — the design-dtype trade end to end, not just in HBM.
        Cached per (shard, dtype); ``None`` when the dense image would
        exceed ``max_bytes`` (default :data:`DENSE_DESIGN_MAX_BYTES`, the
        same cap the fixed-effect layout rule uses) — the budget is applied
        on cache HITS too, so a caller with a tighter budget never receives
        an image a looser caller materialized first."""
        shard = self.shards[shard_id]
        n, d = shard.n_samples, shard.dim
        dtype = jnp.dtype(dtype)
        if max_bytes is None:
            max_bytes = DENSE_DESIGN_MAX_BYTES
        if n * d * dtype.itemsize > max_bytes:
            return None
        key = ("dense_shard", shard_id, dtype.name)
        out = self._device_cache.get(key)
        if out is None:
            counts = shard.row_counts()
            cdt = (np.uint8 if counts.size == 0 or counts.max() < 256
                   else np.int32)
            coldt = (np.uint8 if d <= 256 else
                     np.uint16 if d <= 65536 else np.int32)
            out = _densify_csr(
                jnp.asarray(counts.astype(cdt)),
                jnp.asarray(shard.cols.astype(coldt)),
                jnp.asarray(shard.vals.astype(dtype)), n=n, d=d,
                nnz=shard.nnz)
            self._device_cache[key] = out
        return out

    def clear_device_cache(self) -> None:
        self._device_cache.clear()

    @staticmethod
    def build(labels, shards, offsets=None, weights=None, id_columns=None) -> "GameData":
        labels = np.asarray(labels, np.float32)
        n = labels.shape[0]
        return GameData(
            labels=labels,
            offsets=np.zeros(n, np.float32) if offsets is None
            else np.asarray(offsets, np.float32),
            weights=np.ones(n, np.float32) if weights is None
            else np.asarray(weights, np.float32),
            shards=dict(shards),
            id_columns={k: np.asarray(v, np.int64)
                        for k, v in (id_columns or {}).items()},
        )


@partial(jax.jit, static_argnames=("n", "d", "nnz"))
def _densify_csr(counts, cols, vals, *, n: int, d: int, nnz: int):
    """CSR → dense ``(n, d)`` on device. Duplicate (row, col) entries
    accumulate, matching :meth:`FeatureShard.to_dense`'s ``np.add.at``
    (accumulation always in f32; the image lands in ``vals.dtype``)."""
    rows = jnp.repeat(jnp.arange(n, dtype=jnp.int32),
                      counts.astype(jnp.int32), total_repeat_length=nnz)
    out = jnp.zeros((n, d), jnp.float32).at[
        rows, cols.astype(jnp.int32)].add(vals.astype(jnp.float32))
    return out.astype(vals.dtype)


# ---------------------------------------------------------------------------
# Fixed effect
# ---------------------------------------------------------------------------


def choose_dense_design(shard: FeatureShard, *, n_shards: int = 1,
                        dense_max_dim: Optional[int] = None,
                        itemsize: int = 4) -> bool:
    """Dense vs chunked-sparse layout pick for a fixed-effect design —
    the measured crossover rule (VERDICT r2 item 4, SURVEY.md §7
    hard-part #2). With ``dense_max_dim`` given, the old hard threshold
    applies unchanged (explicit caller override).

    Measured on the axon TPU v5e, 2026-07-31 (`tools/layout_crossover.py`:
    chained jitted ``value_and_grad`` iterations, min-of-2 passes, D2H
    sync; k = nnz/row; n scaled so the dense tensor is ~1 GB):

    ====== ===== ========= ========== ========
    d      k     dense_ms  sparse_ms  winner
    ====== ===== ========= ========== ========
    512    8     15.1      66.0       dense 4.4x
    512    128   13.6      901.0      dense 66x
    2048   8     16.0      23.1       dense 1.4x
    4096   8     15.9      16.9       dense 1.06x
    8192   8     15.9      12.8       sparse 1.2x
    8192   32    11.7      25.2       dense 2.2x
    16384  32    16.1      21.4       dense 1.3x
    16384  128   19.7      56.7       dense 2.9x
    65536  8-128 (bytes)   17-54      sparse
    ====== ===== ========= ========== ========

    Model behind the numbers: the dense iteration streams ``n*d*4`` bytes
    at ~170 GB/s effective (two-pass closed form), while the chunked
    sparse iteration pays ~16-20 ns/nnz (two XLA random-gather passes) —
    so dense wins while ``d ≲ 600*k``. The rule uses 512, the largest
    measured d/k where dense still won, and caps the dense tensor's
    per-device bytes so a billion-row shard can't densify into an OOM.
    """
    return choose_dense_design_stats(shard.n_samples, shard.dim, shard.nnz,
                                     n_shards=n_shards,
                                     dense_max_dim=dense_max_dim,
                                     itemsize=itemsize)


def choose_dense_design_stats(n_samples: int, dim: int, nnz: int, *,
                              n_shards: int = 1,
                              dense_max_dim: Optional[int] = None,
                              n_local_samples: Optional[int] = None,
                              itemsize: int = 4) -> bool:
    """The rule of :func:`choose_dense_design` on explicit statistics —
    multi-process training calls this with GLOBALLY allreduced (n, nnz) so
    every process picks the same layout (an SPMD program must agree).
    ``n_local_samples`` bounds the HOST materialization (the build holds
    the full local (n, d) float32 array before the device split); defaults
    to ``n_samples`` (single-process: local = global). ``itemsize`` is the
    DEVICE storage width (2 under --design-dtype bfloat16, letting designs
    that fit dense only at 2 bytes still take the dense path); the host
    cap stays at 4 bytes — the build materializes f32 before the cast."""
    if dense_max_dim is not None:
        return dim <= dense_max_dim
    n_local = n_samples if n_local_samples is None else n_local_samples
    if n_local * dim * 4 > DENSE_DESIGN_MAX_HOST_BYTES:
        return False
    if n_samples * dim * itemsize // max(n_shards, 1) \
            > DENSE_DESIGN_MAX_BYTES:
        return False
    if dim <= DENSE_DESIGN_MAX_DIM:
        return True
    return dim <= DENSE_CROSSOVER_NNZ_MULT * (nnz / max(n_samples, 1))


def host_design_for_shard(shard: FeatureShard, *,
                          dense_max_dim: Optional[int] = None,
                          n_shards: int = 1,
                          force_dense: Optional[bool] = None,
                          itemsize: int = 4):
    """Host-resident design for a fixed-effect shard, laid out per
    :func:`choose_dense_design`. The single home of the dense/sparse
    cutover — the single- and multi-process feeds must agree
    (``force_dense`` carries a decision already agreed across processes)."""
    dense = (force_dense if force_dense is not None
             else choose_dense_design(shard, n_shards=n_shards,
                                      dense_max_dim=dense_max_dim,
                                      itemsize=itemsize))
    if dense:
        return DenseDesign(x=shard.to_dense())
    return CsrDesign(
        rows=shard.rows().astype(np.int32),
        cols=shard.cols.astype(np.int32),
        values=shard.vals,
        n_rows=shard.n_samples, n_cols=shard.dim)


def design_dtype_of(dtype) -> "jnp.dtype":
    """Normalize a design-dtype spec — the CLI strings ("float32" /
    "bfloat16") or any dtype-like — to a jnp dtype. The single home of
    the string→dtype mapping."""
    if isinstance(dtype, str):
        dtype = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    return jnp.dtype(dtype)


def cast_dense_design(host_design, dtype):
    """Host-side dtype cast of a DENSE host design: the sharded feeds
    (:func:`~photon_ml_tpu.parallel.distributed.shard_glm_data`, the
    multihost global feed) preserve leaf dtypes, so casting here puts the
    design on the wire and in HBM at 2 bytes under bfloat16. Sparse
    layouts keep f32 values — bf16 is the dense-path trade (same policy
    as train_glm's ``_to_glm_data``). ``dtype`` may be the CLI string."""
    dtype = design_dtype_of(dtype)
    if dtype != jnp.float32 and isinstance(host_design, DenseDesign):
        return DenseDesign(x=np.asarray(host_design.x).astype(dtype))
    return host_design


@dataclasses.dataclass(frozen=True)
class FixedEffectDataset:
    """Device-ready data for one fixed-effect coordinate
    (reference ``data/FixedEffectDataset.scala``).

    Holds the device arrays minus offsets — coordinate descent supplies
    fresh residual offsets every sweep via :meth:`glm_data`.

    With a ``mesh`` carrying a ``"data"`` axis, the design/labels/weights
    are built ONCE in the stacked per-device layout of
    :func:`photon_ml_tpu.parallel.distributed.shard_glm_data` (the
    reference's RDD partitioning); only the per-sweep offsets are re-placed.
    """

    coordinate_id: str
    feature_shard_id: str
    design: object  # DenseDesign | ChunkedSparseDesign (device; stacked when sharded)
    labels: jnp.ndarray
    weights: jnp.ndarray
    dim: int
    n_samples: int = 0
    mesh: Optional[object] = None  # jax.sharding.Mesh with a "data" axis
    n_shards: int = 1

    @staticmethod
    def build(coordinate_id: str, data: GameData, feature_shard_id: str,
              *, dense_max_dim: Optional[int] = None,
              dtype=jnp.float32, mesh=None) -> "FixedEffectDataset":
        shard = data.shards[feature_shard_id]
        from photon_ml_tpu.parallel.mesh import DATA_AXIS

        n_shards = 1
        if mesh is not None and DATA_AXIS in getattr(mesh, "shape", {}):
            n_shards = int(mesh.shape[DATA_AXIS])
        itemsize = design_dtype_of(dtype).itemsize
        if (n_shards == 1
                and choose_dense_design(shard, n_shards=1,
                                        dense_max_dim=dense_max_dim,
                                        itemsize=itemsize)):
            # single-chip dense: materialize the design ON DEVICE from the
            # compact CSR upload — skips both the host densify and the
            # (n, d, 4)-byte wire transfer (the wire is ~35 MB/s here);
            # a bfloat16 request ships the values at 2 bytes as well
            x_dev = data.device_dense_shard(
                feature_shard_id, max_bytes=DENSE_DESIGN_MAX_BYTES,
                dtype=dtype)
            if x_dev is not None:
                design = DenseDesign(x=x_dev)
                return FixedEffectDataset(
                    coordinate_id=coordinate_id,
                    feature_shard_id=feature_shard_id,
                    design=design, labels=data.device_labels(),
                    weights=data.device_weights(), dim=shard.dim,
                    n_samples=shard.n_samples)
        # host-resident design first: the sharded branch pads/splits on host
        # and device_puts per-shard blocks directly — never materializing
        # the full design in one device's HBM (the whole point of dp)
        host_design = host_design_for_shard(
            shard, dense_max_dim=dense_max_dim, n_shards=n_shards,
            itemsize=itemsize)
        host_design = cast_dense_design(host_design, dtype)
        if n_shards > 1:
            from photon_ml_tpu.parallel.distributed import shard_glm_data

            sharded = shard_glm_data(
                GLMData(design=host_design, labels=data.labels,
                        offsets=np.zeros(shard.n_samples, np.float32),
                        weights=data.weights),
                n_shards, device_put_mesh=mesh)
            return FixedEffectDataset(
                coordinate_id=coordinate_id,
                feature_shard_id=feature_shard_id,
                design=sharded.design, labels=sharded.labels,
                weights=sharded.weights, dim=shard.dim,
                n_samples=shard.n_samples, mesh=mesh, n_shards=n_shards)
        if isinstance(host_design, DenseDesign):
            design = DenseDesign(x=jnp.asarray(host_design.x, dtype))
        else:
            # single-chip wide-sparse: the chunked dual layout (measured
            # ~20x the CsrDesign segment_sum/scatter path on TPU — see
            # ops/design.py::ChunkedSparseDesign)
            from photon_ml_tpu.ops.design import ChunkedSparseDesign

            design = ChunkedSparseDesign.from_coo(
                host_design.rows, host_design.cols, host_design.values,
                n_rows=host_design.n_rows, n_cols=host_design.n_cols)
        return FixedEffectDataset(
            coordinate_id=coordinate_id, feature_shard_id=feature_shard_id,
            design=design, labels=jnp.asarray(data.labels),
            weights=jnp.asarray(data.weights), dim=shard.dim,
            n_samples=shard.n_samples)

    def glm_data(self, offsets) -> GLMData:
        """Bind per-sweep residual offsets (host numpy or device array —
        a device residual never round-trips through the host)."""
        if self.n_shards > 1:
            from jax.sharding import NamedSharding, PartitionSpec

            from photon_ml_tpu.parallel.mesh import DATA_AXIS

            import jax

            per = self.labels.shape[1]
            offsets = jnp.asarray(offsets, jnp.float32)
            pad = self.n_shards * per - offsets.shape[0]
            if pad:
                offsets = jnp.concatenate(
                    [offsets, jnp.zeros((pad,), jnp.float32)])
            off = jax.device_put(
                offsets.reshape(self.n_shards, per),
                NamedSharding(self.mesh, PartitionSpec(DATA_AXIS)))
            return GLMData(design=self.design, labels=self.labels,
                           offsets=off, weights=self.weights)
        return GLMData(design=self.design, labels=self.labels,
                       offsets=jnp.asarray(offsets, jnp.float32),
                       weights=self.weights)


# ---------------------------------------------------------------------------
# Random effect
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class RandomEffectDatasetConfig:
    """Bounds and projection settings for one random-effect coordinate
    (reference ``data/RandomEffectDataset.scala`` +
    ``RandomEffectDataConfiguration``)."""

    random_effect_type: str  # id-column name, e.g. "userId"
    feature_shard_id: str
    #: max training rows kept per entity (reservoir subsample beyond this);
    #: None = unlimited (reference activeDataUpperBound).
    active_data_upper_bound: Optional[int] = None
    #: entities with fewer rows than this get no model (rows stay passive).
    active_data_lower_bound: int = 1
    #: cap on per-entity features kept (by within-entity support, ties by id;
    #: reference LocalDataset feature pruning). None = all observed.
    max_active_features: Optional[int] = None
    #: feature-space projector (reference ``projector/ProjectorType.scala``):
    #: INDEX_MAP compacts each entity's observed features (default);
    #: RANDOM projects through a shared Gaussian matrix of width
    #: ``projected_dim`` (reference ``RandomProjection``).
    projector_type: ProjectorType = ProjectorType.INDEX_MAP
    projected_dim: Optional[int] = None
    #: bucket shape granularity: per-entity sample/feature counts are padded
    #: up to powers of these growth factors. Every distinct padded
    #: (samples, features) shape is a separate XLA compilation of the
    #: vmapped solver, so coarser growth = fewer compiles but more padded
    #: compute. 4.0 keeps shape count ~log4(max entity size) ≈ a handful.
    sample_bucket_growth: float = 4.0
    feature_bucket_growth: float = 2.0
    #: "geometric" pads each dim to a growth-factor power (above);
    #: "histogram" chooses ≤max_{sample,feature}_buckets padded sizes from
    #: the actual entity-size distribution by a min-total-padding partition
    #: (ROADMAP bucket autotuning). The DP is per-dimension optimal: total
    #: padded samples (resp. features) is minimal for the given shape
    #: budget — so with a budget ≥ the geometric scheme's shape count it
    #: never pads a dimension more than geometric does. (The E·S·D product
    #: is not jointly optimized; a very tight budget can lose on it.)
    #: Correctness is identical either way — padding is masked.
    bucket_strategy: str = "geometric"
    max_sample_buckets: int = 8
    max_feature_buckets: int = 4
    #: keep the static bucket arrays resident on device across CD sweeps
    #: (one upload total instead of one per sweep). Peak HBM then holds ALL
    #: buckets of the coordinate; turn off for coordinates whose total
    #: bucket payload exceeds device memory (reverts to upload-and-drop
    #: per sweep).
    cache_device_buckets: bool = True
    seed: int = 20260729

    def __post_init__(self):
        if (self.projector_type is ProjectorType.RANDOM
                and self.max_active_features is not None):
            raise ValueError(
                "max_active_features applies to the INDEX_MAP projector's "
                "per-entity feature selection; the RANDOM projector replaces "
                "feature selection with a shared projection (set "
                "projected_dim to control its width instead)")
        if self.bucket_strategy not in ("geometric", "histogram"):
            raise ValueError(
                f"unknown bucket_strategy {self.bucket_strategy!r} "
                "(expected 'geometric' or 'histogram')")
        if self.max_sample_buckets < 1 or self.max_feature_buckets < 1:
            raise ValueError(
                "max_sample_buckets and max_feature_buckets must be ≥ 1 "
                f"(got {self.max_sample_buckets}/{self.max_feature_buckets})")




def _geom_at_least(x: np.ndarray, growth: float, floor: int = 1) -> np.ndarray:
    """Elementwise next integer power of ``growth`` ≥ max(x, floor)."""
    x = np.maximum(np.asarray(x, np.int64), floor)
    exp = np.ceil(np.log(x) / np.log(growth) - 1e-9).astype(np.int64)
    out = np.ceil(np.power(growth, exp)).astype(np.int64)
    return np.maximum(out, x)  # guard against fp rounding down


#: unique-size cap for the histogram DP: above this, sizes are pre-quantized
#: to a 2% geometric grid (keeps the O(K·m²) DP trivial at any entity count)
_HIST_MAX_UNIQUE = 512


def _histogram_pad(x: np.ndarray, max_buckets: int, floor: int = 1) -> np.ndarray:
    """Elementwise padded size via a min-total-padding ≤max_buckets partition.

    Power-law entity sizes (SURVEY.md §3 "straggler entities") make fixed
    geometric growth pad-heavy; this picks the padded sizes FROM the observed
    size distribution. DP over the sorted unique sizes: the cost of one
    bucket covering sizes (v_i..v_j] is v_j · (count in the range) — total
    padded rows, since every member pads to the bucket max. O(K·m²) with
    m ≤ _HIST_MAX_UNIQUE after quantization; exact when m is under the cap.
    """
    x = np.maximum(np.asarray(x, np.int64), floor)
    v, c = np.unique(x, return_counts=True)
    if len(v) > _HIST_MAX_UNIQUE:
        # quantize UP to a geometric grid (padding stays valid) whose growth
        # is derived from the observed range, so the grid point count — and
        # with it the DP's m — is actually bounded by _HIST_MAX_UNIQUE at
        # any size range (a fixed 2% growth is not: 1e9/1 spans ~1000 steps)
        lo = max(floor, int(v[0]))
        growth = max(1.02,
                     (float(v[-1]) / lo) ** (1.0 / (_HIST_MAX_UNIQUE - 1)))
        xq = _geom_at_least(x, growth, floor)
        v, c = np.unique(xq, return_counts=True)
        x = xq
    m = len(v)
    k_max = min(max_buckets, m)
    # W[j] = total count of sizes ≤ v_{j-1} (prefix, 1-indexed)
    w = np.zeros(m + 1, np.int64)
    np.cumsum(c, out=w[1:])
    inf = np.int64(1) << 60
    # dp[k][j] = min Σ padded rows covering the first j unique sizes with
    # exactly k buckets; group (i..j] costs v[j-1] * (W[j] - W[i])
    dp = np.full((k_max + 1, m + 1), inf)
    dp[0, 0] = 0
    parent = np.zeros((k_max + 1, m + 1), np.int64)
    lower = np.arange(m)[:, None] <= np.arange(m)[None, :]  # i ≤ j-1
    for k in range(1, k_max + 1):
        # cand[i, j-1] = dp[k-1][i] + v[j-1] * (W[j] - W[i])
        cand = dp[k - 1, :m, None] + v[None, :] * (w[1:][None, :] - w[:m, None])
        cand = np.where(lower & (dp[k - 1, :m, None] < inf), cand, inf)
        dp[k, 1:] = cand.min(axis=0)
        parent[k, 1:] = cand.argmin(axis=0)
    # more buckets never costs more: take the best k for covering all m
    k_best = int(np.argmin(dp[1:, m])) + 1
    bounds = []
    j = m
    for k in range(k_best, 0, -1):
        bounds.append(int(v[j - 1]))
        j = int(parent[k, j])
    bounds = np.array(sorted(set(bounds)), np.int64)
    # pad each size to its bucket boundary
    pos = np.searchsorted(bounds, x, side="left")
    return bounds[pos]


@dataclasses.dataclass(frozen=True)
class REBucket:
    """One fixed-shape bucket of entities: the unit of vmapped solving.

    ``x`` is dense ``(E, S, D)`` in each entity's **local** feature space;
    ``feature_index`` maps local column j of entity e to the shard-global
    feature id (``-1`` on padding columns, whose x-values are all zero).
    ``weights`` is zero on padded sample rows, which the objective treats as
    exactly absent.
    """

    entity_ids: np.ndarray  # (E,) int64 — global entity index
    #: (E, S, D) float32 — the native build installs a zero-arg THUNK
    #: returning ``(x, labels, weights)`` instead when the solver's compact
    #: device path makes the host fill unnecessary (the fill is the
    #: dominant host cost of a bucket build); ``__getattribute__``
    #: materializes transparently on first access.
    x: np.ndarray
    labels: np.ndarray  # (E, S) float32
    offsets_zero: bool  # offsets supplied per sweep; kept for clarity
    weights: np.ndarray  # (E, S) float32 (0 = padding)
    sample_idx: np.ndarray  # (E, S) int64 global sample row of each slot (-1 pad)
    feature_index: np.ndarray  # (E, D) int64 shard-global feature ids (-1 pad)

    def __getattribute__(self, name):
        if name in ("x", "labels", "weights"):
            val = object.__getattribute__(self, name)
            if callable(val):
                materialize_thunk(self, ("x", "labels", "weights"),
                                  _THUNK_LOCK)
                return object.__getattribute__(self, name)
            return val
        return object.__getattribute__(self, name)

    @property
    def n_entities(self) -> int:
        return int(self.entity_ids.shape[0])

    @property
    def tensor_shape(self) -> tuple[int, int, int]:
        """(E, S, D) without materializing a lazy ``x``."""
        e, s = self.sample_idx.shape
        return (e, s, int(self.feature_index.shape[1]))

    @property
    def shape(self) -> tuple[int, int]:
        return self.tensor_shape[1:]


@dataclasses.dataclass(frozen=True)
class RandomEffectDataset:
    """Active data bucketed for vmapped solves + passive remainder.

    The reference's active data is ``RDD[(REId, LocalDataset)]`` hash-sharded
    by ``RandomEffectDatasetPartitioner``; here the load balancing is done by
    construction — same-shaped entities share a bucket, and buckets shard
    evenly over the ``entity`` mesh axis.
    """

    coordinate_id: str
    config: RandomEffectDatasetConfig
    buckets: list[REBucket]
    #: passive rows, scored-only (reference passiveData): global sample rows
    #: plus their entity ids.
    passive_sample_idx: np.ndarray  # (p,) int64
    passive_entity_ids: np.ndarray  # (p,) int64
    n_entities_total: int
    #: set when config.projector_type is RANDOM; buckets then hold projected
    #: features and models train in the projected space.
    projector: Optional[RandomProjector] = None
    #: the GameData this dataset was bucketed from — lets the solver's
    #: compact-upload path rebuild bucket tensors ON DEVICE (gathers through
    #: the shared dense shard image) instead of shipping the padded
    #: (E, S, D) arrays over the slow host↔device wire.
    source_data: Optional[GameData] = dataclasses.field(
        default=None, compare=False, repr=False)
    #: device placements of the static bucket arrays (x, labels, weights),
    #: keyed by (bucket index, mesh) — filled lazily by the solver so a CD
    #: run uploads each bucket's design ONCE, not once per sweep (the
    #: dominant H2D payload; offsets/warm starts stay per-sweep). NOTE this
    #: pins every bucket in HBM while the dataset lives — intended during a
    #: run (each sweep touches every coordinate) and across a tuning loop's
    #: repeated fits; call :meth:`clear_device_cache` when training is done.
    _device_cache: dict = dataclasses.field(
        default_factory=dict, compare=False, repr=False)

    def clear_device_cache(self) -> None:
        """Release the cached device placements (frees the buckets' HBM)."""
        self._device_cache.clear()

    @property
    def n_active_entities(self) -> int:
        return sum(b.n_entities for b in self.buckets)

    @staticmethod
    def build(coordinate_id: str, data: GameData,
              config: RandomEffectDatasetConfig,
              projector: Optional[RandomProjector] = None,
              use_native: Optional[bool] = None,
              sample_uids: Optional[np.ndarray] = None,
              n_entity_shards: int = 1,
              ) -> "RandomEffectDataset":
        """``projector`` overrides the seeded Gaussian matrix for the RANDOM
        path — the factored coordinate passes its LEARNED projection here
        (reference ``FactoredRandomEffectCoordinate``'s per-iteration
        projection update). ``use_native`` pins the bucket packer
        (``native/bucket_pack.cc`` vs the numpy formulation — identical
        outputs, see tests/test_native.py::TestNativeBucketPackParity);
        None auto-picks native when the library loads. ``sample_uids``
        (default ``arange(n)``) are the stable global ids keying the
        active-bound subsample draw — multi-process training passes each
        row's global id so the kept subset is identical under any row
        partition."""
        shard = data.shards[config.feature_shard_id]
        entities = data.id_columns[config.random_effect_type]
        n = data.n_samples
        if sample_uids is None:
            sample_uids = np.arange(n, dtype=np.int64)

        present = entities >= 0
        order = _stable_group_order(entities[present])
        sample_rows = np.flatnonzero(present)[order]  # samples grouped by entity
        ent_sorted = entities[sample_rows]
        # segment boundaries by linear scan — ent_sorted is already sorted,
        # np.unique would pay a second O(n log n) sort for nothing
        if len(ent_sorted):
            bound = np.empty(len(ent_sorted), bool)
            bound[0] = True
            np.not_equal(ent_sorted[1:], ent_sorted[:-1], out=bound[1:])
            seg_start = np.flatnonzero(bound)
            uniq = ent_sorted[seg_start]
            seg_count = np.diff(np.append(seg_start, len(ent_sorted)))
        else:
            seg_start = np.zeros(0, np.int64)
            uniq = np.zeros(0, np.int64)
            seg_count = np.zeros(0, np.int64)

        # --- active/passive split per entity (fully vectorized: no Python
        # loop over entities — this is the path that must survive the
        # reference's "hundreds of millions of entities" regime) -----------
        lower = config.active_data_lower_bound
        upper = config.active_data_upper_bound
        n_rows = len(sample_rows)
        seg_of_row = np.repeat(np.arange(len(uniq)), seg_count)
        entity_active = seg_count >= lower
        keep = np.ones(n_rows, bool)
        if (upper is not None and seg_count.size
                and int(seg_count.max()) > upper):
            # reservoir-equivalent subsample: random rank within each
            # entity's segment, keep ranks < upper (uniform without
            # replacement, one global vectorized pass). Skipped entirely
            # when no entity exceeds the bound — the common case shouldn't
            # pay the O(n log n) lexsort. The rank key is a counter-based
            # hash of (seed, global sample id) — NOT a sequential rng
            # stream — so the kept subset is a pure per-row function:
            # identical under any row partition (multi-process builds) and
            # stable when other entities' rows come or go.
            keys = _hash_uniform(sample_uids[sample_rows], config.seed)
            order2 = np.lexsort((keys, seg_of_row))
            ranks = np.empty(n_rows, np.int64)
            ranks[order2] = np.arange(n_rows) - np.repeat(seg_start, seg_count)
            keep = ranks < upper
        active_mask = entity_active[seg_of_row] & keep
        passive = sample_rows[~active_mask]
        all_active = sample_rows[active_mask]
        active_seg = np.flatnonzero(entity_active)
        act_entity = uniq[active_seg].astype(np.int64)
        n_active = len(act_entity)
        dense_of_seg = np.full(len(uniq), -1, np.int64)
        dense_of_seg[active_seg] = np.arange(n_active)
        #: dense active-entity index per active row (rows stay grouped by
        #: entity and in original order within an entity)
        ent_of_active = dense_of_seg[seg_of_row[active_mask]]

        n_entities_total = int(entities.max()) + 1 if n and present.any() else 0

        if config.projector_type is ProjectorType.RANDOM:
            if projector is None:
                if config.projected_dim is None:
                    raise ValueError("RANDOM projector requires projected_dim")
                projector = RandomProjector.build(
                    shard.dim, config.projected_dim, config.seed)
            buckets = _random_projection_buckets(
                data, shard, all_active, ent_of_active, act_entity,
                projector, config)
            config = _guard_fat_cache(coordinate_id, config, buckets,
                                      n_entity_shards)
            return RandomEffectDataset(
                coordinate_id=coordinate_id, config=config, buckets=buckets,
                passive_sample_idx=passive,
                passive_entity_ids=entities[passive],
                n_entities_total=n_entities_total, projector=projector)

        # --- bucket pack: native single-pass packer when available --------
        buckets = _index_map_buckets(data, shard, all_active, ent_of_active,
                                     act_entity, config, use_native)
        config = _guard_fat_cache(coordinate_id, config, buckets,
                                  n_entity_shards)
        return RandomEffectDataset(
            coordinate_id=coordinate_id, config=config, buckets=buckets,
            passive_sample_idx=passive,
            passive_entity_ids=entities[passive],
            n_entities_total=n_entities_total, source_data=data)


def resident_fat_bytes(buckets) -> int:
    """f32 HBM estimate of a coordinate's device-RESIDENT bucket tensors —
    the :func:`~photon_ml_tpu.game.random_effect._materialize_fat` product:
    x (E,S,D) + labels/weights/gather-idx/scatter-idx (E,S) each. The
    single home of the formula (build guard, estimator budget, probe)."""
    return sum(
        e * s * d * 4 + 4 * e * s * 4
        for (e, s, d) in (b.tensor_shape for b in buckets))


def _guard_fat_cache(coordinate_id: str, config: "RandomEffectDatasetConfig",
                     buckets, n_entity_shards: int
                     ) -> "RandomEffectDatasetConfig":
    """Memory-cliff guard: device-resident buckets (the fast path) pin
    EVERY bucket's fat tensors in HBM for the dataset's lifetime. Past the
    per-DEVICE cap — total fat divided by the entity-mesh width, since an
    entity axis shards the lanes 1/K per chip — degrade to upload-and-drop
    streaming (peak HBM = one bucket) instead of OOMing. Measured scaling
    table in tools/re_scaling_probe.py justifies the threshold.
    Cross-coordinate accounting lives in GameEstimator.prepare, which sees
    every coordinate."""
    if not config.cache_device_buckets:
        return config
    fat = resident_fat_bytes(buckets) // max(int(n_entity_shards), 1)
    if fat <= RE_FAT_CACHE_MAX_BYTES:
        return config
    import logging

    logging.getLogger(__name__).warning(
        "random-effect coordinate %s: device-resident buckets would hold "
        "%.1f GiB of fat tensors per device (> %.1f GiB cap) — reverting "
        "to upload-and-drop streaming (peak HBM = one bucket; slower "
        "sweeps). Shard entities across more processes (--multihost) or "
        "chips (--mesh entity=K) to regain the resident path.",
        coordinate_id, fat / 2**30, RE_FAT_CACHE_MAX_BYTES / 2**30)
    return dataclasses.replace(config, cache_device_buckets=False)


def _stable_group_order(ids: np.ndarray) -> np.ndarray:
    """Stable argsort of a dense non-negative id column (entity ids are
    pre-indexed into ``[0, n_entities)`` by ingest) — native O(n) counting
    sort when available (the numpy stable argsort was ~0.25 s per
    coordinate build at 1M rows), numpy fallback."""
    from photon_ml_tpu import native

    if native.available():
        out = native.counting_sort(ids)
        if out is not None:
            return out
    return np.argsort(ids, kind="stable")


def _padded_shapes(n_samp_per_entity: np.ndarray, n_feat_per_entity: np.ndarray,
                   config: RandomEffectDatasetConfig
                   ) -> tuple[np.ndarray, np.ndarray]:
    """Per-entity padded (samples, features) per the configured strategy."""
    if config.bucket_strategy == "histogram":
        return (_histogram_pad(n_samp_per_entity, config.max_sample_buckets),
                _histogram_pad(n_feat_per_entity, config.max_feature_buckets))
    return (_geom_at_least(n_samp_per_entity, config.sample_bucket_growth),
            _geom_at_least(n_feat_per_entity, config.feature_bucket_growth))


def _index_map_buckets(data: GameData, shard: FeatureShard,
                       all_active: np.ndarray, ent_of_active: np.ndarray,
                       act_entity: np.ndarray,
                       config: RandomEffectDatasetConfig,
                       use_native: Optional[bool]) -> list[REBucket]:
    """INDEX_MAP bucket construction, native fast path with numpy fallback.

    Both produce identical buckets (same order, same arrays); the native
    packer (``native/bucket_pack.cc``) replaces the numpy path's full sorts
    of the nnz stream with two linear passes — the difference between ~45 s
    and ~2 s at 10^7 rows (VERDICT r2 "host-side GAME wall")."""
    n_active = len(act_entity)
    if not n_active:
        return []
    if use_native is None or use_native:
        from photon_ml_tpu import native

        if native.available():
            bks = _index_map_buckets_native(
                data, shard, all_active, ent_of_active, act_entity, config)
            if bks is not None:
                return bks
        if use_native:
            raise RuntimeError("native bucket packer requested but the "
                               "native library is unavailable")
    return _index_map_buckets_numpy(
        data, shard, all_active, ent_of_active, act_entity, config)


def _index_map_buckets_native(data, shard, all_active, ent_of_active,
                              act_entity, config):
    from photon_ml_tpu import native

    n_active = len(act_entity)
    n_samp_per_entity = np.bincount(ent_of_active, minlength=n_active
                                    ).astype(np.int64)
    ent_starts = np.zeros(n_active + 1, np.int64)
    np.cumsum(n_samp_per_entity, out=ent_starts[1:])
    # dtype/contiguity contract lives in the native wrappers' ndpointer
    # argtypes; FeatureShard/GameData already store these exact dtypes
    indptr, cols, vals = shard.indptr, shard.cols, shard.vals
    aa = np.ascontiguousarray(all_active, np.int64)
    scratch = native.BucketPackScratch(shard.dim)
    n_feat_per_entity = native.re_feature_counts(
        indptr, cols, aa, ent_starts, shard.dim, config.max_active_features,
        scratch)
    if n_feat_per_entity is None:
        return None
    s_pad, d_pad = _padded_shapes(n_samp_per_entity, n_feat_per_entity, config)
    bucket_key = s_pad * np.int64(1 << 40) + d_pad
    labels32, weights32 = data.labels, data.weights
    # indices-only build when the solver's compact device path will
    # reconstruct the fat tensors on device: the (E, S, D) host fill (a
    # ~3-4x-padded memset+scatter) is deferred to a lazy thunk that almost
    # nothing ever calls. Conservative gate — mirrors _compact_shared's
    # densify bound; a config that later needs the fat path just pays the
    # fill at first access.
    # (RANDOM-projected configs never reach this builder, so projector-free
    # is already guaranteed here)
    indices_only = (config.cache_device_buckets
                    and shard.n_samples * shard.dim * 4
                    <= DENSE_DESIGN_MAX_BYTES)
    # one scratch shared by every deferred fill of this build (created on
    # first use): the stamp contract holds — each bucket fills at most once
    # (REBucket caches the materialization) and buckets hold disjoint
    # entities — and per-fill fresh scratch would memset dim-sized arrays
    # per bucket when a fat-path consumer materializes them all
    lazy_scratch: list = []
    buckets: list[REBucket] = []
    for key in np.unique(bucket_key):
        sel = np.flatnonzero(bucket_key == key)
        S, D = int(s_pad[sel[0]]), int(d_pad[sel[0]])
        if indices_only:
            packed = native.re_bucket_indices(
                indptr, cols, aa, ent_starts, sel, S, D,
                config.max_active_features, scratch)
            if packed is None:
                return None
            sample_idx, feature_index = packed

            def fill(sel=sel, S=S, D=D):
                if not lazy_scratch:
                    lazy_scratch.append(native.BucketPackScratch(shard.dim))
                out = native.re_bucket_fill(
                    indptr, cols, vals, aa, ent_starts, labels32, weights32,
                    sel, S, D, shard.dim, config.max_active_features,
                    lazy_scratch[0])
                if out is None:
                    raise RuntimeError(
                        "native library became unavailable for the deferred "
                        "bucket fill")
                return out[0], out[1], out[2]

            buckets.append(REBucket(
                entity_ids=act_entity[sel], x=fill, labels=fill,
                offsets_zero=True, weights=fill, sample_idx=sample_idx,
                feature_index=feature_index))
            continue
        packed = native.re_bucket_fill(
            indptr, cols, vals, aa, ent_starts, labels32, weights32, sel,
            S, D, shard.dim, config.max_active_features, scratch)
        if packed is None:
            return None
        x, labels, weights, sample_idx, feature_index = packed
        buckets.append(REBucket(
            entity_ids=act_entity[sel], x=x, labels=labels,
            offsets_zero=True, weights=weights, sample_idx=sample_idx,
            feature_index=feature_index))
    return buckets


def _index_map_buckets_numpy(data, shard, all_active, ent_of_active,
                             act_entity, config):
    n_active = len(act_entity)
    # --- per-entity local feature maps --------------------------------
    # For each active entity: observed shard features (optionally pruned
    # to the top max_active_features by support), compact-indexed.
    sub = shard.take(all_active)  # CSR over active rows, entity-grouped
    nnz_ent = np.repeat(ent_of_active, sub.row_counts())  # entity per nnz

    # count support per (entity, feature)
    pair_keys = nnz_ent * np.int64(shard.dim) + sub.cols.astype(np.int64)
    uniq_pairs, pair_inv, pair_support = np.unique(
        pair_keys, return_inverse=True, return_counts=True)
    pair_ent = uniq_pairs // shard.dim
    pair_feat = uniq_pairs % shard.dim

    # prune: rank features within entity by (-support, feature id)
    if config.max_active_features is not None:
        rank_order = np.lexsort((pair_feat, -pair_support, pair_ent))
        ranked_ent = pair_ent[rank_order]
        starts = _group_starts(ranked_ent)
        rank_within = np.arange(len(ranked_ent)) - np.repeat(
            starts, np.diff(np.append(starts, len(ranked_ent))))
        kept_sorted = rank_within < config.max_active_features
        kept = np.zeros(len(uniq_pairs), bool)
        kept[rank_order] = kept_sorted
    else:
        kept = np.ones(len(uniq_pairs), bool)

    # local index of each kept pair within its entity (order: feature id)
    local_idx = np.full(len(uniq_pairs), -1, np.int64)
    kept_ent = pair_ent[kept]
    starts_k = _group_starts(kept_ent)
    counts_k = np.diff(np.append(starts_k, len(kept_ent)))
    local_idx[kept] = np.arange(len(kept_ent)) - np.repeat(starts_k, counts_k)
    n_feat_per_entity = np.zeros(n_active, np.int64)
    if len(kept_ent):
        ent_u, ent_c = np.unique(kept_ent, return_counts=True)
        n_feat_per_entity[ent_u] = ent_c

    n_samp_per_entity = np.bincount(ent_of_active, minlength=n_active
                                    ).astype(np.int64)
    # one active-row index per nnz (loop-invariant over buckets)
    nnz_rows_local = np.repeat(
        np.arange(len(all_active)), sub.row_counts())

    # --- bucketing by (padded samples, padded features) ----------------
    buckets: list[REBucket] = []
    s_pad, d_pad = _padded_shapes(n_samp_per_entity, n_feat_per_entity, config)
    bucket_key = s_pad * np.int64(1 << 40) + d_pad
    # bucket id per entity, gathered ONCE onto pairs/nnz/rows: the
    # per-bucket membership tests below are then O(len) compares
    # instead of np.isin's sort-based lookups over the full nnz
    # array per bucket (measured: the dominant build cost at 10^7
    # rows — O(buckets × nnz) turned into O(nnz))
    uniq_keys, bucket_of_entity = np.unique(bucket_key,
                                            return_inverse=True)
    pair_bucket = bucket_of_entity[pair_ent]
    nnz_bucket = bucket_of_entity[nnz_ent]
    row_bucket = bucket_of_entity[ent_of_active]
    nnz_kept = local_idx[pair_inv] >= 0
    for bi, key in enumerate(uniq_keys):
        sel = np.flatnonzero(bucket_key == key)
        S = int(s_pad[sel[0]])
        D = int(d_pad[sel[0]])
        E = len(sel)
        x = np.zeros((E, S, D), np.float32)
        feature_index = np.full((E, D), -1, np.int64)

        slot_of_entity = np.full(n_active, -1, np.int64)
        slot_of_entity[sel] = np.arange(E)

        # features
        sel_pairs = kept & (pair_bucket == bi)
        pe = slot_of_entity[pair_ent[sel_pairs]]
        feature_index[pe, local_idx[sel_pairs]] = pair_feat[sel_pairs]

        # samples: rows of these entities, slot position within entity
        labels, weights, sample_idx, rows_sel, pos, es = \
            _bucket_sample_fill(data, all_active, ent_of_active,
                                slot_of_entity, sel, S,
                                rows_sel=np.flatnonzero(
                                    row_bucket == bi))

        # nnz values into local dense tensor
        nnz_sel = (nnz_bucket == bi) & nnz_kept
        # local sample position for each nnz: position of its active row
        pos_of_active_row = np.full(len(all_active), -1, np.int64)
        pos_of_active_row[rows_sel] = pos
        take = nnz_sel
        e_nnz = slot_of_entity[nnz_ent[take]]
        s_nnz = pos_of_active_row[nnz_rows_local[take]]
        d_nnz = local_idx[pair_inv[take]]
        np.add.at(x, (e_nnz, s_nnz, d_nnz), sub.vals[take])

        buckets.append(REBucket(
            entity_ids=act_entity[sel],
            x=x, labels=labels, offsets_zero=True, weights=weights,
            sample_idx=sample_idx, feature_index=feature_index))

    return buckets


def _bucket_sample_fill(
    data: GameData,
    all_active: np.ndarray,
    ent_of_active: np.ndarray,
    slot_of_entity: np.ndarray,
    sel: np.ndarray,
    n_slots: int,
    rows_sel: np.ndarray | None = None,
):
    """Scatter the selected entities' rows into bucket sample slots.

    Shared by the INDEX_MAP and RANDOM bucket builders. Returns
    ``(labels, weights, sample_idx, rows_sel, pos, es)`` where ``rows_sel``
    indexes ``all_active``, ``pos`` is each row's slot within its entity and
    ``es`` its entity's bucket lane. Callers that already know the selected
    rows (the INDEX_MAP path's precomputed bucket map) pass ``rows_sel``;
    otherwise it is derived here.
    """
    e = len(sel)
    labels = np.zeros((e, n_slots), np.float32)
    weights = np.zeros((e, n_slots), np.float32)
    sample_idx = np.full((e, n_slots), -1, np.int64)
    if rows_sel is None:
        rows_sel = np.flatnonzero(np.isin(ent_of_active, sel))
    ent_rows = ent_of_active[rows_sel]
    row_starts = _group_starts(ent_rows)
    row_counts = np.diff(np.append(row_starts, len(ent_rows)))
    pos = np.arange(len(ent_rows)) - np.repeat(row_starts, row_counts)
    es = slot_of_entity[ent_rows]
    g = all_active[rows_sel]
    labels[es, pos] = data.labels[g]
    weights[es, pos] = data.weights[g]
    sample_idx[es, pos] = g
    return labels, weights, sample_idx, rows_sel, pos, es


def _random_projection_buckets(
    data: GameData,
    shard: FeatureShard,
    all_active: np.ndarray,
    ent_of_active: np.ndarray,
    act_entity: np.ndarray,
    projector: RandomProjector,
    config: RandomEffectDatasetConfig,
) -> list[REBucket]:
    """Fixed-shape buckets in the shared projected space.

    Every entity shares the feature dim (``projected_dim``), so entities
    bucket by padded sample count only; ``feature_index`` is the identity
    into the projected space — model keys live there until
    ``RandomEffectModel.to_shard_space`` back-projects for export.
    """
    buckets: list[REBucket] = []
    n_active = len(act_entity)
    if not n_active:
        return buckets
    sub = shard.take(all_active)
    z = projector.project_rows(sub.cols, sub.vals, sub.rows(), len(all_active))
    d = projector.projected_dim
    n_samp = np.bincount(ent_of_active, minlength=n_active).astype(np.int64)
    if config.bucket_strategy == "histogram":
        s_pad = _histogram_pad(n_samp, config.max_sample_buckets)
    else:
        s_pad = _geom_at_least(n_samp, config.sample_bucket_growth)
    for s_key in np.unique(s_pad):
        sel = np.flatnonzero(s_pad == s_key)
        S, E = int(s_key), len(sel)
        x = np.zeros((E, S, d), np.float32)
        feature_index = np.tile(np.arange(d, dtype=np.int64), (E, 1))

        slot_of_entity = np.full(n_active, -1, np.int64)
        slot_of_entity[sel] = np.arange(E)
        labels, weights, sample_idx, rows_sel, pos, es = _bucket_sample_fill(
            data, all_active, ent_of_active, slot_of_entity, sel, S)
        x[es, pos, :] = z[rows_sel]

        buckets.append(REBucket(
            entity_ids=act_entity[sel],
            x=x, labels=labels, offsets_zero=True, weights=weights,
            sample_idx=sample_idx, feature_index=feature_index))
    return buckets


