"""GameEstimator: build datasets once, fit many configurations, pick the best.

Re-design of ``photon-api/.../estimators/GameEstimator.scala``: the estimator
owns the (expensive) dataset construction — fixed-effect device arrays and
random-effect bucketing happen once — then loops over hyperparameter
configurations (a grid of per-coordinate regularization weights, or points
suggested by the Bayesian search), running coordinate descent per
configuration and evaluating validation data. Returns one
:class:`GameResult` per configuration; the first validation evaluator is the
model-selection criterion (reference ``ModelSelection``).
"""

from __future__ import annotations

import dataclasses
import json
import logging
from typing import Mapping, Optional, Sequence

import jax.numpy as jnp

from photon_ml_tpu.evaluation import EvaluationResults, Evaluator
from photon_ml_tpu.game.coordinate import (
    FixedEffectCoordinate,
    RandomEffectCoordinate,
)
from photon_ml_tpu.game.coordinate_descent import CoordinateDescent
from photon_ml_tpu.game.data import (
    FixedEffectDataset,
    GameData,
    RandomEffectDataset,
    RandomEffectDatasetConfig,
)
from photon_ml_tpu.game.model import GameModel
from photon_ml_tpu.glm.problem import GLMOptimizationConfiguration
from photon_ml_tpu.sampling import DownSampler
from photon_ml_tpu.types import TaskType

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class FixedEffectCoordinateConfig:
    """Static definition of a fixed-effect coordinate
    (reference ``FixedEffectDataConfiguration`` +
    ``FixedEffectOptimizationConfiguration``)."""

    feature_shard_id: str
    optimization: GLMOptimizationConfiguration = GLMOptimizationConfiguration()
    downsampler: Optional[DownSampler] = None
    #: "float32" (default) or "bfloat16" — the dtype the dense design is
    #: stored in on device. bfloat16 halves the HBM traffic of the
    #: dominant payload (the same trade the GLM driver's --design-dtype
    #: offers: ~1.4-1.5x solve speed for ~1e-3-digit design rounding).
    design_dtype: str = "float32"


@dataclasses.dataclass(frozen=True)
class RandomEffectCoordinateConfig:
    """Static definition of a random-effect coordinate
    (reference ``RandomEffectDataConfiguration`` +
    ``RandomEffectOptimizationConfiguration``)."""

    dataset: RandomEffectDatasetConfig
    optimization: GLMOptimizationConfiguration = GLMOptimizationConfiguration()
    #: "float32" (default) or "bfloat16" — dtype of the per-entity designs
    #: on device AND on the host↔device wire (the shared dense shard image
    #: ships its values at 2 bytes under bfloat16); labels/weights/
    #: coefficients stay float32, margins accumulate in float32.
    design_dtype: str = "float32"


@dataclasses.dataclass(frozen=True)
class FactoredRandomEffectCoordinateConfig:
    """Static definition of a factored random-effect coordinate (legacy
    reference ``FactoredRandomEffectCoordinate`` — SURVEY.md §2.4).
    ``dataset.projector_type`` must be RANDOM; ``projected_dim`` is the
    latent dim."""

    dataset: RandomEffectDatasetConfig
    optimization: GLMOptimizationConfiguration = GLMOptimizationConfiguration()
    projection_optimization: GLMOptimizationConfiguration = (
        GLMOptimizationConfiguration())
    lam_projection: float = 0.0
    n_factored_iterations: int = 2


CoordinateConfig = (FixedEffectCoordinateConfig | RandomEffectCoordinateConfig
                    | FactoredRandomEffectCoordinateConfig)


@dataclasses.dataclass(frozen=True)
class GameOptimizationConfiguration:
    """One hyperparameter point: per-coordinate regularization weights
    (reference ``GameEstimator.GameOptimizationConfiguration``)."""

    regularization_weights: Mapping[str, float]

    def lam(self, coordinate_id: str) -> float:
        return float(self.regularization_weights.get(coordinate_id, 0.0))


@dataclasses.dataclass
class GameResult:
    """(model, validation evaluation, configuration) triple."""

    model: GameModel
    configuration: GameOptimizationConfiguration
    evaluation: Optional[EvaluationResults]
    validation_history: list[dict[str, float]]


@dataclasses.dataclass
class GameEstimator:
    """Fits GAME models over a training set for many configurations.

    ``mesh`` turns on multi-chip training: a ``"data"`` axis shards every
    fixed-effect solve (psum gradients inside the compiled optimizer), an
    ``"entity"`` axis shards every random-effect coordinate's bucket lanes.
    A 2D ``{"data": a, "entity": b}`` mesh does both — the layout
    ``dryrun_multichip`` validates.
    """

    task: TaskType
    coordinate_configs: Mapping[str, CoordinateConfig]
    update_sequence: Sequence[str]
    n_cd_iterations: int = 1
    mesh: Optional[object] = None
    #: plumbed to CoordinateDescent's score-memory guard (None = half the
    #: device's memory; the guard's error message names this knob)
    max_score_memory_bytes: Optional[int] = None

    def __post_init__(self):
        # coordinates may be absent from configs only if locked at fit time
        # (partial retrain); prepare()/fit() validate against ``locked``
        pass

    def _check_sequence(self, locked: Sequence[str]) -> None:
        locked = set(locked)
        for cid in self.update_sequence:
            if cid not in self.coordinate_configs and cid not in locked:
                raise KeyError(
                    f"update sequence names unknown coordinate {cid!r} "
                    f"(not configured, not locked)")
        # a locked coordinate outside the update sequence would silently
        # vanish from the model and the residual accounting — reject it
        missing = locked - set(self.update_sequence)
        if missing:
            raise ValueError(
                f"locked coordinates {sorted(missing)} must appear in the "
                f"update sequence to stay part of the model")

    # --- dataset construction (once) --------------------------------------
    def _prefetch_device_feed(self, data: GameData,
                              locked: Sequence[str]) -> None:
        """Dispatch the async host→device uploads the coordinates will need
        BEFORE the host-side bucket builds start: jax transfers are
        asynchronous, so the ~35 MB/s wire streams the dense shard images /
        labels / weights while the host packs buckets. Without this the
        wire only starts when the first solve asks for the image — fully
        serialized after the builds."""
        from photon_ml_tpu.game.data import (
            choose_dense_design,
            design_dtype_of,
        )
        from photon_ml_tpu.game.projector import ProjectorType

        if self.mesh is not None:
            return  # sharded paths build their own per-device feeds
        seen: set = set()
        for cid in self.update_sequence:
            if cid in locked:
                continue
            cfg = self.coordinate_configs.get(cid)
            if isinstance(cfg, FixedEffectCoordinateConfig):
                sid, dt = cfg.feature_shard_id, cfg.design_dtype
            elif isinstance(cfg, RandomEffectCoordinateConfig):
                if (not cfg.dataset.cache_device_buckets
                        or cfg.dataset.projector_type
                        is ProjectorType.RANDOM):
                    continue  # solver won't use the shared image
                sid, dt = cfg.dataset.feature_shard_id, cfg.design_dtype
            else:
                continue
            if (sid, dt) in seen:
                continue
            seen.add((sid, dt))
            dtype = design_dtype_of(dt)
            # same itemsize-aware rule as FixedEffectDataset.build — a
            # mismatch would skip the prefetch exactly when it matters
            if choose_dense_design(data.shards[sid], n_shards=1,
                                   itemsize=dtype.itemsize):
                data.device_dense_shard(sid, dtype=dtype)
            data.device_labels()
            data.device_weights()

    def _entity_shards(self) -> int:
        if self.mesh is None:
            return 1
        from photon_ml_tpu.parallel.mesh import ENTITY_AXIS

        return int(getattr(self.mesh, "shape", {}).get(ENTITY_AXIS, 1))

    def prepare(self, data: GameData,
                locked: Sequence[str] = ()) -> dict[str, object]:
        self._check_sequence(locked)
        self._prefetch_device_feed(data, locked)
        datasets: dict[str, object] = {}
        ep = self._entity_shards()
        for cid in self.update_sequence:
            if cid in locked:
                continue  # frozen coordinate: no dataset, no training
            cfg = self.coordinate_configs[cid]
            if isinstance(cfg, FixedEffectCoordinateConfig):
                datasets[cid] = FixedEffectDataset.build(
                    cid, data, cfg.feature_shard_id, mesh=self.mesh,
                    dtype=(jnp.bfloat16 if cfg.design_dtype == "bfloat16"
                           else jnp.float32))
            elif isinstance(cfg, FactoredRandomEffectCoordinateConfig):
                # rebuilt each alternation around the learned projection
                datasets[cid] = None
            else:
                datasets[cid] = RandomEffectDataset.build(
                    cid, data, cfg.dataset, n_entity_shards=ep)
                logger.info(
                    "coordinate %s: %d active entities in %d buckets, "
                    "%d passive rows", cid, datasets[cid].n_active_entities,
                    len(datasets[cid].buckets),
                    len(datasets[cid].passive_sample_idx))
        # cross-coordinate residency budget BEFORE warm compiles: the warm
        # threads must compile the signatures the final (possibly flipped-
        # to-streaming) datasets will actually solve with
        self._apply_fat_budget(data, datasets)
        for cid, ds in datasets.items():
            if isinstance(ds, RandomEffectDataset):
                self._start_warm_compile(ds, self.coordinate_configs[cid],
                                         data.n_samples)
        return datasets

    def _apply_fat_budget(self, data: GameData, datasets) -> None:
        """Cross-coordinate HBM accounting (the per-build guard can't see
        it): several coordinates can each pass the per-device fat cap while
        their SUM exceeds it. Flip the largest offenders to streaming until
        the total fits, then drop any prefetched dense shard images that no
        remaining resident consumer will read — a dead multi-GiB pin in the
        memory-tight regime would defeat the guard's purpose."""
        from photon_ml_tpu.game.data import (
            RE_FAT_CACHE_MAX_BYTES,
            resident_fat_bytes,
        )

        ep = self._entity_shards()
        resident = [
            (cid, ds, resident_fat_bytes(ds.buckets) // ep)
            for cid, ds in datasets.items()
            if isinstance(ds, RandomEffectDataset)
            and ds.config.cache_device_buckets]
        total = sum(f for _, _, f in resident)
        for cid, ds, f in sorted(resident, key=lambda t: -t[2]):
            if total <= RE_FAT_CACHE_MAX_BYTES:
                break
            logger.warning(
                "coordinate %s: flipping to upload-and-drop streaming — "
                "the coordinates' combined resident fat tensors "
                "(%.1f GiB/device) exceed the %.1f GiB cap",
                cid, total / 2**30, RE_FAT_CACHE_MAX_BYTES / 2**30)
            datasets[cid] = dataclasses.replace(
                ds, config=dataclasses.replace(
                    ds.config, cache_device_buckets=False))
            total -= f
        # evict dense images with no resident consumer (streaming solvers
        # never touch the shared image; fixed effects keep theirs)
        keep = set()
        for cid, cfg in self.coordinate_configs.items():
            if isinstance(cfg, FixedEffectCoordinateConfig):
                keep.add(cfg.feature_shard_id)
            elif isinstance(cfg, RandomEffectCoordinateConfig):
                ds = datasets.get(cid)
                if (isinstance(ds, RandomEffectDataset)
                        and ds.config.cache_device_buckets):
                    keep.add(cfg.dataset.feature_shard_id)
        for key in list(data._device_cache):
            if (isinstance(key, tuple) and key
                    and key[0] == "dense_shard" and key[1] not in keep):
                del data._device_cache[key]

    def _start_warm_compile(self, dataset, cfg, n: int) -> None:
        """Kick off the coordinate's bucket-shape compiles in the background
        so they overlap the fixed-effect stage (a warm driver run measured
        ~2.8 s of compile-cache loading serialized inside the first RE
        sweep). The solver hash (task, optimization config, mesh) matches
        the one RandomEffectCoordinate builds, so train() hits the same jit
        cache; RandomEffectSolver._warm_compile joins this thread before
        checking the done flag."""
        import threading

        from photon_ml_tpu.game.random_effect import RandomEffectSolver

        solver = RandomEffectSolver(task=self.task, config=cfg.optimization,
                                    mesh=self.mesh,
                                    design_dtype=cfg.design_dtype)
        th = threading.Thread(target=solver._warm_compile, args=(dataset, n),
                              daemon=True)
        object.__setattr__(dataset, "_warm_thread", th)
        th.start()

    def _coordinates(self, data: GameData, datasets: Mapping[str, object],
                     config: GameOptimizationConfiguration,
                     locked: Sequence[str] = ()):
        out = {}
        for cid in self.update_sequence:
            if cid in locked:
                continue
            ccfg = self.coordinate_configs[cid]
            if isinstance(ccfg, FixedEffectCoordinateConfig):
                out[cid] = FixedEffectCoordinate(
                    coordinate_id=cid, dataset=datasets[cid], task=self.task,
                    config=ccfg.optimization, lam=config.lam(cid),
                    downsampler=ccfg.downsampler)
            elif isinstance(ccfg, FactoredRandomEffectCoordinateConfig):
                from photon_ml_tpu.game.factored import (
                    FactoredRandomEffectCoordinate,
                )

                out[cid] = FactoredRandomEffectCoordinate(
                    coordinate_id=cid, data=data,
                    dataset_config=ccfg.dataset, task=self.task,
                    config=ccfg.optimization,
                    projection_config=ccfg.projection_optimization,
                    lam=config.lam(cid),
                    lam_projection=ccfg.lam_projection,
                    n_factored_iterations=ccfg.n_factored_iterations,
                    mesh=self.mesh)
            else:
                out[cid] = RandomEffectCoordinate(
                    coordinate_id=cid, dataset=datasets[cid], data=data,
                    task=self.task, config=ccfg.optimization,
                    lam=config.lam(cid), mesh=self.mesh,
                    design_dtype=ccfg.design_dtype)
        return out

    # --- fit ---------------------------------------------------------------
    def fit(
        self,
        data: GameData,
        configurations: Sequence[GameOptimizationConfiguration],
        validation: Optional[tuple[GameData, Sequence[Evaluator]]] = None,
        datasets: Optional[Mapping[str, object]] = None,
        initial_models: Optional[Mapping[str, object]] = None,
        locked: Sequence[str] = (),
        checkpoint=None,
        resume: bool = False,
        guard=None,  # Optional[photon_ml_tpu.resilience.DivergenceGuard]
        on_result=None,  # Optional[Callable[[int, GameResult], None]]
    ) -> list[GameResult]:
        """``datasets`` (from :meth:`prepare`) lets callers that fit many
        times over the same data — e.g. a tuning loop — build the coordinate
        datasets once. ``initial_models``/``locked`` are the reference's
        partial-retrain path (warm-start from a saved GameModel; frozen
        coordinates keep their model and skip training);
        ``checkpoint``/``resume`` persist/restore coordinate-boundary state
        (single-configuration fits only — a resumed grid would mis-attribute
        the restored state to every configuration). ``guard`` is the
        resilience subsystem's divergence guard (rollback / regularization
        backoff / freeze at coordinate boundaries; see RESILIENCE.md) —
        shared across configurations so a tuning loop's failure budget is
        per-run, not per-point. ``validation`` may be a zero-arg callable
        returning the ``(GameData, evaluators)`` tuple — resolved at first
        use, so a driver can keep the validation read in flight while
        early sweeps run. ``on_result(index, result)`` fires the moment
        each configuration finishes — the async I/O pipeline's hook for
        submitting that model's background save while the remaining grid
        points still train."""
        self._check_sequence(locked)
        if checkpoint is not None and len(configurations) != 1:
            raise ValueError("checkpointing supports exactly one configuration")
        if datasets is None:
            datasets = self.prepare(data, locked=locked)
        cd = CoordinateDescent(
            update_sequence=self.update_sequence,
            n_iterations=self.n_cd_iterations,
            max_score_memory_bytes=self.max_score_memory_bytes)
        results: list[GameResult] = []
        for config in configurations:
            coordinates = self._coordinates(data, datasets, config, locked)
            # identify the whole run shape, not just the lambdas: a resumed
            # checkpoint with a different update sequence / sweep count /
            # locked set / dataset would silently mis-attribute state
            fingerprint = json.dumps({
                "weights": sorted(config.regularization_weights.items()),
                "update_sequence": list(self.update_sequence),
                "n_cd_iterations": self.n_cd_iterations,
                "locked": sorted(locked),
                "n_samples": data.n_samples,
                # every coordinate's full configuration (optimizer, bounds,
                # regularization, design dtype) — resuming under a changed
                # config must fail loudly, not blend incompatible state
                # (the multi-process fingerprint has always done this)
                "configs": {c: repr(self.coordinate_configs.get(c))
                            for c in self.update_sequence},
            }, sort_keys=True)
            cd_result = cd.run(coordinates, data, self.task,
                               validation=validation,
                               initial_models=initial_models,
                               checkpoint=checkpoint, resume=resume,
                               locked=locked,
                               config_fingerprint=fingerprint,
                               guard=guard)
            # the final CD sweep already evaluated this exact model
            evaluation = cd_result.final_evaluation
            results.append(GameResult(
                model=cd_result.model, configuration=config,
                evaluation=evaluation,
                validation_history=cd_result.validation_history))
            logger.info("configuration %s -> %s",
                        dict(config.regularization_weights), evaluation)
            if on_result is not None:
                on_result(len(results) - 1, results[-1])
        return results

    @staticmethod
    def select_best(results: Sequence[GameResult]) -> GameResult:
        """Best by the first validation evaluator (reference ModelSelection)."""
        scored = [r for r in results if r.evaluation is not None]
        if not scored:
            return results[0]
        best = scored[0]
        for r in scored[1:]:
            ev, val = r.evaluation.primary
            if ev.better_than(val, best.evaluation.primary[1]):
                best = r
        return best
