"""Block coordinate descent over GAME coordinates.

Re-design of ``photon-api/.../algorithm/CoordinateDescent.scala``: for each
sweep, for each coordinate in the update sequence, subtract the coordinate's
previous scores from the total, train on the residual offsets, add the new
scores back, and (optionally) evaluate validation metrics. Warm starts flow
from each coordinate's previous-sweep model.

The score-accounting invariant (SURVEY.md §7 hard-parts #6): at any point,
``total = data.offsets + Σ_c scores[c]`` — verified cheaply after every
sweep; a property test asserts it to float tolerance.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Mapping, Optional, Sequence

import numpy as np

from photon_ml_tpu.evaluation import evaluate_all
from photon_ml_tpu.game.coordinate import Coordinate, CoordinateModel
from photon_ml_tpu.game.data import GameData
from photon_ml_tpu.game.model import GameModel
from photon_ml_tpu.resilience import fault_point, fault_value, heartbeat
from photon_ml_tpu.telemetry import metrics as _tmetrics
from photon_ml_tpu.types import TaskType

logger = logging.getLogger(__name__)

#: host-side dispatch wall per coordinate step (device work may still be in
#: flight — async dispatch is what lets the next coordinate's host prep
#: overlap; the sweep span is the honest total). A registry timer, not a
#: raw perf_counter pair, so the number lands in /metrics (hygiene rule 5).
_STEP_DISPATCH = _tmetrics.histogram(
    "photon_game_step_dispatch_seconds",
    "Host-side dispatch wall per committed coordinate-descent step "
    "(async: device work may continue past it)", labels=("coordinate",))


from collections.abc import Mapping as _Mapping


class _LazyScores(_Mapping):
    """The result's coordinate-score decomposition, pulled device→host on
    first access in ONE concatenated transfer. The training driver never
    reads it (it saves the model), so the common path pays neither the
    transfer nor the pipeline drain; consumers that do read it (tests, the
    accounting invariant) see a plain mapping."""

    def __init__(self, device_scores: dict, n: int):
        self._device = device_scores
        self._n = n
        self._host: dict | None = None

    def _pull(self) -> dict:
        if self._host is None:
            import jax.numpy as jnp

            keys = list(self._device)
            if keys:
                flat = np.asarray(
                    jnp.concatenate([self._device[k] for k in keys]),
                    np.float32)
                self._host = {k: flat[i * self._n:(i + 1) * self._n]
                              for i, k in enumerate(keys)}
            else:
                self._host = {}
            self._device = {}
        return self._host

    def __getitem__(self, k):
        return self._pull()[k]

    def __iter__(self):
        return iter(self._pull())

    def __len__(self):
        return len(self._device) if self._host is None else len(self._host)


@dataclasses.dataclass
class CoordinateDescentResult:
    model: GameModel
    #: this coordinate-score decomposition of the training data
    scores: dict[str, np.ndarray]
    #: per-sweep validation metric dicts (empty when no validation set)
    validation_history: list[dict[str, float]]
    #: final sweep's full evaluation (None without a validation set)
    final_evaluation: object = None  # Optional[EvaluationResults]


def _device_memory_bytes() -> int:
    """Best-effort per-device memory limit (used by the score-memory
    guard); a conservative 16 GiB (v5e HBM) when the backend won't say."""
    import jax

    try:
        stats = jax.devices()[0].memory_stats()
        if stats and "bytes_limit" in stats:
            return int(stats["bytes_limit"])
    except Exception:
        pass
    return 16 << 30


@dataclasses.dataclass(frozen=True)
class CoordinateDescent:
    """Drives the sweep loop over an ordered update sequence.

    ``max_score_memory_bytes`` guards the memory cliff of the
    device-resident score decomposition: the run holds K+1 vectors of
    ``n_samples`` f32 on device (K coordinate scores + the running total).
    The DESIGN hits HBM first in practice (≥8x the footprint — ROADMAP
    item 5), but past ~2-3 B samples/chip the decomposition itself stops
    fitting; rather than an opaque allocator failure mid-sweep, the run
    refuses up front with guidance. ``None`` → half the device's memory;
    the sharded-score prototype (tests/test_sharded_scores.py) is the
    escape hatch when a workload genuinely crosses the cliff.
    """

    update_sequence: Sequence[str]
    n_iterations: int = 1
    max_score_memory_bytes: Optional[int] = None

    def run(
        self,
        coordinates: Mapping[str, Coordinate],
        data: GameData,
        task: TaskType,
        validation=None,  # (GameData, evaluators) | zero-arg callable -> same
        initial_models: Optional[Mapping[str, CoordinateModel]] = None,
        checkpoint=None,  # Optional[photon_ml_tpu.io.checkpoint.CheckpointManager]
        resume: bool = False,
        locked: Sequence[str] = (),
        config_fingerprint: Optional[str] = None,
        guard=None,  # Optional[photon_ml_tpu.resilience.DivergenceGuard]
    ) -> CoordinateDescentResult:
        """``locked`` coordinates (reference partial retrain via
        ``--model-input-dir``: freeze some coordinates, retrain others) keep
        their ``initial_models`` entry; their scores participate in the
        residual accounting but they are never retrained — so they need no
        entry in ``coordinates`` (and no dataset build).

        ``guard`` (a :class:`~photon_ml_tpu.resilience.DivergenceGuard`)
        checks each coordinate step's outputs for NaN/Inf: on divergence
        the step is rolled back to the last good state (re-read from
        ``checkpoint`` when one is present — the same path a crash-restart
        takes), the coordinate's regularization is bumped, and the step
        retries; past the policy's retry budget the coordinate is frozen
        at its last good model (the ``locked`` mechanism) and the run
        continues degraded. ``guard=None`` (default) is the exact
        pre-guard code path; a healthy guarded run is bit-identical since
        the checks are pure reads."""
        locked = set(locked)
        coordinates = dict(coordinates)  # guard retries may bump a lam
        for cid in locked:
            if not initial_models or cid not in initial_models:
                raise KeyError(
                    f"locked coordinate {cid!r} needs an initial model")
        for cid in self.update_sequence:
            if cid not in coordinates and cid not in locked:
                raise KeyError(f"update sequence names unknown coordinate {cid!r}")

        import jax.numpy as jnp

        # memory-cliff guard: K coordinate score vectors + the running
        # total, all device-resident f32 for the whole run
        score_bytes = (len(self.update_sequence) + 1) * data.n_samples * 4
        budget = (self.max_score_memory_bytes
                  if self.max_score_memory_bytes is not None
                  else _device_memory_bytes() // 2)
        if score_bytes > budget:
            raise ValueError(
                f"score decomposition needs {score_bytes / 2**30:.1f} GiB "
                f"device memory ({len(self.update_sequence)}+1 vectors x "
                f"{data.n_samples} samples x 4 B) — over the "
                f"{budget / 2**30:.1f} GiB budget. Shard the run across "
                f"more chips/processes (game/multiprocess.py), or raise "
                f"max_score_memory_bytes if you know the design fits; the "
                f"data-sharded score prototype is "
                f"tests/test_sharded_scores.py (ROADMAP item 5)")

        models: dict[str, CoordinateModel] = dict(initial_models or {})
        # The score decomposition lives ON DEVICE for the whole run (ROADMAP
        # "score-path device residency"): residual arithmetic and the
        # coordinates' score gathers/scatters happen where the margins are
        # computed, so a CD sweep moves no O(n_samples) vectors host↔device.
        # Host copies are made only at checkpoint saves and in the result.
        scores: dict[str, jnp.ndarray] = {
            cid: jnp.zeros(data.n_samples, jnp.float32)
            for cid in self.update_sequence}
        # host mirror for checkpointing: synced incrementally (only the
        # just-trained coordinate is copied back per step) so a checkpointed
        # run still moves one score vector D2H per coordinate step, not K
        host_scores: dict[str, np.ndarray] = {
            cid: np.zeros(data.n_samples, np.float32)
            for cid in self.update_sequence}
        # seed scores from initial models (partial-retrain warm start path)
        for cid, model in models.items():
            if cid in scores:
                host_scores[cid] = model.score(data).astype(np.float32)
                scores[cid] = jnp.asarray(host_scores[cid])

        start_sweep, start_coord = 0, 0
        if resume and checkpoint is not None and checkpoint.latest_step() is not None:
            state = checkpoint.restore(expected_fingerprint=config_fingerprint)
            models = dict(state.model.coordinates)
            for k, v in state.scores.items():
                if k in scores:
                    host_scores[k] = np.asarray(v, np.float32)
                    scores[k] = jnp.asarray(host_scores[k])
            start_sweep, start_coord = state.sweep, state.coordinate_index
            logger.info("resumed from checkpoint: sweep %d coordinate %d",
                        start_sweep, start_coord)
        # all-zero offsets (no base margin — the common case) skip their
        # 4 B/row upload; the host scan costs ~0.5 ms/1M rows
        if data.offsets.size and not data.offsets.any():
            total = sum(scores.values()) + jnp.zeros(
                data.n_samples, jnp.float32)
        else:
            total = jnp.asarray(data.offsets, jnp.float32) \
                + sum(scores.values())

        # --- telemetry (live only under --telemetry-dir: the loss/grad-norm
        # reads force a device sync per step, which a bare run's async
        # dispatch pipeline must not pay) ---------------------------------
        from photon_ml_tpu.telemetry import aggregate as fleet
        from photon_ml_tpu.telemetry import tracing
        telemetry_on = tracing.enabled()
        if telemetry_on:
            from photon_ml_tpu.ops.losses import loss_for_task
            from photon_ml_tpu.telemetry import metrics as tmetrics

            _loss = loss_for_task(task)
            _labels_d = jnp.asarray(data.labels, jnp.float32)
            _weights_d = jnp.asarray(data.weights, jnp.float32)
            _loss_gauge = tmetrics.gauge(
                "photon_game_coordinate_loss",
                "Weighted data objective (no regularizer) after the "
                "coordinate's step", labels=("coordinate",))
            _gnorm_gauge = tmetrics.gauge(
                "photon_game_coordinate_grad_norm",
                "Norm of the weighted margin gradient after the "
                "coordinate's step", labels=("coordinate",))
            _steps_total = tmetrics.counter(
                "photon_game_coordinate_steps_total",
                "Committed coordinate-descent steps",
                labels=("coordinate",))

        history: list[dict[str, float]] = []
        final_evaluation = None
        for sweep in range(start_sweep, self.n_iterations):
            heartbeat("cd.sweep")
            fault_point("worker.stall", sweep=sweep)
            with tracing.span("cd.sweep", sweep=sweep) as sweep_span:
                if telemetry_on:
                    # the training flat-recompile contract, trace-visible:
                    # every cd.sweep span carries the number of profiled-jit
                    # compiles it triggered — 0 for every sweep after the
                    # first (tests/test_telemetry.py hard-asserts this)
                    from photon_ml_tpu.telemetry import profiling

                    _compiles_at_sweep_start = profiling.total_compiles()
                for ci, cid in enumerate(self.update_sequence):
                    if sweep == start_sweep and ci < start_coord:
                        continue
                    if cid in locked:
                        continue  # frozen: scores stay as seeded
                    if (guard is not None and cid in guard.frozen
                            and cid in models):
                        # diverged earlier THIS fit: locked at last good
                        # model. A fresh configuration (no model yet — e.g.
                        # the next grid point sharing the guard) retrains:
                        # its new regularization may well not diverge.
                        continue
                    heartbeat("cd.step")
                    with tracing.span("cd.step", coordinate=cid,
                                      sweep=sweep) as step_span, \
                            _STEP_DISPATCH.labels(
                                coordinate=cid).time() as dispatch_timer:
                        while True:
                            residual = total - scores[cid]
                            try:
                                model, new_scores = coordinates[cid].train(
                                    residual, models.get(cid), sweep=sweep)
                                new_scores = fault_value(
                                    "optimizer.step", new_scores,
                                    coordinate=cid, sweep=sweep)
                                step_error = None
                            except Exception as e:
                                if guard is None:
                                    raise
                                model, new_scores, step_error = None, None, e
                            if guard is None or (step_error is None
                                                 and guard.healthy(
                                                     model, new_scores)):
                                break  # healthy: commit below
                            action = guard.on_divergence(
                                cid, sweep=sweep,
                                has_good_model=cid in models,
                                error=step_error)
                            if action == "freeze":
                                new_scores = None  # keep last good state
                                break
                            # roll back to the last durable state: nothing
                            # was committed in-process, and when a
                            # checkpoint manager is present the state is
                            # re-read from disk so recovery exercises the
                            # exact crash-restart path
                            if (checkpoint is not None
                                    and checkpoint.latest_step() is not None):
                                state = checkpoint.restore(
                                    expected_fingerprint=config_fingerprint)
                                models = dict(state.model.coordinates)
                                for k, v in state.scores.items():
                                    if k in scores:
                                        host_scores[k] = np.asarray(
                                            v, np.float32)
                                        scores[k] = jnp.asarray(
                                            host_scores[k])
                                total = jnp.asarray(data.offsets,
                                                    jnp.float32) \
                                    + sum(scores.values())
                            # regularization backoff: stronger curvature is
                            # the standard fix for a diverged GLM solve
                            coord = coordinates[cid]
                            if hasattr(coord, "lam"):
                                coordinates[cid] = dataclasses.replace(
                                    coord, lam=guard.next_lam(coord.lam))
                        if new_scores is None:
                            continue  # frozen mid-sweep: nothing to commit
                        models[cid] = model
                        total = residual + new_scores
                        scores[cid] = new_scores
                        if telemetry_on:
                            # progress of the BLOCK objective CD minimizes:
                            # loss of the committed total margin, and the
                            # norm of its margin gradient (≈ how much signal
                            # is left for later coordinates to absorb)
                            margins = total.astype(jnp.float32)
                            obj = float(jnp.sum(
                                _weights_d * _loss.loss(margins, _labels_d)))
                            gnorm = float(jnp.linalg.norm(
                                _weights_d * _loss.d1(margins, _labels_d)))
                            step_span.set(loss=obj, grad_norm=gnorm)
                            _loss_gauge.labels(coordinate=cid).set(obj)
                            _gnorm_gauge.labels(coordinate=cid).set(gnorm)
                            _steps_total.labels(coordinate=cid).inc()
                        # dispatch time: device work may still be in flight
                        # (async dispatch is what lets the next coordinate's
                        # host prep overlap); the sweep wall is the honest
                        # total. The timer's running read keeps the log line
                        # inside the step without a second clock.
                        logger.info(
                            "sweep %d coordinate %s dispatched in %.2fs",
                            sweep, cid, dispatch_timer.elapsed())
                        if checkpoint is not None:
                            from photon_ml_tpu.io.checkpoint import (
                                CoordinateDescentState,
                            )

                            # sync ONLY the trained coordinate to the mirror
                            host_scores[cid] = np.asarray(new_scores,
                                                          np.float32)
                            next_ci = (ci + 1) % len(self.update_sequence)
                            checkpoint.save(
                                sweep * len(self.update_sequence) + ci + 1,
                                CoordinateDescentState(
                                    sweep=sweep + (next_ci == 0),
                                    coordinate_index=next_ci,
                                    model=GameModel(
                                        coordinates=dict(models), task=task),
                                    scores=dict(host_scores)),
                                fingerprint=config_fingerprint)

                if validation is not None:
                    if callable(validation):
                        # async-ingest join point: the driver kicked the
                        # validation read off in the background; the first
                        # sweep's evaluation is its first (and only) wait
                        validation = validation()
                    vdata, evaluators = validation
                    with tracing.span("cd.validate", sweep=sweep):
                        gm = GameModel(coordinates=dict(models), task=task)
                        vscores = gm.score(vdata)
                        results = evaluate_all(
                            evaluators, vscores, vdata.labels,
                            weights=vdata.weights, id_tags=vdata.id_columns)
                    history.append(results.as_dict())
                    final_evaluation = results
                    logger.info("sweep %d validation: %s", sweep, results)
                if telemetry_on:
                    sweep_span.set(compiles=profiling.total_compiles()
                                   - _compiles_at_sweep_start)
            # fleet-metrics fold point (no-op unless --metrics-port
            # installed a hook; placed outside the cd.sweep span so the
            # fold's own wall time never pollutes the sweep timing)
            fleet.sweep_boundary(sweep=sweep)

        model = GameModel(
            coordinates={cid: models[cid] for cid in self.update_sequence},
            task=task)
        if validation is not None and final_evaluation is None:
            # sweep loop fully skipped (resume from a completed checkpoint):
            # the model is final but unevaluated — evaluate it now so the
            # caller still gets metrics
            if callable(validation):
                validation = validation()
            vdata, evaluators = validation
            vscores = model.score(vdata)
            final_evaluation = evaluate_all(
                evaluators, vscores, vdata.labels, weights=vdata.weights,
                id_tags=vdata.id_columns)
            history.append(final_evaluation.as_dict())
        return CoordinateDescentResult(
            model=model,
            scores=_LazyScores(dict(scores), data.n_samples),
            validation_history=history,
            final_evaluation=final_evaluation)
