"""Block coordinate descent over GAME coordinates.

Re-design of ``photon-api/.../algorithm/CoordinateDescent.scala``: for each
sweep, for each coordinate in the update sequence, subtract the coordinate's
previous scores from the total, train on the residual offsets, add the new
scores back, and (optionally) evaluate validation metrics. Warm starts flow
from each coordinate's previous-sweep model.

The score-accounting invariant (SURVEY.md §7 hard-parts #6): at any point,
``total = data.offsets + Σ_c scores[c]`` — verified cheaply after every
sweep; a property test asserts it to float tolerance.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Mapping, Optional, Sequence

import numpy as np

from photon_ml_tpu.evaluation import Evaluator, evaluate_all
from photon_ml_tpu.game.coordinate import Coordinate, CoordinateModel
from photon_ml_tpu.game.data import GameData
from photon_ml_tpu.game.model import GameModel
from photon_ml_tpu.types import TaskType

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class CoordinateDescentResult:
    model: GameModel
    #: this coordinate-score decomposition of the training data
    scores: dict[str, np.ndarray]
    #: per-sweep validation metric dicts (empty when no validation set)
    validation_history: list[dict[str, float]]


@dataclasses.dataclass(frozen=True)
class CoordinateDescent:
    """Drives the sweep loop over an ordered update sequence."""

    update_sequence: Sequence[str]
    n_iterations: int = 1

    def run(
        self,
        coordinates: Mapping[str, Coordinate],
        data: GameData,
        task: TaskType,
        validation: Optional[tuple[GameData, Sequence[Evaluator]]] = None,
        initial_models: Optional[Mapping[str, CoordinateModel]] = None,
    ) -> CoordinateDescentResult:
        for cid in self.update_sequence:
            if cid not in coordinates:
                raise KeyError(f"update sequence names unknown coordinate {cid!r}")

        models: dict[str, CoordinateModel] = dict(initial_models or {})
        scores: dict[str, np.ndarray] = {
            cid: np.zeros(data.n_samples, np.float32)
            for cid in self.update_sequence}
        # seed scores from initial models (partial-retrain warm start path)
        for cid, model in models.items():
            if cid in scores:
                scores[cid] = model.score(data).astype(np.float32)
        total = data.offsets + sum(scores.values())

        history: list[dict[str, float]] = []
        for sweep in range(self.n_iterations):
            for cid in self.update_sequence:
                t0 = time.perf_counter()
                residual = (total - scores[cid]).astype(np.float32)
                model, new_scores = coordinates[cid].train(
                    residual, models.get(cid), sweep=sweep)
                models[cid] = model
                total = residual + new_scores
                scores[cid] = new_scores
                logger.info("sweep %d coordinate %s trained in %.2fs",
                            sweep, cid, time.perf_counter() - t0)

            if validation is not None:
                vdata, evaluators = validation
                gm = GameModel(coordinates=dict(models), task=task)
                vscores = gm.score(vdata)
                results = evaluate_all(
                    evaluators, vscores, vdata.labels, weights=vdata.weights,
                    id_tags=vdata.id_columns)
                history.append(results.as_dict())
                logger.info("sweep %d validation: %s", sweep, results)

        model = GameModel(
            coordinates={cid: models[cid] for cid in self.update_sequence},
            task=task)
        return CoordinateDescentResult(
            model=model, scores=scores, validation_history=history)
