"""Random-effect feature-space projectors.

Re-design of the reference's projection layer
(``photon-api/.../projector/{Projector, ProjectionMatrix,
ProjectionMatrixBroadcast, IndexMapProjector, RandomProjection,
LinearSubspaceProjector}.scala`` + ``projector/ProjectorType.scala``), which
shrinks each per-entity solve to a small feature space:

- **INDEX_MAP** — each entity's observed shard features are compacted to a
  dense local index range (the reference's ``IndexMapProjector`` /
  ``LinearSubspaceProjector``). This is the default and is implemented
  directly inside the bucket build in :mod:`photon_ml_tpu.game.data` — the
  bucket's ``feature_index`` IS the projection map.
- **RANDOM** — one shared Gaussian Johnson–Lindenstrauss matrix ``P``
  (``projected_dim × shard_dim``) projects every entity's features into a
  common low-dimensional space (the reference's ``RandomProjection`` with the
  matrix broadcast to executors via ``ProjectionMatrixBroadcast``; here it is
  simply a host array closed over by the jitted solve). Training happens on
  ``z = P x``; because margins are linear, the learned ``v`` is exactly
  equivalent to shard-space coefficients ``w = Pᵀ v``, which is how models
  are "projected back after training" for output parity.

TPU-first departure: the reference projects models back to the original
space immediately after training. We keep projected-space models live
(scoring projects features on the fly — a dense ``(rows, projected_dim)``
matmul that maps straight onto the MXU) and only materialize the
back-projection when exporting to the reference's Avro layout
(:func:`RandomEffectModel.to_shard_space`).
"""

from __future__ import annotations

import dataclasses
import enum

import numpy as np


class ProjectorType(str, enum.Enum):
    """Reference ``projector/ProjectorType.scala``."""

    INDEX_MAP = "INDEX_MAP"
    RANDOM = "RANDOM"


@dataclasses.dataclass(frozen=True)
class RandomProjector:
    """Shared Gaussian projection ``P`` with JL scaling 1/sqrt(projected_dim).

    The same matrix serves every entity of the coordinate (reference
    ``ProjectionMatrixBroadcast``: one matrix broadcast cluster-wide).
    """

    matrix: np.ndarray  # (projected_dim, shard_dim) float32

    @property
    def projected_dim(self) -> int:
        return int(self.matrix.shape[0])

    @property
    def shard_dim(self) -> int:
        return int(self.matrix.shape[1])

    @staticmethod
    def build(shard_dim: int, projected_dim: int, seed: int) -> "RandomProjector":
        if projected_dim <= 0 or projected_dim > shard_dim:
            raise ValueError(
                f"projected_dim must be in [1, shard_dim={shard_dim}], "
                f"got {projected_dim}")
        rng = np.random.default_rng(seed)
        m = rng.normal(size=(projected_dim, shard_dim)).astype(np.float32)
        m /= np.float32(np.sqrt(projected_dim))
        return RandomProjector(matrix=m)

    def project_rows(self, cols: np.ndarray, vals: np.ndarray,
                     rows: np.ndarray, n_rows: int) -> np.ndarray:
        """Dense projected features ``Z = X Pᵀ`` from COO parts.

        ``rows/cols/vals`` are the CSR triplets of the rows being projected
        (rows already renumbered 0..n_rows-1). One scatter-accumulated
        outer-product pass — no shard-dim dense intermediate.
        """
        z = np.zeros((n_rows, self.projected_dim), np.float32)
        if len(cols):
            contrib = vals[:, None].astype(np.float32) * self.matrix.T[cols]
            np.add.at(z, rows, contrib)
        return z

    def project_back(self, v: np.ndarray) -> np.ndarray:
        """Shard-space coefficients ``w = Pᵀ v`` (exact for scoring:
        ``w·x = v·Px``). Works on ``(..., projected_dim)`` batches."""
        return np.asarray(v, np.float32) @ self.matrix

    def project_back_variances(self, var: np.ndarray) -> np.ndarray:
        """Approximate shard-space variances ``var_w = (P²)ᵀ var_v``
        (exact under an independent-coordinate posterior; same caveat as the
        reference's projected-space variance output)."""
        return np.asarray(var, np.float32) @ (self.matrix ** 2)
