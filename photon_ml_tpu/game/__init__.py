"""GAME: Generalized Additive Mixed-Effect models, TPU-first.

Re-design of the reference's GAME stack (``photon-api/.../algorithm/``,
``data/``, ``model/``, ``estimators/``): block coordinate descent over a
fixed-effect coordinate (pod-wide sharded GLM solve) and random-effect
coordinates (per-entity solves, ``vmap``-batched over size buckets instead of
the reference's per-executor breeze loops).
"""

from photon_ml_tpu.game.data import (  # noqa: F401
    FeatureShard,
    FixedEffectDataset,
    GameData,
    RandomEffectDataset,
    RandomEffectDatasetConfig,
)
from photon_ml_tpu.game.projector import (  # noqa: F401
    ProjectorType,
    RandomProjector,
)
from photon_ml_tpu.game.model import (  # noqa: F401
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.game.coordinate import (  # noqa: F401
    Coordinate,
    FixedEffectCoordinate,
    RandomEffectCoordinate,
)
from photon_ml_tpu.game.coordinate_descent import (  # noqa: F401
    CoordinateDescent,
    CoordinateDescentResult,
)
from photon_ml_tpu.game.estimator import (  # noqa: F401
    GameEstimator,
    GameOptimizationConfiguration,
    GameResult,
)
from photon_ml_tpu.game.transformer import (  # noqa: F401
    GameTransformer,
    ModelDataScores,
)
from photon_ml_tpu.game.factored import (  # noqa: F401
    FactoredDesign,
    FactoredRandomEffectCoordinate,
)
