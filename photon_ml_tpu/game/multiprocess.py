"""Multi-process GAME training: entity-partitioned random effects.

The reference trains random effects sharded across machines: rows are
shuffled so each Spark executor owns complete entities
(``photon-api/.../data/RandomEffectDatasetPartitioner.scala`` — a
frequency-balanced partition map), the per-entity solves then run
executor-local with zero communication
(``algorithm/RandomEffectCoordinate.scala``), and the model stays an RDD
sharded the same way. The multi-controller-JAX analog implemented here:

- **Entity partition** (:func:`balanced_entity_partition`): a deterministic,
  frequency-balanced (longest-processing-time greedy) assignment
  entity → process, computed identically on every process from globally
  allreduced entity row counts.
- **Row shuffle** (:func:`exchange_rows`): each process starts from its own
  arbitrary row shard (host-local Avro reads) and keeps exactly the rows
  whose owner it is. Implemented over the host allgather collective —
  O(total) received per process, like Spark's shuffle volume at its
  reduce side; JAX exposes no host-side point-to-point, and the exchange
  runs once per RE entity type at dataset-build time, not per sweep.
- **Per-process datasets**: the fixed effect feeds the global ``data``-axis
  mesh via :func:`~photon_ml_tpu.parallel.multihost.global_glm_data_multihost`
  (one psum'd global solve — every process participates); each
  :class:`~photon_ml_tpu.game.data.RandomEffectDataset` is built
  per-process over that process's OWN entities only and solved on LOCAL
  devices — the executor-local zero-comm solve, verbatim.
- **Row-local score accounting**: coordinate-descent residuals live on the
  process that owns the row; the score invariant
  ``total = offsets + Σ_c scores[c]`` holds per-process. A random-effect
  coordinate whose entity type differs from the primary row partition
  exchanges residuals/scores through a host allgather per sweep (the
  analog of the reference's per-iteration score join shuffle).
- **Model assembly**: at sweep end the per-process random-effect
  (key, coefficient) tables allgather into the identical global
  :class:`~photon_ml_tpu.game.model.RandomEffectModel` on every process;
  the fixed-effect model is already replicated by the psum'd solve. The
  chief process writes outputs.

Every collective here degenerates to the identity on a single process, so
the whole pipeline runs (and is unit-tested) single-process; the 2-process
loopback test in ``tests/test_multihost.py`` exercises the real collectives
and asserts equality with the single-process result.
"""

from __future__ import annotations

import dataclasses
import heapq
import logging
import os
from typing import Mapping, Optional, Sequence

import numpy as np

from photon_ml_tpu.game.data import (
    FeatureShard,
    GameData,
    RandomEffectDataset,
    RandomEffectDatasetConfig,
    host_design_for_shard,
)
from photon_ml_tpu.game.model import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.glm.problem import GLMOptimizationConfiguration
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.ops.objective import GLMData
from photon_ml_tpu.resilience import fault_point, fault_value, heartbeat
from photon_ml_tpu.types import TaskType

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# Entity partition (RandomEffectDatasetPartitioner analog)
# ---------------------------------------------------------------------------


def balanced_entity_partition(row_counts: np.ndarray,
                              n_processes: int) -> np.ndarray:
    """Frequency-balanced entity → process assignment.

    Longest-processing-time greedy: entities sorted by row count
    descending (ties by entity id, so the result is deterministic — every
    process must compute the SAME partition from the same counts), each
    assigned to the least-loaded process. The reference's
    ``RandomEffectDatasetPartitioner`` builds the same kind of map from a
    sampled frequency table.

    Returns an ``(n_entities,)`` int32 array of process ids. Entities with
    zero rows are still assigned (they all land on whatever process is
    least-loaded after the real entities — harmless, they carry no data),
    so the map is total.
    """
    counts = np.asarray(row_counts, np.int64)
    n_processes = int(n_processes)
    if n_processes <= 1:
        return np.zeros(len(counts), np.int32)
    order = np.lexsort((np.arange(len(counts)), -counts))
    owner = np.zeros(len(counts), np.int32)
    # (load, process) heap — process index tie-breaks deterministically
    heap = [(0, p) for p in range(n_processes)]
    heapq.heapify(heap)
    for e in order:
        load, p = heapq.heappop(heap)
        owner[e] = p
        heapq.heappush(heap, (load + int(counts[e]), p))
    return owner


# ---------------------------------------------------------------------------
# Row shuffle
# ---------------------------------------------------------------------------


def exchange_rows(game_local: GameData, dest_local: np.ndarray,
                  ) -> tuple[GameData, np.ndarray]:
    """All-to-all row shuffle: keep the rows this process owns.

    ``dest_local`` gives the destination process of each local row. Global
    row ids are defined as (process-order offset + local index) — the
    concatenation order of the host allgather — and the returned rows are
    sorted by global id, so every process's view of "its" rows is a
    deterministic slice of one global ordering (what makes the
    multi-process result comparable to a single-process run row-for-row).

    Returns ``(owned GameData, owned global row ids)``.
    """
    import jax

    from photon_ml_tpu.parallel.multihost import allgather_concat

    me = jax.process_index()
    dest_local = np.asarray(dest_local, np.int32)
    if jax.process_count() == 1:
        keep = np.flatnonzero(dest_local == me)
        return _take_rows(game_local, keep), keep.astype(np.int64)

    dest = allgather_concat(dest_local)
    keep = np.flatnonzero(dest == me).astype(np.int64)

    labels = allgather_concat(game_local.labels)[keep]
    offsets = allgather_concat(game_local.offsets)[keep]
    weights = allgather_concat(game_local.weights)[keep]
    id_columns = {k: allgather_concat(v)[keep]
                  for k, v in game_local.id_columns.items()}
    shards = {}
    for name, shard in game_local.shards.items():
        counts = allgather_concat(shard.row_counts().astype(np.int64))
        cols = allgather_concat(shard.cols)
        vals = allgather_concat(shard.vals)
        indptr = np.zeros(len(counts) + 1, np.int64)
        np.cumsum(counts, out=indptr[1:])
        shards[name] = FeatureShard(
            indptr=indptr, cols=cols, vals=vals, dim=shard.dim).take(keep)
    return GameData(labels=labels, offsets=offsets, weights=weights,
                    shards=shards, id_columns=id_columns), keep


def _take_rows(game: GameData, rows: np.ndarray) -> GameData:
    return GameData(
        labels=game.labels[rows],
        offsets=game.offsets[rows],
        weights=game.weights[rows],
        shards={k: s.take(rows) for k, s in game.shards.items()},
        id_columns={k: v[rows] for k, v in game.id_columns.items()})


def owner_of_rows(entities: np.ndarray, owner_of_entity: np.ndarray,
                  global_rows: np.ndarray, n_processes: int) -> np.ndarray:
    """Destination process per row: the row's entity's owner; rows with no
    entity (id < 0) spread round-robin by global row id so the fixed effect
    still sees balanced shards."""
    entities = np.asarray(entities, np.int64)
    dest = np.where(entities >= 0,
                    owner_of_entity[np.maximum(entities, 0)],
                    (np.asarray(global_rows, np.int64) % n_processes
                     ).astype(np.int32))
    return dest.astype(np.int32)


def process_file_share(reader, input_path) -> list[str]:
    """This process's share of the input file list — the multi-process
    drivers' read assignment (the executor-local reads of the reference).

    Shares are CONTIGUOUS runs of the sorted file list (size-balanced by
    cumulative file bytes), not strided: the global row ids every process
    derives from the process-concat order then coincide with the
    single-process sequential read order, which is what keeps every
    per-global-row-id keyed draw (down-sampling, active-bound subsampling)
    bit-identical to the single-process run. A strided share would permute
    the id ↔ record mapping and silently change the sampled sets.

    Raises when there are fewer files than processes (an empty-handed
    process would feed zero rows and desync shard budgets)."""
    import jax

    all_files = reader.paths(input_path)
    n_proc = jax.process_count()
    if n_proc > 1:
        # agree on the LISTING itself before ANY unilateral exit or further
        # collective: a file landing mid-listing (or a too-few-files exit
        # taken by one process only) must fail cleanly on every process,
        # not crash some and hang the rest at the next collective
        import hashlib

        from photon_ml_tpu.parallel.multihost import allgather_concat
        digest = hashlib.sha256("\0".join(all_files).encode()).digest()[:8]
        h = np.frombuffer(digest, np.uint32).astype(np.float64)
        sig = allgather_concat(
            np.array([float(len(all_files)), h[0], h[1]])).reshape(n_proc, 3)
        if not (sig == sig[:1]).all():
            raise SystemExit(
                "--multihost: the input file listing diverges across "
                "processes (different lengths or names) — every process "
                "must see the same files; re-run once the input directory "
                "is stable")
    # symmetric from here on: every process sees the same listing, so this
    # exit (and every later decision) fires on all processes or none
    if len(all_files) < n_proc:
        raise SystemExit(
            f"--multihost with {n_proc} processes needs at "
            f"least that many input files (got {len(all_files)}; split "
            f"the data)")
    try:
        sizes = np.array([max(os.path.getsize(f), 1) for f in all_files],
                         np.float64)
    except OSError:
        # non-stat-able paths (e.g. remote URIs a reader may accept)
        sizes = None
    if n_proc > 1:
        # stat results can still diverge across hosts (a file renamed
        # between the two passes, host-local disks): keep byte-size
        # balancing only when every process saw the same sizes, else
        # equal-count shares — the cuts below must be IDENTICAL everywhere
        from photon_ml_tpu.parallel.multihost import allgather_concat
        ok = sizes is not None
        local = np.concatenate(
            [[float(ok)], sizes if ok else np.zeros(len(all_files))])
        rows = allgather_concat(local).reshape(n_proc, len(all_files) + 1)
        if (rows[:, 0] == 1.0).all() and (rows == rows[:1]).all():
            sizes = rows[0, 1:]
        else:
            sizes = np.ones(len(all_files), np.float64)
    elif sizes is None:
        sizes = np.ones(len(all_files), np.float64)
    # cut the cumulative-size curve into n_proc near-equal spans, keeping
    # every span non-empty (each process must read at least one file)
    cum = np.cumsum(sizes)
    targets = cum[-1] * (np.arange(1, n_proc) / n_proc)
    cuts = np.searchsorted(cum, targets, side="left") + 1
    # enforce strictly increasing interior cuts within [1, len-...] so no
    # share is empty even with one huge file
    bounds = [0]
    for i, c in enumerate(cuts):
        lo = bounds[-1] + 1
        hi = len(all_files) - (n_proc - 1 - i)
        bounds.append(int(min(max(c, lo), hi)))
    bounds.append(len(all_files))
    pid = jax.process_index()
    return all_files[bounds[pid]:bounds[pid + 1]]


# ---------------------------------------------------------------------------
# Global id agreement (feature index maps + entity vocabularies)
# ---------------------------------------------------------------------------


def reconcile_global_ids(data: GameData, index_maps, vocabs,
                         id_columns=()):
    """Make per-process feature index maps and entity vocabularies GLOBAL.

    Under multi-process training each process reads its own file subset
    (the reference's executor-local HDFS reads), so locally-built feature
    indices and entity vocabularies disagree across processes. This unions
    the key sets through a host allgather, rebuilds them in the canonical
    deterministic order (:func:`~photon_ml_tpu.io.index.build_index_map`'s
    sorted order for features — identical to what a single-process read of
    ALL files would build — and sorted raw ids for vocabularies), and
    remaps this process's columns in place.

    Returns the remapped ``(data, index_maps, vocabs)``. Collective: every
    process must call with the same shard/vocab key sets requested
    (``id_columns`` pins the vocabulary iteration order, since a process
    that saw no rows for a column would otherwise skip its collectives).
    """
    from photon_ml_tpu.io.index import build_index_map
    from photon_ml_tpu.parallel.multihost import allgather_concat_strings
    from photon_ml_tpu.types import INTERCEPT_KEY

    new_maps = {}
    new_shards = dict(data.shards)
    for sid in sorted(index_maps):
        imap = index_maps[sid]
        local_names = imap.names()
        union = set(allgather_concat_strings(local_names))
        gmap = build_index_map(union,
                               add_intercept=INTERCEPT_KEY in union)
        perm = np.array([gmap.key_to_index[k] for k in local_names],
                        np.int32)
        shard = data.shards[sid]
        new_shards[sid] = dataclasses.replace(
            shard, cols=(perm[shard.cols] if len(shard.cols)
                         else shard.cols), dim=len(gmap))
        new_maps[sid] = gmap

    data = dataclasses.replace(data, shards=new_shards)
    data, new_vocabs = reconcile_vocabs(data, vocabs, id_columns)
    return data, new_maps, new_vocabs


def reconcile_vocabs(data: GameData, vocabs, id_columns=()):
    """The entity-vocabulary half of :func:`reconcile_global_ids` alone —
    for drivers whose FEATURE index maps are preset (scoring loads them
    with the model and must not re-key the coefficient tables) but whose
    grouped-metric id tags still need one global id space. Collective;
    identity-shaped at one process (modulo canonical re-sort)."""
    from photon_ml_tpu.parallel.multihost import allgather_concat_strings

    new_vocabs = {}
    new_ids = dict(data.id_columns)
    for col in sorted(set(id_columns) | set(vocabs)):
        vocab = vocabs.get(col, {})
        # vocab values are a permutation of range(len): invert to the
        # local id -> raw string table (every slot gets filled)
        local_names = [""] * len(vocab)
        for k, i in vocab.items():
            local_names[i] = k
        union = sorted(set(allgather_concat_strings(local_names)))
        gvocab = {k: i for i, k in enumerate(union)}
        perm = np.array([gvocab[k] for k in local_names], np.int64)
        ids = data.id_columns.get(col)
        if ids is not None and len(perm):
            new_ids[col] = np.where(ids >= 0, perm[np.maximum(ids, 0)],
                                    np.int64(-1))
        new_vocabs[col] = gvocab

    return dataclasses.replace(data, id_columns=new_ids), new_vocabs


# ---------------------------------------------------------------------------
# Multi-process fixed-effect dataset (global data-axis feed, re-fed offsets)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class MultiProcessFixedEffectDataset:
    """Fixed-effect data fed once onto the global ``data``-axis mesh; only
    the per-sweep residual offsets are re-fed (the multi-process analog of
    :class:`~photon_ml_tpu.game.data.FixedEffectDataset`'s per-sweep
    ``glm_data``). Rows are this process's owned rows; every process's
    blocks compose into the one global sharded layout.
    """

    coordinate_id: str
    feature_shard_id: str
    design: object
    labels: object
    weights: object
    dim: int
    n_local_rows: int
    n_local_blocks: int
    rows_per_shard: int
    mesh: object
    n_shards: int

    @staticmethod
    def build(coordinate_id: str, game_owned: GameData,
              feature_shard_id: str, mesh,
              *, dense_max_dim: Optional[int] = None,
              design_dtype: str = "float32",
              ) -> "MultiProcessFixedEffectDataset":
        from photon_ml_tpu.game.data import (
            cast_dense_design,
            choose_dense_design_stats,
            design_dtype_of,
        )
        from photon_ml_tpu.parallel.mesh import DATA_AXIS
        from photon_ml_tpu.parallel.multihost import (
            allreduce_max,
            allreduce_sum,
            global_glm_data_multihost,
            local_axis_blocks,
        )

        shard = game_owned.shards[feature_shard_id]
        # layout decision on GLOBAL stats: local (n, nnz) differ per
        # process, and an SPMD program needs every process on one layout.
        # The host cap uses the LARGEST process's local n (the binding
        # host materialization), max-reduced so everyone agrees.
        g = allreduce_sum(np.array([shard.n_samples, shard.nnz], np.int64))
        n_loc = int(allreduce_max(np.array([shard.n_samples], np.int64))[0])
        dense = choose_dense_design_stats(
            int(g[0]), shard.dim, int(g[1]),
            n_shards=int(mesh.shape[DATA_AXIS]), dense_max_dim=dense_max_dim,
            n_local_samples=n_loc,
            itemsize=design_dtype_of(design_dtype).itemsize)
        host_design = host_design_for_shard(shard, force_dense=dense)
        # every process runs the same CLI flags, so the dtype decision is
        # symmetric; the budget reconciliation below is dtype-independent
        host_design = cast_dense_design(host_design, design_dtype)
        local = GLMData(design=host_design, labels=game_owned.labels,
                        offsets=np.zeros(shard.n_samples, np.float32),
                        weights=game_owned.weights)
        fed = global_glm_data_multihost(local, mesh)
        return MultiProcessFixedEffectDataset(
            coordinate_id=coordinate_id, feature_shard_id=feature_shard_id,
            design=fed.design, labels=fed.labels, weights=fed.weights,
            dim=shard.dim, n_local_rows=shard.n_samples,
            n_local_blocks=local_axis_blocks(mesh, DATA_AXIS),
            rows_per_shard=int(fed.labels.shape[1]), mesh=mesh,
            n_shards=int(mesh.shape[DATA_AXIS]))

    def _feed_rowvec(self, local_values) -> object:
        """Place one per-local-row float32 vector into the global
        ``(n_shards, rows_per_shard)`` data-axis layout (tail zero-padded)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from photon_ml_tpu.parallel.mesh import DATA_AXIS

        per = self.rows_per_shard
        buf = np.zeros(self.n_local_blocks * per, np.float32)
        buf[:self.n_local_rows] = np.asarray(local_values, np.float32)
        return jax.make_array_from_process_local_data(
            NamedSharding(self.mesh, P(DATA_AXIS)),
            buf.reshape(self.n_local_blocks, per),
            (self.n_shards, per))

    def glm_data(self, local_offsets, local_weights=None) -> GLMData:
        """Bind this process's residual offsets into the global layout.
        ``local_weights`` (per-sweep down-sampled weights) replaces the
        static weight vector for this solve only."""
        return GLMData(
            design=self.design, labels=self.labels,
            offsets=self._feed_rowvec(local_offsets),
            weights=(self.weights if local_weights is None
                     else self._feed_rowvec(local_weights)))

    def local_scores(self, scores) -> np.ndarray:
        """Pull this process's rows out of a globally-sharded ``(n_shards,
        rows_per_shard)`` score array (drop local tail padding). Shards are
        deduped by data-axis block: on a mesh with extra axes the score
        vector is replicated across them, and counting each replica would
        duplicate rows."""
        by_block = {}
        for s in scores.addressable_shards:
            by_block.setdefault(s.index[0].start or 0, s)
        flat = np.concatenate([np.asarray(by_block[k].data).reshape(-1)
                               for k in sorted(by_block)])
        return flat[:self.n_local_rows]


# ---------------------------------------------------------------------------
# The multi-process coordinate-descent driver
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class MultiProcessGameResult:
    model: GameModel  # identical on every process
    #: this process's rows: global ids and per-coordinate scores
    global_rows: np.ndarray
    scores: dict[str, np.ndarray]
    #: per-sweep validation metric dicts (empty without a validation set) —
    #: identical on every process
    validation_history: list = dataclasses.field(default_factory=list)


# ---------------------------------------------------------------------------
# Sweep-boundary checkpointing (per-process state files)
# ---------------------------------------------------------------------------
#
# The single-process CoordinateDescent checkpoints per coordinate step
# (io/checkpoint.py). Multi-process state is row-partitioned — each
# process's residual scores cover only ITS rows and its random-effect
# tables only ITS entities — so each process persists its own shard
# (proc-<pid>/sweep-<k>.npz, atomic tmp+rename) at every sweep boundary,
# fingerprint-guarded like the single-process manager. Resume agrees on
# min(latest sweep) across processes, so a process that died mid-save
# just replays its last complete sweep. The reference's recovery story is
# the same shape: deterministic re-entry from written models (SURVEY §5.3).


def _mp_ckpt_dir(root: str) -> str:
    import jax

    return os.path.join(root, f"proc-{jax.process_index()}")


def _mp_ckpt_save(root: str, sweep: int, fingerprint: str,
                  scores: Mapping[str, np.ndarray],
                  re_local_models: Mapping[str, RandomEffectModel],
                  fe_models: Mapping[str, FixedEffectModel],
                  validation_history: Sequence[Mapping] = (),
                  trained_projection_cids: frozenset = frozenset()) -> None:
    import json as _json

    d = _mp_ckpt_dir(root)
    os.makedirs(d, exist_ok=True)
    payload: dict[str, np.ndarray] = {}
    if validation_history:
        # per-sweep metric dicts ride along so a resumed run returns the
        # FULL history, not just the sweeps after the resume point
        payload["history"] = np.frombuffer(
            _json.dumps(list(validation_history)).encode("utf-8"), np.uint8)
    for cid, s in scores.items():
        payload[f"score::{cid}"] = np.asarray(s, np.float32)
    for cid, m in re_local_models.items():
        payload[f"rekeys::{cid}"] = m.keys
        payload[f"recoef::{cid}"] = m.coeffs
        if m.variances is not None:
            payload[f"revar::{cid}"] = m.variances
        payload[f"remeta::{cid}"] = np.array(
            [m.dim], np.int64)
        if m.projector is not None and cid in trained_projection_cids:
            # a FACTORED coordinate's projection is TRAINED state (not
            # seed-derived like the RANDOM projector, which the load path
            # reconstructs from config) — it must survive resume or
            # restored latents would score through the initial P
            payload[f"reproj::{cid}"] = np.asarray(
                m.projector.matrix, np.float32)
    for cid, m in fe_models.items():
        payload[f"few::{cid}"] = np.asarray(m.model.coefficients.means,
                                            np.float32)
        v = m.model.coefficients.variances
        if v is not None:
            payload[f"fev::{cid}"] = np.asarray(v, np.float32)
    payload["fingerprint"] = np.frombuffer(
        fingerprint.encode("utf-8"), np.uint8)

    from photon_ml_tpu.resilience import fault_point, retry

    def attempt() -> None:
        tmp = os.path.join(d, f".sweep-{sweep}.npz.tmp")
        with open(tmp, "wb") as f:
            np.savez(f, **payload)
        # crash-mid-write window: payload fully written, rename pending —
        # a kill here must leave the previous sweep as the loadable latest
        fault_point("ckpt.save", step=sweep, path=d, scope="mp")
        os.replace(tmp, os.path.join(d, f"sweep-{sweep}.npz"))

    retry(attempt, name=f"ckpt.save:mp-sweep-{sweep}")
    # prune like the single-process manager (io/checkpoint.py keep=3): a
    # 10M-row score decomposition is ~10s of MB per sweep per process
    kept = sorted(
        (int(n[len("sweep-"):-len(".npz")]) for n in os.listdir(d)
         if n.startswith("sweep-") and n.endswith(".npz")), reverse=True)
    for old in kept[3:]:
        try:
            os.unlink(os.path.join(d, f"sweep-{old}.npz"))
        except OSError:
            pass


def _mp_ckpt_latest(root: str) -> int:
    """Latest complete sweep saved by THIS process (-1: none)."""
    d = _mp_ckpt_dir(root)
    if not os.path.isdir(d):
        return -1
    best = -1
    for name in os.listdir(d):
        if name.startswith("sweep-") and name.endswith(".npz"):
            try:
                best = max(best, int(name[len("sweep-"):-len(".npz")]))
            except ValueError:
                pass
    return best


def _mp_ckpt_load(root: str, sweep: int, fingerprint: str, task,
                  re_templates: Mapping[str, RandomEffectModel],
                  fe_templates: Mapping[str, object]):
    """Restore this process's (scores, re_local_models, fe_models).

    ``re_templates``/``fe_templates`` carry the non-array fields (types,
    shard ids, the seed-derived projector) from the current configuration
    — state files hold arrays only, and a configuration mismatch is
    caught by the fingerprint (which hashes the run shape AND every
    coordinate's configuration repr)."""
    with np.load(os.path.join(_mp_ckpt_dir(root),
                              f"sweep-{sweep}.npz")) as z:
        saved_fp = bytes(z["fingerprint"]).decode("utf-8")
        if saved_fp != fingerprint:
            raise ValueError(
                f"checkpoint fingerprint mismatch under {root!r}: saved "
                f"{saved_fp!r} != current {fingerprint!r} — the run "
                "configuration or row partition changed; delete the "
                "checkpoint directory to start fresh")
        scores = {k[len("score::"):]: z[k] for k in z.files
                  if k.startswith("score::")}
        re_models = {}
        for k in z.files:
            if not k.startswith("rekeys::"):
                continue
            cid = k[len("rekeys::"):]
            t = re_templates[cid]
            if f"reproj::{cid}" in z.files:
                # trained projection (factored coordinate) restored verbatim
                from photon_ml_tpu.game.projector import RandomProjector

                projector = RandomProjector(matrix=z[f"reproj::{cid}"])
            else:
                # seed-derived, identical on every process — must survive
                # resume or a projected-space model would score raw ids
                projector = t.projector
            re_models[cid] = RandomEffectModel(
                random_effect_type=t.random_effect_type,
                feature_shard_id=t.feature_shard_id, task=task,
                dim=int(z[f"remeta::{cid}"][0]),
                keys=z[f"rekeys::{cid}"], coeffs=z[f"recoef::{cid}"],
                variances=(z[f"revar::{cid}"]
                           if f"revar::{cid}" in z.files else None),
                projector=projector)
        fe_models = {}
        for k in z.files:
            if not k.startswith("few::"):
                continue
            cid = k[len("few::"):]
            fe_models[cid] = FixedEffectModel(
                model=GeneralizedLinearModel(
                    coefficients=Coefficients(
                        means=z[k],
                        variances=(z[f"fev::{cid}"]
                                   if f"fev::{cid}" in z.files else None)),
                    task=task),
                feature_shard_id=fe_templates[cid].feature_shard_id)
        history = []
        if "history" in z.files:
            import json as _json

            history = _json.loads(bytes(z["history"]).decode("utf-8"))
    return scores, re_models, fe_models, history


@dataclasses.dataclass(frozen=True)
class _FactoredPlan:
    """Per-process plan for a factored coordinate: owned rows + config (the
    per-alternation datasets rebuild around the trained projection)."""

    cfg: object  # FactoredRandomEffectCoordinateConfig
    game: GameData
    global_rows: np.ndarray
    primary: bool


@dataclasses.dataclass(frozen=True)
class _REPlan:
    config: RandomEffectDatasetConfig
    optimization: GLMOptimizationConfiguration
    #: owned rows for THIS coordinate's entity type
    game: GameData
    global_rows: np.ndarray
    dataset: RandomEffectDataset
    #: True when this coordinate's rows coincide with the primary partition
    primary: bool


def _train_factored_mp(coord, global_rows: np.ndarray, offsets,
                       warm, fe_mesh):
    """Multi-process factored training: the per-entity LATENT solves run
    process-local exactly like any random effect (rows are grouped with
    their owned entities), and the shared-projection update — a GLM in
    ``vec(P)`` — runs as one psum'd global solve over the data mesh, the
    same machinery as the fixed effect. Mirrors
    :meth:`FactoredRandomEffectCoordinate.train` step for step; global row
    ids key the active-bound subsample so dataset builds stay
    partition-invariant."""
    import jax.numpy as jnp

    from photon_ml_tpu.game.coordinate import _factored_projection_cache
    from photon_ml_tpu.game.factored import FactoredDesign
    from photon_ml_tpu.game.projector import RandomProjector
    from photon_ml_tpu.game.random_effect import RandomEffectSolver
    from photon_ml_tpu.parallel.multihost import global_glm_data_multihost

    shard = coord.data.shards[coord.dataset_config.feature_shard_id]
    if warm is not None and warm.projector is not None:
        p = warm.projector.matrix
    else:
        p = RandomProjector.build(
            shard.dim, coord.latent_dim, coord.dataset_config.seed).matrix
    solver = RandomEffectSolver(task=coord.task, config=coord.config,
                                mesh=coord.mesh)
    x_host = shard.to_dense()
    entities = coord.data.id_columns[coord.dataset_config.random_effect_type]
    # one compiled DISTRIBUTED projection solve per (task, config, mesh):
    # the Khatri-Rao design rows shard over the global data mesh and the
    # solve psums, so every process computes the identical shared projection
    run_fn = _factored_projection_cache(
        coord.task, coord.projection_config, fe_mesh)
    offsets_np = np.asarray(offsets, np.float32)
    latent = warm
    fed = None
    for _ in range(max(1, coord.n_factored_iterations)):
        projector = RandomProjector(matrix=p)
        dataset = RandomEffectDataset.build(
            coord.coordinate_id, coord.data, coord._ds_config,
            projector=projector, sample_uids=global_rows)
        latent, _ = solver.train(dataset, offsets_np, coord.lam,
                                 warm_start=latent)
        v = coord._latent_table(latent, entities).astype(np.float32)
        if fed is None:
            # first alternation pays the full budget-reconciled feed; the
            # design's x / labels / weights / offsets are loop-invariant
            # (the single-chip counterpart builds x_dev once the same way),
            # so later alternations re-feed ONLY v
            local = GLMData(
                design=FactoredDesign(x=x_host, v=v,
                                      latent_dim=coord.latent_dim),
                labels=coord.data.labels, offsets=offsets_np,
                weights=coord.data.weights)
            fed = global_glm_data_multihost(local, fe_mesh)
        else:
            fed = dataclasses.replace(
                fed, design=FactoredDesign(
                    x=fed.design.x, v=_feed_stacked(v, fe_mesh,
                                                    fed.labels.shape[1]),
                    latent_dim=coord.latent_dim))
        result = run_fn(fed, jnp.asarray(p.reshape(-1)),
                        jnp.asarray(coord.lam_projection, jnp.float32))
        p = np.asarray(result.w, np.float32).reshape(
            coord.latent_dim, x_host.shape[1])
    # final latent solve so the returned (v, P) pair is consistent
    projector = RandomProjector(matrix=p)
    dataset = RandomEffectDataset.build(
        coord.coordinate_id, coord.data, coord._ds_config,
        projector=projector, sample_uids=global_rows)
    latent, _ = solver.train(dataset, offsets_np, coord.lam,
                             warm_start=latent)
    return latent, np.asarray(latent.score(coord.data), np.float32)


def _feed_stacked(a: np.ndarray, mesh, per: int):
    """Place one per-local-row array (trailing dims preserved) into the
    mesh's global data-axis layout at an already-agreed ``per`` — the
    cheap re-feed for loop-varying leaves (the factored solve's v).

    LAYOUT CONTRACT with ``parallel.distributed.shard_glm_data``: local
    rows fill CONTIGUOUSLY with zero padding at the tail, then reshape to
    ``(n_local_blocks, per, ...)`` row-major. The re-fed leaf must align
    row-for-row with the labels/weights blocks the first full feed built;
    if shard_glm_data's stacking ever changes, this helper must change
    with it (a mismatch would silently scramble rows)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from photon_ml_tpu.parallel.mesh import DATA_AXIS
    from photon_ml_tpu.parallel.multihost import local_axis_blocks

    a = np.asarray(a, np.float32)
    n_local = local_axis_blocks(mesh, DATA_AXIS)
    buf = np.zeros((n_local * per,) + a.shape[1:], np.float32)
    buf[:a.shape[0]] = a
    return jax.make_array_from_process_local_data(
        NamedSharding(mesh, P(DATA_AXIS)),
        buf.reshape((n_local, per) + a.shape[1:]),
        (int(mesh.shape[DATA_AXIS]), per) + a.shape[1:])


def _allgather_rowvec(global_rows: np.ndarray, values: np.ndarray,
                      n_global: int) -> np.ndarray:
    """Assemble a replicated global row vector from per-process slices."""
    from photon_ml_tpu.parallel.multihost import allgather_concat

    rows = allgather_concat(np.asarray(global_rows, np.int64))
    vals = allgather_concat(np.asarray(values, np.float32))
    out = np.zeros(n_global, np.float32)
    out[rows] = vals
    return out


def train_game_multiprocess(
    game_local: GameData,
    task: TaskType,
    coordinate_configs: Mapping[str, object],
    update_sequence: Sequence[str],
    lam: Mapping[str, float],
    n_cd_iterations: int = 1,
    fe_mesh=None,
    re_mesh=None,
    checkpoint_dir: Optional[str] = None,
    resume: bool = False,
    initial_models: Optional[Mapping[str, object]] = None,
    locked: Sequence[str] = (),
    validation: Optional[tuple] = None,
    guard=None,  # Optional[photon_ml_tpu.resilience.DivergenceGuard]
) -> MultiProcessGameResult:
    """Run GAME coordinate descent across all processes.

    ``game_local`` is THIS process's row shard (any partition — e.g. its
    host-local Avro files); ``coordinate_configs`` maps coordinate id to
    :class:`~photon_ml_tpu.game.estimator.FixedEffectCoordinateConfig` or
    :class:`~photon_ml_tpu.game.estimator.RandomEffectCoordinateConfig`.
    The primary row partition follows the FIRST random-effect coordinate in
    ``update_sequence`` (additional RE types exchange residuals per sweep);
    with no random effects, rows stay on their reading process.

    ``fe_mesh`` must be a global mesh with a ``data`` axis (default:
    :func:`~photon_ml_tpu.parallel.multihost.make_multihost_mesh`);
    ``re_mesh`` an optional LOCAL mesh with an ``entity`` axis for the
    per-process bucket solves.

    ``initial_models``/``locked`` are the reference's partial-retrain path,
    with single-process semantics: every process holds the (identical,
    loaded-from-disk) initial models, scores are seeded row-locally, locked
    coordinates keep their model and are never retrained. ``validation``
    (``(GameData, evaluators)``; the validation data must be read in full
    on EVERY process) enables per-sweep validation tracking: the global
    model is assembled at each sweep boundary and evaluated — identical on
    every process since model and data are. History is in the result.

    ``guard`` (a :class:`~photon_ml_tpu.resilience.DivergenceGuard`)
    enables divergence rollback: each coordinate step's NaN/Inf verdict is
    allreduce-maxed so every process rolls back (bumping the coordinate's
    regularization) or freezes the coordinate in lockstep. Fault plans in
    multi-process runs must be seeded identically on every process —
    injected faults then fire symmetrically, which is what keeps the
    collective schedule aligned through a recovery.
    """
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.game.coordinate import (
        RandomEffectCoordinate,
        _fixed_train_fn_dist,
    )
    from photon_ml_tpu.game.estimator import (
        FactoredRandomEffectCoordinateConfig,
        FixedEffectCoordinateConfig,
        RandomEffectCoordinateConfig,
    )
    from photon_ml_tpu.parallel.multihost import (
        allgather_concat,
        allreduce_max,
        allreduce_sum,
        make_multihost_mesh,
    )

    n_proc = jax.process_count()
    locked = set(locked)
    initial_models = dict(initial_models or {})
    for cid in locked:
        if cid not in initial_models:
            raise KeyError(f"locked coordinate {cid!r} needs an initial model")
    missing_seq = locked - set(update_sequence)
    if missing_seq:
        # single-process semantics (GameEstimator._check_sequence): a locked
        # coordinate outside the sequence would silently drop from the model
        raise ValueError(
            f"locked coordinates {sorted(missing_seq)} must appear in the "
            f"update sequence")
    for cid in update_sequence:
        if cid not in coordinate_configs and cid not in locked:
            raise KeyError(f"update sequence names unknown coordinate {cid!r}")

    n_local = game_local.n_samples
    # one gather yields both the global row count and this process's base
    counts = allgather_concat(np.array([n_local], np.int64))
    n_global = int(counts.sum())
    base = int(np.concatenate([[0], np.cumsum(counts)])[jax.process_index()])
    local_global_rows = base + np.arange(n_local, dtype=np.int64)

    # --- entity partitions: one owner map per RE entity type --------------
    # locked coordinates never train, so they need no dataset build, no
    # entity partition, and no say in the primary row partition
    re_types = [coordinate_configs[cid].dataset.random_effect_type
                for cid in update_sequence
                if cid not in locked
                and isinstance(coordinate_configs[cid],
                               (RandomEffectCoordinateConfig,
                                FactoredRandomEffectCoordinateConfig))]
    owner_by_type: dict[str, np.ndarray] = {}
    for t in dict.fromkeys(re_types):  # ordered unique
        ents = game_local.id_columns[t]
        n_ent = int(allreduce_max(
            np.array([ents.max() + 1 if len(ents) else 0], np.int64))[0])
        counts = allreduce_sum(np.bincount(
            ents[ents >= 0], minlength=max(n_ent, 1)).astype(np.int64))
        owner_by_type[t] = balanced_entity_partition(counts, n_proc)

    # --- primary row partition + shuffle ----------------------------------
    primary_type = re_types[0] if re_types else None
    if primary_type is None:
        # fixed-effects only: rows stay where they were read — no shuffle
        game_primary, primary_rows = game_local, local_global_rows
    else:
        # ship only what the primary-partition coordinates read: fixed
        # shards + the primary RE coordinate's shard and entity column
        # (non-primary coordinates run their own slim exchange below)
        need_shards = set()
        for cid in update_sequence:
            if cid in locked:
                continue
            cfg = coordinate_configs[cid]
            if isinstance(cfg, FixedEffectCoordinateConfig):
                need_shards.add(cfg.feature_shard_id)
            elif (isinstance(cfg, (RandomEffectCoordinateConfig,
                                   FactoredRandomEffectCoordinateConfig))
                  and cfg.dataset.random_effect_type == primary_type):
                need_shards.add(cfg.dataset.feature_shard_id)
        slim_primary = GameData(
            labels=game_local.labels, offsets=game_local.offsets,
            weights=game_local.weights,
            shards={k: v for k, v in game_local.shards.items()
                    if k in need_shards},
            id_columns={primary_type: game_local.id_columns[primary_type]})
        dest = owner_of_rows(game_local.id_columns[primary_type],
                             owner_by_type[primary_type],
                             local_global_rows, n_proc)
        game_primary, primary_rows = exchange_rows(slim_primary, dest)

    # --- per-coordinate builds --------------------------------------------
    if fe_mesh is None:
        fe_mesh = make_multihost_mesh()
    fe_datasets: dict[str, MultiProcessFixedEffectDataset] = {}
    re_plans: dict[str, _REPlan] = {}
    factored_plans: dict[str, _FactoredPlan] = {}
    for cid in update_sequence:
        if cid in locked:
            continue  # frozen: no dataset, scores seeded from the model
        cfg = coordinate_configs[cid]
        if isinstance(cfg, FixedEffectCoordinateConfig):
            # (downsamplers are supported: the per-sweep draw is the keyed
            # per-global-row-id hash, identical under any row partition)
            fe_datasets[cid] = MultiProcessFixedEffectDataset.build(
                cid, game_primary, cfg.feature_shard_id, fe_mesh,
                design_dtype=cfg.design_dtype)
        elif isinstance(cfg, (RandomEffectCoordinateConfig,
                              FactoredRandomEffectCoordinateConfig)):
            t = cfg.dataset.random_effect_type
            if t == primary_type:
                game_c, rows_c, is_primary = game_primary, primary_rows, True
            else:
                # exchange only what this coordinate reads — its feature
                # shard and entity column — not the whole dataset (the
                # allgather otherwise ships every shard to every process)
                slim = GameData(
                    labels=game_local.labels, offsets=game_local.offsets,
                    weights=game_local.weights,
                    shards={cfg.dataset.feature_shard_id:
                            game_local.shards[cfg.dataset.feature_shard_id]},
                    id_columns={t: game_local.id_columns[t]})
                dest_c = owner_of_rows(
                    game_local.id_columns[t], owner_by_type[t],
                    local_global_rows, n_proc)
                game_c, rows_c = exchange_rows(slim, dest_c)
                is_primary = False
            if isinstance(cfg, FactoredRandomEffectCoordinateConfig):
                # latent solves are process-local like any random effect;
                # datasets rebuild per alternation (the projector is the
                # trained object), so the plan carries data, not a dataset
                factored_plans[cid] = _FactoredPlan(
                    cfg=cfg, game=game_c, global_rows=rows_c,
                    primary=is_primary)
                continue
            # rows of owned entities are complete here by construction, so
            # the per-process dataset covers exactly its entities; global
            # row ids key the active-bound subsample draw so the kept
            # subset matches the single-process build exactly
            ds = RandomEffectDataset.build(cid, game_c, cfg.dataset,
                                           sample_uids=rows_c)
            re_plans[cid] = _REPlan(
                config=cfg.dataset, optimization=cfg.optimization,
                game=game_c, global_rows=rows_c, dataset=ds,
                primary=is_primary)
        else:
            raise TypeError(
                f"coordinate {cid!r}: multi-process training supports fixed, "
                f"random, and factored random effects "
                f"(got {type(cfg).__name__})")

    # --- coordinate descent with row-local score accounting ---------------
    scores: dict[str, np.ndarray] = {
        cid: np.zeros(len(primary_rows), np.float32)
        for cid in update_sequence}
    models: dict[str, object] = {}
    re_local_models: dict[str, RandomEffectModel] = {}

    # seed from initial models (partial-retrain warm start; single-process
    # CD semantics): scores computed ROW-LOCALLY on the original read
    # partition — game_local holds every shard/id column, where the slim
    # primary exchange ships only what training reads — then mapped onto
    # the primary partition through the replicated global vector
    for cid, m0 in initial_models.items():
        if cid not in update_sequence:
            continue
        models[cid] = m0
        if isinstance(m0, RandomEffectModel) and cid not in locked:
            # the GLOBAL table warm-starts the local solves (the bucket →
            # key-table join handles the superset transparently)
            re_local_models[cid] = m0
        sc_local = np.asarray(m0.score(game_local), np.float32)
        g = _allgather_rowvec(local_global_rows, sc_local, n_global)
        scores[cid] = g[primary_rows].astype(np.float32)

    start_sweep = 0
    fingerprint = None
    resumed_history: list = []
    if checkpoint_dir is not None:
        import hashlib
        import json

        fingerprint = hashlib.sha1(json.dumps({
            "n_proc": n_proc,
            "task": str(task),
            "sequence": list(update_sequence),
            "lam": sorted((c, float(lam.get(c, 0.0)))
                          for c in update_sequence),
            # every coordinate's full configuration (optimizer, bounds,
            # regularization, shard ids) — resuming under a changed config
            # must fail loudly, not blend incompatible state
            "configs": {c: repr(coordinate_configs.get(c))
                        for c in update_sequence},
            "locked": sorted(locked),
            # resuming under different seed models must fail loudly too
            "initial": {c: hashlib.sha1(np.asarray(
                m.coeffs if isinstance(m, RandomEffectModel)
                else m.model.coefficients.means,
                np.float32).tobytes()).hexdigest()
                for c, m in sorted(initial_models.items())},
            "n_global": n_global,
            "rows": hashlib.sha1(
                np.ascontiguousarray(primary_rows).tobytes()).hexdigest(),
        }, sort_keys=True).encode()).hexdigest()
        if resume:
            # every process resumes from the newest sweep ALL of them
            # completed (a process that died mid-save replays its last
            # complete one)
            latest = -allreduce_max(
                np.array([-_mp_ckpt_latest(checkpoint_dir)], np.int64))
            agreed = int(latest[0])
            if agreed >= 0:
                re_templates = {
                    cid: RandomEffectModel(
                        random_effect_type=p.config.random_effect_type,
                        feature_shard_id=p.config.feature_shard_id,
                        task=task, dim=0, keys=np.zeros(0, np.int64),
                        coeffs=np.zeros(0, np.float32),
                        projector=p.dataset.projector)
                    for cid, p in re_plans.items()}
                re_templates.update({
                    cid: RandomEffectModel(
                        random_effect_type=p.cfg.dataset.random_effect_type,
                        feature_shard_id=p.cfg.dataset.feature_shard_id,
                        task=task, dim=0, keys=np.zeros(0, np.int64),
                        coeffs=np.zeros(0, np.float32),
                        projector=None)  # learned P rides in the state file
                    for cid, p in factored_plans.items()})
                from photon_ml_tpu.resilience import retry as _retry

                (saved_scores, saved_re, fe_models,
                 resumed_history) = _retry(
                    lambda: _mp_ckpt_load(
                        checkpoint_dir, agreed, fingerprint, task,
                        re_templates, fe_datasets),
                    name=f"ckpt.restore:mp-sweep-{agreed}")
                re_local_models.update(saved_re)
                scores.update(saved_scores)
                models.update(fe_models)
                # the RE coordinates' contribution to the GLOBAL model also
                # comes back from the local tables at assembly time below
                start_sweep = agreed + 1
                logger.info("mp resumed from checkpoint sweep %d", agreed)

    total = game_primary.offsets.astype(np.float32) + sum(
        scores[cid] for cid in update_sequence)

    # memo for the assembly: the final model after the last sweep is the
    # same object the last validation step assembled — don't repeat the
    # RE-table allgathers. Cleared whenever any coordinate trains.
    assembled_memo: list = []

    def _assemble_global_model() -> GameModel:
        """Allgather the per-process RE tables into the (identical on every
        process) global model — at sweep boundaries when validation tracks
        per-sweep metrics, and once at the end."""
        if assembled_memo:
            return assembled_memo[0]
        out = dict(models)
        for cid, local_model in re_local_models.items():
            if local_model is initial_models.get(cid):
                continue  # still the seeded global table — nothing local
            keys = allgather_concat(local_model.keys)
            coeffs = allgather_concat(local_model.coeffs)
            has_var = local_model.variances is not None
            variances = (allgather_concat(local_model.variances)
                         if has_var else None)
            order = np.argsort(keys, kind="stable")
            out[cid] = RandomEffectModel(
                random_effect_type=local_model.random_effect_type,
                feature_shard_id=local_model.feature_shard_id,
                task=task, dim=local_model.dim,
                keys=keys[order], coeffs=coeffs[order],
                variances=None if variances is None else variances[order],
                # RANDOM-projected models keep their (shared, seed-derived —
                # identical on every process) projector so scoring still
                # maps shard features into the projected key space
                projector=local_model.projector)
        gm = GameModel(
            coordinates={cid: out[cid] for cid in update_sequence},
            task=task)
        assembled_memo.append(gm)
        return gm

    validation_history: list[dict] = list(resumed_history)
    lam = dict(lam)  # guard retries bump a coordinate's weight in place
    for sweep in range(start_sweep, n_cd_iterations):
        heartbeat("mp.sweep")
        fault_point("worker.stall", sweep=sweep)
        for cid in update_sequence:
            heartbeat("mp.step")
            if cid in locked:
                continue  # frozen: scores stay as seeded
            if (guard is not None and cid in guard.frozen
                    and (cid in models or cid in re_local_models)):
                # diverged earlier THIS run: locked at last good model (a
                # fresh run sharing the guard — the next grid point —
                # retrains under its new regularization)
                continue
            cfg = coordinate_configs[cid]
            while True:
                residual = total - scores[cid]
                prev_fe = models.get(cid)
                prev_re = re_local_models.get(cid)
                step_error = None
                new_model = None
                new_scores = None
                try:
                    if cid in fe_datasets:
                        ds = fe_datasets[cid]
                        w_sweep = None
                        if cfg.downsampler is not None:
                            # keyed per-global-row-id draw: the kept set is
                            # a pure per-row function, so every partition of
                            # the rows — including the single-process run —
                            # samples identically
                            w_sweep = cfg.downsampler.downsample(
                                game_primary.labels, game_primary.weights,
                                sweep=sweep, uids=primary_rows)
                        data = ds.glm_data(residual, local_weights=w_sweep)
                        w0 = (jnp.zeros((ds.dim,), jnp.float32)
                              if cid not in models else
                              jnp.asarray(models[cid].model.coefficients.means))
                        train_fn = _fixed_train_fn_dist(
                            task, cfg.optimization, fe_mesh)
                        result, variances, g_scores = train_fn(
                            data, w0,
                            jnp.asarray(lam.get(cid, 0.0), jnp.float32))
                        new_scores = ds.local_scores(g_scores)
                        models[cid] = new_model = FixedEffectModel(
                            model=GeneralizedLinearModel(
                                coefficients=Coefficients(
                                    means=np.asarray(result.w),
                                    variances=(None if variances is None
                                               else np.asarray(variances))),
                                task=task),
                            feature_shard_id=ds.feature_shard_id)
                    else:
                        plan = re_plans.get(cid) or factored_plans[cid]
                        if plan.primary:
                            res_c = residual
                        else:
                            # residuals live on primary owners; this
                            # coordinate's rows live on ITS entity owners —
                            # exchange via the replicated global vector (the
                            # reference's score join)
                            g_res = _allgather_rowvec(primary_rows, residual,
                                                      n_global)
                            res_c = g_res[plan.global_rows]
                        if cid in re_plans:
                            coord = RandomEffectCoordinate(
                                coordinate_id=cid, dataset=plan.dataset,
                                data=plan.game, task=task,
                                config=plan.optimization,
                                lam=lam.get(cid, 0.0), mesh=re_mesh,
                                design_dtype=getattr(coordinate_configs[cid],
                                                     "design_dtype",
                                                     "float32"))
                            model_c, scores_c = coord.train(
                                res_c, re_local_models.get(cid), sweep=sweep)
                        else:
                            from photon_ml_tpu.game.factored import (
                                FactoredRandomEffectCoordinate,
                            )

                            fcfg = plan.cfg
                            fcoord = FactoredRandomEffectCoordinate(
                                coordinate_id=cid, data=plan.game,
                                dataset_config=fcfg.dataset, task=task,
                                config=fcfg.optimization,
                                projection_config=fcfg.projection_optimization,
                                lam=lam.get(cid, 0.0),
                                lam_projection=fcfg.lam_projection,
                                n_factored_iterations=fcfg.n_factored_iterations,
                                mesh=re_mesh)
                            model_c, scores_c = _train_factored_mp(
                                fcoord, plan.global_rows, res_c,
                                re_local_models.get(cid), fe_mesh)
                        re_local_models[cid] = new_model = model_c
                        sc = np.asarray(scores_c, np.float32)
                        if plan.primary:
                            new_scores = sc
                        else:
                            g_sc = _allgather_rowvec(plan.global_rows, sc,
                                                     n_global)
                            new_scores = g_sc[primary_rows]
                    new_scores = fault_value("optimizer.step", new_scores,
                                             coordinate=cid, sweep=sweep)
                except Exception as e:
                    if guard is None:
                        raise
                    # deterministic faults raise SYMMETRICALLY (the plan's
                    # decisions are a pure function of seeded counters), so
                    # every process lands here together and the verdict
                    # collective below stays aligned
                    step_error = e
                if guard is None:
                    break
                # the guard verdict is COLLECTIVE: a local isfinite could
                # differ across row shards, and a split verdict would
                # desync every later collective — allreduce_max so all
                # processes roll back (or not) in lockstep
                local_ok = (step_error is None
                            and guard.healthy(new_model, new_scores))
                bad = int(allreduce_max(
                    np.array([0 if local_ok else 1], np.int64))[0]) > 0
                if not bad:
                    break
                # roll back this coordinate's in-process state to the last
                # good model (identical on every process, like the verdict)
                if prev_fe is None:
                    models.pop(cid, None)
                else:
                    models[cid] = prev_fe
                if prev_re is None:
                    re_local_models.pop(cid, None)
                else:
                    re_local_models[cid] = prev_re
                action = guard.on_divergence(
                    cid, sweep=sweep,
                    has_good_model=(prev_fe is not None
                                    or prev_re is not None
                                    or cid in initial_models),
                    error=step_error)
                if action == "freeze":
                    new_scores = None
                    break
                lam[cid] = guard.next_lam(lam.get(cid, 0.0))
            if new_scores is None:
                continue  # frozen mid-sweep: nothing to commit
            assembled_memo.clear()  # model state changed
            total = residual + new_scores
            scores[cid] = new_scores
            logger.info("mp sweep %d coordinate %s done", sweep, cid)
        if validation is not None:
            # per-sweep validation tracking (single-process CD semantics:
            # CoordinateDescent evaluates every sweep). Model and
            # validation data are identical on every process, so each
            # evaluates independently and identically — no collective.
            from photon_ml_tpu.evaluation import evaluate_all

            vdata, evaluators = validation
            gm = _assemble_global_model()
            results = evaluate_all(
                evaluators, gm.score(vdata), vdata.labels,
                weights=vdata.weights, id_tags=vdata.id_columns)
            validation_history.append(results.as_dict())
            logger.info("mp sweep %d validation: %s", sweep, results)
        if checkpoint_dir is not None:
            # saved AFTER the sweep's validation entry so a resume returns
            # the full per-sweep history, not just the post-resume tail
            _mp_ckpt_save(checkpoint_dir, sweep, fingerprint, scores,
                          {cid: m for cid, m in re_local_models.items()
                           if m is not initial_models.get(cid)},
                          {cid: m for cid, m in models.items()
                           if cid in fe_datasets},
                          validation_history=validation_history,
                          trained_projection_cids=frozenset(factored_plans))
        # fleet-metrics fold point. COLLECTIVE when --metrics-port installed
        # the fold hook: every process reaches this line once per sweep (the
        # loop above is already collective-symmetric), so the allgather
        # inside the hook stays aligned. No hook (the default) is a no-op.
        from photon_ml_tpu.telemetry.aggregate import sweep_boundary

        sweep_boundary(sweep=sweep)

    # --- model assembly: allgather RE tables ------------------------------
    model = _assemble_global_model()
    return MultiProcessGameResult(
        model=model, global_rows=primary_rows, scores=scores,
        validation_history=validation_history)
