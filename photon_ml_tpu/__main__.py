"""Subcommand dispatch: ``python -m photon_ml_tpu <driver> [args...]``.

The four reference entry points (SURVEY.md §2.5) under one module runner —
``train_glm``, ``train_game``, ``score_game``, ``build_index`` — plus
``serve_game`` (online serving) and ``refresh_game`` (the continuous-
training incremental refresh), neither of which the reference shipped.
"""

from __future__ import annotations

import sys

_DRIVERS = {
    "train_glm": "photon_ml_tpu.cli.train_glm",
    "train_game": "photon_ml_tpu.cli.train_game",
    "refresh_game": "photon_ml_tpu.cli.refresh_game",
    "join_feedback": "photon_ml_tpu.cli.join_feedback",
    "score_game": "photon_ml_tpu.cli.score_game",
    "serve_game": "photon_ml_tpu.cli.serve_game",
    "serve_fleet": "photon_ml_tpu.cli.serve_fleet",
    "build_index": "photon_ml_tpu.cli.build_index",
}


def main(argv=None) -> None:
    argv = sys.argv[1:] if argv is None else list(argv)
    if not argv or argv[0] in ("-h", "--help") or argv[0] not in _DRIVERS:
        names = ", ".join(_DRIVERS)
        print(f"usage: python -m photon_ml_tpu {{{names}}} [options]\n"
              f"run a driver with -h for its options")
        raise SystemExit(0 if argv and argv[0] in ("-h", "--help") else 2)
    import importlib

    driver = importlib.import_module(_DRIVERS[argv[0]])
    result = driver.run(argv[1:])
    if result:
        print(result)


if __name__ == "__main__":
    main()
