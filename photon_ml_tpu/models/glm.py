"""Generalized linear models: task-typed coefficient containers that score data.

Re-design of the reference's model hierarchy
(``photon-api/.../supervised/classification/LogisticRegressionModel.scala``,
``supervised/regression/{LinearRegressionModel, PoissonRegressionModel}.scala``,
``SmoothedHingeLossLinearSVMModel`` and the ``GeneralizedLinearModel`` base).

One pytree dataclass parameterized by :class:`photon_ml_tpu.types.TaskType`
instead of a subclass tree: the task selects the pointwise loss / inverse link,
and scoring is a pure function usable inside jit. Factory helpers carry the
reference class names for discoverability.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.ops.design import Design
from photon_ml_tpu.ops.losses import PointwiseLoss, loss_for_task
from photon_ml_tpu.types import TaskType

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class GeneralizedLinearModel:
    """A trained GLM: coefficients plus the task that interprets them."""

    coefficients: Coefficients
    task: TaskType = dataclasses.field(metadata=dict(static=True))

    @property
    def dim(self) -> int:
        return self.coefficients.dim

    @property
    def loss(self) -> PointwiseLoss:
        return loss_for_task(self.task)

    # --- scoring ----------------------------------------------------------
    def score(self, design: Design, offsets: Array | float = 0.0) -> Array:
        """Raw margins ``X @ w + offset`` — what GAME coordinate accounting
        sums across coordinates (reference ``DatumScoringModel.score``)."""
        return design.matvec(self.coefficients.means) + offsets

    def predict_mean(self, design: Design, offsets: Array | float = 0.0) -> Array:
        """Response-scale predictions (sigmoid / identity / exp per task),
        the reference's ``computeMeanFunction``."""
        return self.loss.mean(self.score(design, offsets))

    def with_coefficients(self, coefficients: Coefficients) -> "GeneralizedLinearModel":
        return dataclasses.replace(self, coefficients=coefficients)


def logistic_regression_model(coefficients: Coefficients) -> GeneralizedLinearModel:
    """Reference: ``supervised/classification/LogisticRegressionModel.scala``."""
    return GeneralizedLinearModel(coefficients, TaskType.LOGISTIC_REGRESSION)


def linear_regression_model(coefficients: Coefficients) -> GeneralizedLinearModel:
    """Reference: ``supervised/regression/LinearRegressionModel.scala``."""
    return GeneralizedLinearModel(coefficients, TaskType.LINEAR_REGRESSION)


def poisson_regression_model(coefficients: Coefficients) -> GeneralizedLinearModel:
    """Reference: ``supervised/regression/PoissonRegressionModel.scala``."""
    return GeneralizedLinearModel(coefficients, TaskType.POISSON_REGRESSION)


def smoothed_hinge_loss_linear_svm_model(coefficients: Coefficients) -> GeneralizedLinearModel:
    """Reference: ``supervised/classification/SmoothedHingeLossLinearSVMModel.scala``."""
    return GeneralizedLinearModel(coefficients, TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM)
