from photon_ml_tpu.models.coefficients import Coefficients  # noqa: F401
from photon_ml_tpu.models.glm import (  # noqa: F401
    GeneralizedLinearModel,
    linear_regression_model,
    logistic_regression_model,
    poisson_regression_model,
    smoothed_hinge_loss_linear_svm_model,
)
