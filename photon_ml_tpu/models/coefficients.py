"""Model coefficients: means plus optional posterior variances.

Re-design of the reference's ``photon-api/.../model/Coefficients.scala``:
a coefficient vector (the GLM weights) and, when variance computation is
enabled (``VarianceComputationType`` SIMPLE/FULL), a per-coefficient variance
vector — together the "Bayesian linear model" the reference writes as
``BayesianLinearModelAvro``.

A frozen pytree dataclass so it flows freely through jit/vmap/shard_map; the
`variances` leaf is optional (None when variance computation is off).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class Coefficients:
    """GLM coefficients: ``means`` ``(d,)``, optional ``variances`` ``(d,)``."""

    means: Array
    variances: Optional[Array] = None

    @property
    def dim(self) -> int:
        return self.means.shape[-1]

    @staticmethod
    def zeros(dim: int, dtype=jnp.float32) -> "Coefficients":
        return Coefficients(means=jnp.zeros((dim,), dtype=dtype))

    def with_variances(self, variances: Optional[Array]) -> "Coefficients":
        return dataclasses.replace(self, variances=variances)

    def norm(self) -> Array:
        return jnp.linalg.norm(self.means)

    def nnz(self, eps: float = 0.0) -> Array:
        """Count of active (non-zero beyond ``eps``) coefficients — the
        quantity the reference's model-sparsity-threshold option reports."""
        return jnp.sum(jnp.abs(self.means) > eps)

    def sparsify(self, threshold: float) -> "Coefficients":
        """Zero out coefficients with ``|w_j| < threshold`` (the GAME driver's
        ``model-sparsity-threshold`` post-processing)."""
        keep = jnp.abs(self.means) >= threshold
        means = jnp.where(keep, self.means, 0.0)
        variances = None if self.variances is None else jnp.where(keep, self.variances, 0.0)
        return Coefficients(means=means, variances=variances)
