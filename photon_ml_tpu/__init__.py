"""photon-ml-tpu: a TPU-native framework with the capabilities of Photon-ML.

A from-scratch JAX/XLA/Pallas re-design of LinkedIn Photon-ML
(reference: lazycrazyowl/photon-ml, a fork of linkedin/photon-ml):
large-scale Generalized Linear Models (logistic / linear / Poisson /
smoothed-hinge SVM with L1/L2/elastic-net) and GAME/GLMix mixed-effect
models, built TPU-first:

- per-shard math as pure jittable functions; gradients and Hessian-vector
  products derived by autodiff (replacing the reference's hand-written
  aggregators in ``photon-api/.../function/glm/*Aggregator.scala``),
- L-BFGS / OWLQN / TRON as ``lax.while_loop``-compiled on-device optimizers
  (replacing breeze-backed ``photon-lib/.../optimization/{LBFGS,OWLQN,TRON}.scala``),
- data-parallel reductions via ``psum`` over ICI on a ``jax.sharding.Mesh``
  (replacing ``RDD.treeAggregate``),
- entity-sharded ``vmap``-batched local solves for random effects
  (replacing per-executor training in
  ``photon-api/.../algorithm/RandomEffectCoordinate.scala``).

Citation convention: docstrings cite reference files by repo-relative path.
At survey time the reference mount was empty, so line numbers are
deliberately omitted (see SURVEY.md provenance caveat).
"""

__version__ = "0.1.0"

from photon_ml_tpu.types import (  # noqa: F401
    DataValidationType,
    NormalizationType,
    OptimizerType,
    RegularizationType,
    TaskType,
    VarianceComputationType,
)

# NOTE: lazy imports keep `import photon_ml_tpu` light (no jax import until
# a submodule is touched); these are the supported public entry points.
_PUBLIC = {
    # core math
    "GLMData": "photon_ml_tpu.ops.objective",
    "GLMObjective": "photon_ml_tpu.ops.objective",
    "DenseDesign": "photon_ml_tpu.ops.design",
    "CsrDesign": "photon_ml_tpu.ops.design",
    "ChunkedSparseDesign": "photon_ml_tpu.ops.design",
    "loss_for_task": "photon_ml_tpu.ops.losses",
    # optimizers
    "OptimizerConfig": "photon_ml_tpu.optimize",
    "OptimizerResult": "photon_ml_tpu.optimize",
    "minimize_lbfgs": "photon_ml_tpu.optimize",
    "minimize_owlqn": "photon_ml_tpu.optimize",
    "minimize_tron": "photon_ml_tpu.optimize",
    # GLM training
    "GLMOptimizationConfiguration": "photon_ml_tpu.glm",
    "train_glm_sweep": "photon_ml_tpu.glm",
    # GAME
    "GameData": "photon_ml_tpu.game",
    "GameEstimator": "photon_ml_tpu.game",
    "GameOptimizationConfiguration": "photon_ml_tpu.game",
    "GameTransformer": "photon_ml_tpu.game",
    "GameModel": "photon_ml_tpu.game",
    "CoordinateDescent": "photon_ml_tpu.game",
    "RandomEffectDatasetConfig": "photon_ml_tpu.game",
    # evaluation
    "parse_evaluators": "photon_ml_tpu.evaluation",
    "evaluate_all": "photon_ml_tpu.evaluation",
    # IO
    "AvroDataReader": "photon_ml_tpu.io",
    "save_game_model": "photon_ml_tpu.io",
    "load_game_model": "photon_ml_tpu.io",
    # parallel
    "make_mesh": "photon_ml_tpu.parallel",
    "DistributedGLMObjective": "photon_ml_tpu.parallel",
    "FeatureShardedGLMObjective": "photon_ml_tpu.parallel",
}

__all__ = sorted(_PUBLIC) + [
    "DataValidationType", "NormalizationType", "OptimizerType",
    "RegularizationType", "TaskType", "VarianceComputationType",
]


def __getattr__(name: str):
    target = _PUBLIC.get(name)
    if target is None:
        raise AttributeError(f"module 'photon_ml_tpu' has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(target), name)


def __dir__():  # PEP 562 pairing: expose lazy names to dir()/completion
    return sorted(set(__all__) | set(globals()))
