"""photon-ml-tpu: a TPU-native framework with the capabilities of Photon-ML.

A from-scratch JAX/XLA/Pallas re-design of LinkedIn Photon-ML
(reference: lazycrazyowl/photon-ml, a fork of linkedin/photon-ml):
large-scale Generalized Linear Models (logistic / linear / Poisson /
smoothed-hinge SVM with L1/L2/elastic-net) and GAME/GLMix mixed-effect
models, built TPU-first:

- per-shard math as pure jittable functions; gradients and Hessian-vector
  products derived by autodiff (replacing the reference's hand-written
  aggregators in ``photon-api/.../function/glm/*Aggregator.scala``),
- L-BFGS / OWLQN / TRON as ``lax.while_loop``-compiled on-device optimizers
  (replacing breeze-backed ``photon-lib/.../optimization/{LBFGS,OWLQN,TRON}.scala``),
- data-parallel reductions via ``psum`` over ICI on a ``jax.sharding.Mesh``
  (replacing ``RDD.treeAggregate``),
- entity-sharded ``vmap``-batched local solves for random effects
  (replacing per-executor training in
  ``photon-api/.../algorithm/RandomEffectCoordinate.scala``).

Citation convention: docstrings cite reference files by repo-relative path.
At survey time the reference mount was empty, so line numbers are
deliberately omitted (see SURVEY.md provenance caveat).
"""

__version__ = "0.1.0"

from photon_ml_tpu.types import TaskType  # noqa: F401
