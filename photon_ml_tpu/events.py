"""Training lifecycle event bus.

Re-design of the reference's event layer (``photon-client/.../event/...`` ⚠
SURVEY.md §2.5 — lifecycle events consumed by LinkedIn-internal listeners):
a tiny synchronous pub/sub bus the drivers post stage events to, so external
integrations (metrics exporters, progress UIs, experiment trackers) can
observe a run without the framework depending on them.

Listeners are plain callables ``(TrainingEvent) -> None``; a listener
exception is logged and swallowed (an observer must never kill a training
run — same contract as the reference's fire-and-forget event bus).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Any, Callable, Mapping

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class TrainingEvent:
    """One lifecycle notification.

    Standard event names posted by the drivers (mirroring the reference's
    lifecycle):

    - ``training_started`` / ``training_finished``
    - ``stage_started`` / ``stage_finished`` (payload: ``stage``)
    - ``configuration_evaluated`` (payload: config index, evaluation dict)
    - ``model_saved`` (payload: output path)
    """

    name: str
    payload: Mapping[str, Any] = dataclasses.field(default_factory=dict)
    timestamp: float = dataclasses.field(default_factory=time.time)


Listener = Callable[[TrainingEvent], None]


class EventBus:
    """Synchronous in-process pub/sub (reference event bus equivalent).

    Thread-safe: the serving front end posts from ``ThreadingHTTPServer``
    worker threads while training code (or a test) may subscribe
    concurrently, so list mutation happens under a lock and ``post``
    iterates a snapshot. Listeners run on the POSTING thread, outside the
    lock — a slow listener delays its poster, never other
    subscribe/unsubscribe calls.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._listeners: list[Listener] = []

    def subscribe(self, listener: Listener) -> Callable[[], None]:
        """Register; returns an unsubscribe callable."""
        with self._lock:
            self._listeners.append(listener)

        def unsubscribe() -> None:
            with self._lock:
                try:
                    self._listeners.remove(listener)
                except ValueError:
                    pass

        return unsubscribe

    def post(self, name: str, **payload: Any) -> TrainingEvent:
        event = TrainingEvent(name=name, payload=payload)
        with self._lock:
            listeners = list(self._listeners)
        for listener in listeners:
            try:
                listener(event)
            except Exception:  # observers must never kill training
                logger.exception("event listener failed on %s", name)
        return event

    def __len__(self) -> int:
        with self._lock:
            return len(self._listeners)


#: Default process-wide bus the CLI drivers post to; embedders may also pass
#: their own bus to the drivers.
GLOBAL_BUS = EventBus()
