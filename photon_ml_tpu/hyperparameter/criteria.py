"""Acquisition criteria (reference
``photon-lib/.../hyperparameter/criteria/ExpectedImprovement.scala``)."""

from __future__ import annotations

import numpy as np
from scipy.stats import norm


def expected_improvement(mean: np.ndarray, var: np.ndarray,
                         best: float, *, maximize: bool = True) -> np.ndarray:
    """EI of candidate points given GP posterior (mean, var) and incumbent.

    ``maximize`` gives the metric direction (AUC ↑, RMSE ↓); EI itself is
    always maximized by the search.
    """
    std = np.sqrt(var)
    imp = (mean - best) if maximize else (best - mean)
    z = imp / std
    return imp * norm.cdf(z) + std * norm.pdf(z)
