"""Hyperparameter search loops (reference
``photon-lib/.../hyperparameter/search/{RandomSearch, GaussianProcessSearch}.scala``).

Both searches work on a box of named parameter ranges; values are sampled /
modeled in [0,1]^d (log-scaled per dimension when the range spans decades —
regularization weights always do) and mapped back before calling the
evaluation function. The evaluation function is the reference's
``EvaluationFunction``: run training at a config, return the validation
metric (e.g. one ``GameEstimator.fit`` configuration).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Callable, Mapping, Sequence

import numpy as np

from photon_ml_tpu.hyperparameter.criteria import expected_improvement
from photon_ml_tpu.hyperparameter.gp import GaussianProcessEstimator

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class ParamRange:
    """One searched dimension. ``log_scale`` samples uniformly in log space."""

    low: float
    high: float
    log_scale: bool = True

    def __post_init__(self):
        if not self.high > self.low:
            raise ValueError(f"need high > low, got [{self.low}, {self.high}]")
        if self.log_scale and self.low <= 0:
            raise ValueError("log_scale ranges need low > 0")

    def to_unit(self, v: float) -> float:
        if self.log_scale:
            return float((np.log(v) - np.log(self.low))
                         / (np.log(self.high) - np.log(self.low)))
        return float((v - self.low) / (self.high - self.low))

    def from_unit(self, u: float) -> float:
        u = float(np.clip(u, 0.0, 1.0))
        if self.log_scale:
            return float(np.exp(np.log(self.low)
                                + u * (np.log(self.high) - np.log(self.low))))
        return float(self.low + u * (self.high - self.low))


@dataclasses.dataclass
class SearchResult:
    configs: list[dict[str, float]]
    values: list[float]

    def best(self, maximize: bool) -> tuple[dict[str, float], float]:
        i = int(np.argmax(self.values) if maximize else np.argmin(self.values))
        return self.configs[i], self.values[i]


@dataclasses.dataclass(frozen=True)
class RandomSearch:
    """Uniform (log-)random sampling of the box."""

    space: Mapping[str, ParamRange]
    seed: int = 0

    def find(self, evaluate: Callable[[dict[str, float]], float],
             n_iterations: int) -> SearchResult:
        rng = np.random.default_rng(self.seed)
        names = list(self.space)
        configs, values = [], []
        for _ in range(n_iterations):
            u = rng.uniform(size=len(names))
            config = {k: self.space[k].from_unit(ui)
                      for k, ui in zip(names, u)}
            configs.append(config)
            values.append(float(evaluate(config)))
        return SearchResult(configs=configs, values=values)


@dataclasses.dataclass(frozen=True)
class GaussianProcessSearch:
    """Bayesian optimization: GP surrogate + EI, seeded by random points
    (reference ``GaussianProcessSearch``: observed points fit a
    ``GaussianProcessEstimator``; the next config maximizes EI over a
    candidate pool)."""

    space: Mapping[str, ParamRange]
    maximize: bool = True
    n_seed_points: int = 3
    n_candidates: int = 1024
    estimator: GaussianProcessEstimator = GaussianProcessEstimator()
    seed: int = 0

    def find(self, evaluate: Callable[[dict[str, float]], float],
             n_iterations: int,
             prior_observations: Sequence[tuple[dict[str, float], float]] = (),
             ) -> SearchResult:
        rng = np.random.default_rng(self.seed)
        names = list(self.space)
        xs: list[np.ndarray] = []
        configs: list[dict[str, float]] = []
        values: list[float] = []
        for cfg, val in prior_observations:
            xs.append(np.array([self.space[k].to_unit(cfg[k]) for k in names]))
            configs.append(dict(cfg))
            values.append(float(val))

        def observe(u: np.ndarray):
            config = {k: self.space[k].from_unit(ui) for k, ui in zip(names, u)}
            value = float(evaluate(config))
            xs.append(np.asarray(u, np.float64))
            configs.append(config)
            values.append(value)
            logger.info("GP search: %s -> %g", config, value)

        n_seed = min(self.n_seed_points, n_iterations)
        if not xs and n_seed == 0 and n_iterations > 0:
            n_seed = 1  # the GP needs at least one observation to fit
        for _ in range(n_seed):
            observe(rng.uniform(size=len(names)))

        for _ in range(n_iterations - n_seed):
            model = self.estimator.fit(np.stack(xs), np.array(values))
            cand = rng.uniform(size=(self.n_candidates, len(names)))
            mean, var = model.predict(cand)
            best = max(values) if self.maximize else min(values)
            ei = expected_improvement(mean, var, best, maximize=self.maximize)
            observe(cand[int(np.argmax(ei))])

        return SearchResult(configs=configs, values=values)
