"""Bayesian hyperparameter tuning: GP surrogate + Expected Improvement.

Re-design of the reference's tuning stack (``photon-lib/.../hyperparameter/``:
``estimators/{GaussianProcessEstimator, GaussianProcessModel}.scala``,
``search/{GaussianProcessSearch, RandomSearch}.scala``,
``criteria/ExpectedImprovement.scala``, ``kernels/{Matern52, RBF}.scala``,
``sampler/SliceSampler.scala``, ``EvaluationFunction.scala``).

Pure host-side numpy (float64): the GP operates on at most dozens of observed
points, far from the device hot path — exactly as the reference runs it
driver-local between training runs.
"""

from photon_ml_tpu.hyperparameter.kernels import RBF, Matern52  # noqa: F401
from photon_ml_tpu.hyperparameter.gp import (  # noqa: F401
    GaussianProcessEstimator,
    GaussianProcessModel,
)
from photon_ml_tpu.hyperparameter.criteria import expected_improvement  # noqa: F401
from photon_ml_tpu.hyperparameter.sampler import slice_sample  # noqa: F401
from photon_ml_tpu.hyperparameter.search import (  # noqa: F401
    GaussianProcessSearch,
    RandomSearch,
)
