"""Stationary GP covariance kernels (reference
``photon-lib/.../hyperparameter/kernels/{RBF, Matern52}.scala``).

Kernels carry an amplitude and per-dimension lengthscales; ``theta`` packs
``[log_amplitude, log_noise, log_lengthscale_1..d]`` for the slice sampler.
"""

from __future__ import annotations

import dataclasses

import numpy as np


def _scaled_sqdist(x1: np.ndarray, x2: np.ndarray, ls: np.ndarray) -> np.ndarray:
    a = x1 / ls
    b = x2 / ls
    return np.maximum(
        (a * a).sum(1)[:, None] + (b * b).sum(1)[None, :] - 2.0 * a @ b.T, 0.0)


@dataclasses.dataclass(frozen=True)
class RBF:
    amplitude: float = 1.0
    lengthscales: np.ndarray = None  # (d,)

    def __call__(self, x1: np.ndarray, x2: np.ndarray) -> np.ndarray:
        ls = (np.ones(x1.shape[1]) if self.lengthscales is None
              else np.asarray(self.lengthscales))
        return self.amplitude * np.exp(-0.5 * _scaled_sqdist(x1, x2, ls))

    def with_params(self, amplitude: float, lengthscales: np.ndarray) -> "RBF":
        return RBF(amplitude=amplitude, lengthscales=lengthscales)


@dataclasses.dataclass(frozen=True)
class Matern52:
    """Matérn ν=5/2 — the reference's default tuning kernel."""

    amplitude: float = 1.0
    lengthscales: np.ndarray = None

    def __call__(self, x1: np.ndarray, x2: np.ndarray) -> np.ndarray:
        ls = (np.ones(x1.shape[1]) if self.lengthscales is None
              else np.asarray(self.lengthscales))
        r2 = _scaled_sqdist(x1, x2, ls)
        r = np.sqrt(np.maximum(r2, 1e-32))
        s5r = np.sqrt(5.0) * r
        return self.amplitude * (1.0 + s5r + 5.0 * r2 / 3.0) * np.exp(-s5r)

    def with_params(self, amplitude: float, lengthscales: np.ndarray) -> "Matern52":
        return Matern52(amplitude=amplitude, lengthscales=lengthscales)
