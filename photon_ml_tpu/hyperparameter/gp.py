"""Gaussian-process surrogate with slice-sampled kernel hyperparameters.

Reference: ``photon-lib/.../hyperparameter/estimators/
{GaussianProcessEstimator, GaussianProcessModel}.scala`` — a GP posterior
over (config → metric) observations; kernel amplitude, noise, and per-dim
lengthscales are *marginalized* by slice sampling from their posterior (not
point-optimized), and predictions average over the sampled kernels.
"""

from __future__ import annotations

import dataclasses

import numpy as np
from scipy.linalg import cho_solve, solve_triangular

from photon_ml_tpu.hyperparameter.kernels import Matern52
from photon_ml_tpu.hyperparameter.sampler import slice_sample

_JITTER = 1e-8


@dataclasses.dataclass(frozen=True)
class _Posterior:
    """One kernel draw's cached Cholesky factors."""

    kernel: object
    noise: float
    x: np.ndarray
    chol: np.ndarray  # lower
    alpha: np.ndarray  # K^-1 (y - mean)
    y_mean: float


@dataclasses.dataclass(frozen=True)
class GaussianProcessModel:
    """Averaged predictive distribution over sampled kernels."""

    posteriors: tuple[_Posterior, ...]

    def predict(self, x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Posterior mean and variance at ``x`` (n, d), averaged over kernel
        samples (a Gaussian mixture; variance via the law of total variance)."""
        x = np.atleast_2d(np.asarray(x, np.float64))
        means, varis = [], []
        for p in self.posteriors:
            k_star = p.kernel(p.x, x)  # (n_obs, n)
            mean = p.y_mean + k_star.T @ p.alpha
            sol = solve_triangular(p.chol, k_star, lower=True)
            # stationary kernel: prior variance is the amplitude everywhere
            prior_var = np.full(x.shape[0], p.kernel.amplitude)
            var = np.maximum(prior_var - (sol * sol).sum(0) + p.noise, 1e-12)
            means.append(mean)
            varis.append(var)
        means = np.stack(means)
        varis = np.stack(varis)
        mean = means.mean(0)
        var = varis.mean(0) + (means ** 2).mean(0) - mean ** 2
        return mean, np.maximum(var, 1e-12)


@dataclasses.dataclass(frozen=True)
class GaussianProcessEstimator:
    """Fits a :class:`GaussianProcessModel` to observed (x, y) points.

    ``theta`` packs ``[log_amp, log_noise, log_ls_1..d]``; the prior is a
    broad log-normal around unit scales (weakly informative on the
    standardized [0,1]^d search box, as in the reference).
    """

    kernel_factory: type = Matern52
    n_kernel_samples: int = 8
    seed: int = 0

    def fit(self, x: np.ndarray, y: np.ndarray) -> GaussianProcessModel:
        x = np.atleast_2d(np.asarray(x, np.float64))
        y = np.asarray(y, np.float64).ravel()
        n, d = x.shape
        y_mean = float(y.mean()) if n else 0.0
        yc = y - y_mean
        rng = np.random.default_rng(self.seed + n)

        def factors(theta: np.ndarray):
            amp = np.exp(theta[0])
            noise = np.exp(theta[1])
            if not (1e-6 < amp < 1e6 and 1e-9 < noise < 1e3):
                return None
            kern = self.kernel_factory(amplitude=amp,
                                       lengthscales=np.exp(theta[2:]))
            k = kern(x, x) + (noise + _JITTER) * np.eye(n)
            try:
                chol = np.linalg.cholesky(k)
            except np.linalg.LinAlgError:
                return None
            return kern, noise, chol

        def log_posterior(theta: np.ndarray) -> float:
            f = factors(theta)
            if f is None:
                return -np.inf
            _, _, chol = f
            v = solve_triangular(chol, yc, lower=True)
            log_lik = (-0.5 * (v ** 2).sum() - np.log(np.diag(chol)).sum()
                       - 0.5 * n * np.log(2 * np.pi))
            log_prior = -0.5 * float(theta @ theta) / 4.0  # N(0, 2^2) on logs
            return float(log_lik) + log_prior

        theta0 = np.zeros(d + 2)
        theta0[1] = np.log(0.1)
        samples = slice_sample(log_posterior, theta0, rng,
                               self.n_kernel_samples, burn_in=20)

        posteriors = []
        for theta in samples:
            f = factors(theta)
            if f is None:
                continue
            kern, noise, chol = f
            alpha = cho_solve((chol, True), yc)
            posteriors.append(_Posterior(
                kernel=kern, noise=noise, x=x, chol=chol,
                alpha=alpha, y_mean=y_mean))
        if not posteriors:
            raise RuntimeError("GP fit failed: no valid kernel samples")
        return GaussianProcessModel(posteriors=tuple(posteriors))
