"""Univariate-step slice sampler over log-posterior densities
(reference ``photon-lib/.../hyperparameter/sampler/SliceSampler.scala``).

Coordinate-wise slice sampling with step-out: the standard scheme used to
marginalize GP kernel hyperparameters instead of point-optimizing them.
"""

from __future__ import annotations

from typing import Callable

import numpy as np


def slice_sample(
    log_density: Callable[[np.ndarray], float],
    x0: np.ndarray,
    rng: np.random.Generator,
    n_samples: int,
    *,
    width: float = 1.0,
    max_step_out: int = 8,
    burn_in: int = 10,
) -> np.ndarray:
    """Draw ``n_samples`` points (after ``burn_in``) from ``exp(log_density)``.

    Coordinate-wise: each scan updates every dimension once via step-out +
    shrink. Returns an ``(n_samples, d)`` array.
    """
    x = np.array(x0, np.float64)
    d = x.shape[0]
    fx = log_density(x)
    out = np.empty((n_samples, d))
    kept = 0
    for it in range(burn_in + n_samples):
        for j in range(d):
            log_y = fx + np.log(rng.uniform(1e-300, 1.0))
            lo = x[j] - width * rng.uniform()
            hi = lo + width
            for _ in range(max_step_out):
                if _eval_at(log_density, x, j, lo) <= log_y:
                    break
                lo -= width
            for _ in range(max_step_out):
                if _eval_at(log_density, x, j, hi) <= log_y:
                    break
                hi += width
            while True:
                xj = rng.uniform(lo, hi)
                f_new = _eval_at(log_density, x, j, xj)
                if f_new > log_y:
                    x[j] = xj
                    fx = f_new
                    break
                if xj < x[j]:
                    lo = xj
                else:
                    hi = xj
                if hi - lo < 1e-12:  # degenerate slice; keep current point
                    fx = log_density(x)
                    break
        if it >= burn_in:
            out[kept] = x
            kept += 1
    return out


def _eval_at(log_density, x, j, val) -> float:
    x2 = x.copy()
    x2[j] = val
    return log_density(x2)
