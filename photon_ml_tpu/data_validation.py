"""Row-level input validation (reference
``photon-client/.../DataValidators.scala``): finite features, task-legal
labels, non-negative weights — applied fully, on a sample, or disabled
(``DataValidationType``). Vectorized over the columnar arrays instead of the
reference's per-row closures.
"""

from __future__ import annotations

import numpy as np

from photon_ml_tpu.game.data import GameData
from photon_ml_tpu.types import DataValidationType, TaskType


class DataValidationError(ValueError):
    pass


def validate_game_data(
    data: GameData,
    task: TaskType,
    validation_type: DataValidationType = DataValidationType.VALIDATE_FULL,
    *,
    sample_fraction: float = 0.1,
    seed: int = 0,
) -> None:
    """Raise :class:`DataValidationError` on the first violated check."""
    if validation_type == DataValidationType.VALIDATE_DISABLED:
        return
    n = data.n_samples
    if validation_type == DataValidationType.VALIDATE_SAMPLE and n:
        rng = np.random.default_rng(seed)
        rows = np.sort(rng.choice(n, size=max(1, int(n * sample_fraction)),
                                  replace=False))
    else:
        rows = np.arange(n)

    labels = data.labels[rows]
    weights = data.weights[rows]
    offsets = data.offsets[rows]

    if not np.isfinite(labels).all():
        raise DataValidationError("non-finite labels")
    if not np.isfinite(offsets).all():
        raise DataValidationError("non-finite offsets")
    if not np.isfinite(weights).all() or (weights < 0).any():
        raise DataValidationError("weights must be finite and non-negative")

    if task == TaskType.LOGISTIC_REGRESSION or \
            task == TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM:
        if not np.isin(labels, (0.0, 1.0)).all():
            raise DataValidationError(
                f"binary task {task.value} needs 0/1 labels")
    elif task == TaskType.POISSON_REGRESSION:
        if (labels < 0).any():
            raise DataValidationError("Poisson regression needs labels >= 0")

    for name, shard in data.shards.items():
        vals = shard.vals
        if validation_type == DataValidationType.VALIDATE_SAMPLE:
            vals = vals[np.isin(shard.rows(), rows)]
        if not np.isfinite(vals).all():
            raise DataValidationError(f"non-finite feature values in shard {name!r}")
