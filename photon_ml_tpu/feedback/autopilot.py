"""Drift-triggered refresh autopilot: the loop's trigger.

Subscribes to the registry bus; on ``quality_drift_detected`` (whose
payload now names the drifted coordinate, kind and score —
quality/monitor.py) it runs the full learn leg of the loop on a worker
thread:

1. flush the in-process request logs and **join** the logged traffic to
   the configured label source (:func:`~photon_ml_tpu.feedback.joiner.
   join_feedback` — the ``feedback.join`` fault site lives there);
2. **refresh** via ``cli/refresh_game.py::run`` in-process — warm-started
   from the serving model's run dir, restricted to ONLY the drifted
   coordinate (``--refresh-coordinates``): its touched entities re-solve,
   every other random-effect coordinate carries bit-identically with
   zero solves (a ``__total__``/PSI drift refreshes all coordinates);
3. **publish**: the refresh writes into a staging dir under the publish
   root and one ``os.rename`` makes the complete run — full model,
   ``data-manifest.json``, quality baseline, ``patch/`` and, with
   ``fleet_shards=N``, the per-host ``patch-shard-I/`` set — appear
   atomically in the watch directory, where the single-host watcher
   (``serving/watcher.py``) or the router-side fleet watcher
   (``fleet/watcher.py``) discovers and activates it. The published run
   becomes the prior for the NEXT refresh (lineage chains).

Guards — a wedged or faulted refresh must never block serving:

- the bus listener only flips state and spawns a daemon worker; joins
  and refreshes never run on the posting (drift-evaluator) thread;
- **debounce**: events within ``debounce_s`` of the last launch are
  suppressed (the drift evaluator re-posts every poll while drifted);
- **max refresh rate**: launches are floored ``min_interval_s`` apart,
  and at most one refresh is ever in flight;
- the ``feedback.refresh_launch`` fault site fires before any work; any
  stage's failure counts into ``photon_feedback_aborts_total{stage}``,
  the staging dir is discarded, and the incumbent keeps serving.

``photon_feedback_refreshes_total`` counts completed loops and
``photon_freshness_lag_seconds`` gauges publish-time freshness (now
minus the newest joined request's wall timestamp). Waiting uses
``threading.Event.wait`` — this is serving-adjacent code and never
sleeps (hygiene rule 2).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import shutil
import threading
import time
from typing import Optional, Sequence

from photon_ml_tpu.feedback.joiner import join_feedback
from photon_ml_tpu.quality.monitor import TOTAL_COORDINATE
from photon_ml_tpu.resilience.faults import fault_point
from photon_ml_tpu.telemetry import metrics as _metrics

logger = logging.getLogger(__name__)

_REFRESHES = _metrics.counter(
    "photon_feedback_refreshes_total",
    "Completed autopilot loops: drift event -> join -> refresh of the "
    "drifted coordinate -> model + patches published to the watch dir")
_ABORTS = _metrics.counter(
    "photon_feedback_aborts_total",
    "Autopilot loops aborted with the incumbent serving, by stage "
    "(launch = faulted/guarded before work, join = joiner failed or too "
    "few rows, refresh = refresh_game failed, publish = staged run "
    "could not move into the watch dir)", labels=("stage",))
_LAG = _metrics.gauge(
    "photon_freshness_lag_seconds",
    "Freshness lag at the last autopilot publish: wall seconds from the "
    "newest JOINED request to the refreshed model landing in the watch "
    "dir (activation adds one watcher poll on top)")
_metrics.mark_host_owned("photon_freshness_lag_seconds")


class AutopilotAbort(RuntimeError):
    """A guarded, counted abort of one loop (incumbent keeps serving)."""


@dataclasses.dataclass
class AutopilotConfig:
    """Everything one refresh launch needs, round-trippable as JSON
    (``serve_game --autopilot-config config.json``). The training-side
    fields mirror ``refresh_game``'s flags; ``prior_dir`` advances to
    each published run so lineage chains across loops."""

    prior_dir: str
    publish_dir: str
    feature_shards: str
    coordinates: tuple
    update_sequence: str
    grid: tuple
    labels: Optional[str] = None
    task: str = "LOGISTIC_REGRESSION"
    evaluators: str = ""
    data_validation: str = "VALIDATE_FULL"
    fleet_shards: int = 0
    refresh_sweeps: int = 1
    min_rows: int = 1
    debounce_s: float = 30.0
    min_interval_s: float = 300.0
    #: restrict the touched-entity solve to the event's coordinate
    #: (``--refresh-coordinates``); False refreshes every coordinate
    drifted_only: bool = True

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "AutopilotConfig":
        d = dict(d)
        d["coordinates"] = tuple(d.get("coordinates", ()))
        d["grid"] = tuple(d.get("grid", ()))
        return cls(**d)

    @classmethod
    def load(cls, path: str) -> "AutopilotConfig":
        with open(path) as f:
            return cls.from_dict(json.load(f))


class FeedbackAutopilot:
    """Bus subscriber that turns drift events into published refreshes.

    ``reqlog_dirs`` name the request-log directories to join (every
    fleet host's, in the fleet topology); ``reqlogs`` are the in-process
    :class:`~photon_ml_tpu.serving.reqlog.RequestLog` handles to flush
    before joining (a cross-machine deployment passes none and relies on
    segment cadence).
    """

    def __init__(self, bus, config: AutopilotConfig, *,
                 reqlog_dirs: Sequence[str],
                 reqlogs: Sequence = ()):
        self.bus = bus
        self.config = config
        self.reqlog_dirs = list(reqlog_dirs)
        self.reqlogs = list(reqlogs)
        self._lock = threading.Lock()
        self._busy = False  # guarded-by: _lock
        self._last_launch: Optional[float] = None  # guarded-by: _lock
        self._seq = 0  # guarded-by: _lock
        self.n_refreshes = 0  # guarded-by: _lock
        self.n_aborts = 0  # guarded-by: _lock
        self.n_suppressed = 0  # guarded-by: _lock
        self.last_result: Optional[dict] = None  # guarded-by: _lock
        self._stop = threading.Event()
        #: start/stop are operator-lifecycle calls from one control thread
        self._unsubscribe = None  # guarded-by: caller
        self._worker: Optional[threading.Thread] = None  # guarded-by: caller

    # --- lifecycle --------------------------------------------------------
    def start(self) -> "FeedbackAutopilot":
        self._unsubscribe = self.bus.subscribe(self._on_event)
        return self

    def stop(self, timeout_s: float = 60.0) -> None:
        if self._unsubscribe is not None:
            self._unsubscribe()
            self._unsubscribe = None
        self._stop.set()
        worker = self._worker
        if worker is not None and worker.is_alive():
            worker.join(timeout_s)

    # --- the trigger (drift-evaluator thread: flip state and hand off) ----
    def _on_event(self, event) -> None:
        if event.name != "quality_drift_detected" or self._stop.is_set():
            return
        now = time.monotonic()
        with self._lock:
            window = max(self.config.debounce_s, 0.0)
            floor = max(self.config.min_interval_s, 0.0)
            if self._busy or (
                    self._last_launch is not None
                    and now - self._last_launch < max(window, floor)):
                self.n_suppressed += 1
                return
            self._busy = True
            self._last_launch = now
            self._seq += 1
            seq = self._seq
        self._worker = threading.Thread(
            target=self._run, args=(dict(event.payload), seq),
            daemon=True, name="photon-feedback-refresh")
        self._worker.start()

    # --- the loop body (worker thread) ------------------------------------
    def _run(self, payload: dict, seq: int) -> None:
        coordinate = payload.get("coordinate") or TOTAL_COORDINATE
        staging = os.path.join(self.config.publish_dir, ".staging",
                               f"refresh-{seq:04d}")
        stage = "launch"
        try:
            # chaos site: a faulted launch aborts before ANY work — the
            # incumbent serves on, the next drift event retries
            fault_point("feedback.refresh_launch", coordinate=coordinate)
            stage = "join"
            os.makedirs(staging, exist_ok=True)
            self._drain_reqlogs()
            joined_path = os.path.join(staging, "joined.avro")
            join = join_feedback(self.reqlog_dirs, self.config.labels,
                                 joined_path)
            if join.joined < max(self.config.min_rows, 1):
                raise AutopilotAbort(
                    f"joined {join.joined} rows < min_rows "
                    f"{self.config.min_rows} — not enough feedback to "
                    f"refresh on")
            stage = "refresh"
            from photon_ml_tpu.cli import refresh_game

            run_dir = os.path.join(staging, "run")
            argv = [
                "--prior-dir", self.config.prior_dir,
                "--training-data", joined_path,
                "--output-dir", run_dir,
                "--task", self.config.task,
                "--feature-shards", self.config.feature_shards,
                "--coordinates", *self.config.coordinates,
                "--update-sequence", self.config.update_sequence,
                "--grid", *self.config.grid,
                "--evaluators", self.config.evaluators,
                "--data-validation", self.config.data_validation,
                "--refresh-sweeps", str(self.config.refresh_sweeps),
            ]
            if self.config.drifted_only and coordinate != TOTAL_COORDINATE:
                argv += ["--refresh-coordinates", coordinate]
            if self.config.fleet_shards > 0:
                argv += ["--fleet-shards", str(self.config.fleet_shards)]
            result = refresh_game.run(argv)
            stage = "publish"
            entry = os.path.join(self.config.publish_dir,
                                 f"refresh-{seq:04d}")
            # one rename publishes the COMPLETE run (model + manifest +
            # baseline + patches) — the watchers never see it half-built
            os.rename(run_dir, entry)
            self.config.prior_dir = entry
            if join.last_ts is not None:
                _LAG.set(max(time.time() - join.last_ts, 0.0))  # photon-lint: disable=tel-wall-clock -- freshness lag anchors to the log's wall-clock ts (possibly another machine's); a monotonic timer cannot span processes
            _REFRESHES.inc()
            with self._lock:
                self.n_refreshes += 1
                self.last_result = {"entry": entry, "join": join.as_dict(),
                                    "solved": result["solved"],
                                    "coordinate": coordinate}
            logger.info(
                "autopilot refresh %d published %s (coordinate %s, "
                "joined %d rows, solved %s)", seq, entry, coordinate,
                join.joined, result["solved"])
        except Exception as e:
            _ABORTS.labels(stage=stage).inc()
            with self._lock:
                self.n_aborts += 1
            level = (logging.WARNING if isinstance(e, AutopilotAbort)
                     else logging.ERROR)
            logger.log(level,
                       "autopilot refresh %d aborted at stage %s "
                       "(incumbent keeps serving): %r", seq, stage, e)
        finally:
            shutil.rmtree(staging, ignore_errors=True)
            with self._lock:
                self._busy = False

    def _drain_reqlogs(self, timeout_s: float = 10.0) -> None:
        """Flush the in-process logs and wait for their segments to land
        (``Event.wait`` polling — the joiner reads only durable files)."""
        for rl in self.reqlogs:
            rl.flush()
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if all(rl.stats()["buffered"] == 0 for rl in self.reqlogs):
                return
            if self._stop.wait(0.05):
                return

    # --- introspection ----------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {"refreshes": self.n_refreshes, "aborts": self.n_aborts,
                    "suppressed": self.n_suppressed, "busy": self._busy,
                    "last": self.last_result}
