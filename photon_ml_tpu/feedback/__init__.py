"""The feedback subsystem: the fleet retrains itself.

Photon-ML's GLMix deployments (PAPER.md §0) kept per-entity random
effects fresh with operator-scheduled Spark batch retrains; this package
closes that loop ONLINE. The pieces:

- :mod:`photon_ml_tpu.feedback.joiner` — deterministically join labels
  (the request log's inline nullable ``label`` field plus an external
  Avro/CSV source keyed by request id) to logged score records, emitting
  incremental ``TrainingExampleAvro`` data the refresh driver consumes;
  unjoinable/duplicate/late labels are counted, never dropped silently.
- :mod:`photon_ml_tpu.feedback.autopilot` — subscribe to the registry
  bus; on ``quality_drift_detected``, join the logged traffic and run
  ``refresh_game`` in-process for ONLY the drifted coordinate
  (touched-entity solve, carried coefficients bit-identical), publishing
  the full model + per-shard patches into a watch directory under
  debounce + max-refresh-rate guards and the ``feedback.join`` /
  ``feedback.refresh_launch`` fault sites.

Router-side activation (the loop's last hop) lives in
:mod:`photon_ml_tpu.fleet.watcher`; the closed-loop architecture is
drawn in CONTINUOUS.md.
"""

from photon_ml_tpu.feedback.autopilot import (  # noqa: F401
    AutopilotConfig,
    FeedbackAutopilot,
)
from photon_ml_tpu.feedback.joiner import (  # noqa: F401
    JoinResult,
    join_feedback,
    load_labels,
)
