"""Feedback joiner: logged score records + labels → incremental training
data.

The request log (``serving/reqlog.py``) records WHAT was served — request
id, features, entity ids (``metadataMap``), offset, score — but a refresh
needs outcomes. This module is the deterministic join between the two
label channels and the log:

- **inline labels**: the schema's nullable ``label`` field
  (``RequestLogScoredRecordAvro``), stamped at request time by
  backfill/replay clients that already know the outcome;
- **external labels**: an Avro (``FeedbackLabelAvro``) or CSV source
  keyed by ``(request id, record index)`` — the production shape, where
  outcomes (clicks, conversions) arrive minutes after the request.

Join semantics (all deterministic: directories and segments scan in
sorted order, ties resolve first-wins):

- a logged score record with a label (inline wins over external) emits
  one ``TrainingExampleAvro`` row — ``uid=<requestId>#<index>``,
  ``response=label``, features/offset/``metadataMap`` copied verbatim,
  so the entity ids ride into :class:`~photon_ml_tpu.io.data_reader.
  AvroDataReader` exactly as training data does;
- a logged record with NO label counts as **unjoined** (it emits
  nothing — unlabeled traffic is not training data);
- a label whose ``(request id, index)`` never appears in the log counts
  as **late** (the segment rotated out, the request was sampled out, or
  the label outlived retention);
- a second label for an already-joined key, and a re-logged record (a
  replica double-logging a request), count as **duplicates** and do not
  emit a second row.

Nothing is dropped silently: every disposition lands in the
``photon_feedback_{joined,unjoined,late}_total`` counters (late carries
a ``reason`` label separating late labels from duplicates) and in the
returned :class:`JoinResult`.

Reading the log is confined to this module and ``tools/reqlog_replay.py``
by the ``res-reqlog-read-home`` lint rule — one read path, like the one
writer hygiene rule 7 enforces.
"""

from __future__ import annotations

import csv
import dataclasses
import os
from typing import Iterable, Mapping, Optional, Sequence, Union

from photon_ml_tpu.resilience.faults import fault_point
from photon_ml_tpu.serving.reqlog import iter_reqlog
from photon_ml_tpu.telemetry import metrics as _metrics

_JOINED = _metrics.counter(
    "photon_feedback_joined_total",
    "Logged score records successfully joined to a label and emitted as "
    "incremental training examples (feedback/joiner.py)")
_UNJOINED = _metrics.counter(
    "photon_feedback_unjoined_total",
    "Logged score records that had no label from any source — counted, "
    "not silently dropped (unlabeled traffic is not training data)")
_LATE = _metrics.counter(
    "photon_feedback_late_total",
    "Labels that could not join: reason=unknown_request (the request was "
    "sampled out, rotated out, or the label arrived after retention), "
    "reason=duplicate (a second label for a joined key, or a replica's "
    "re-logged record)", labels=("reason",))


@dataclasses.dataclass
class JoinResult:
    """One join pass's full accounting (mirrors the counters)."""

    output_path: str
    joined: int = 0
    unjoined: int = 0
    late: int = 0
    duplicates: int = 0
    requests: int = 0
    #: wall timestamp of the newest JOINED request — the freshness-lag
    #: anchor (photon_freshness_lag_seconds measures from here)
    last_ts: Optional[float] = None

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def load_labels(path: str) -> dict[tuple[str, int], float]:
    """``(request id, record index) → label`` from an external source.

    ``.avro`` reads ``FeedbackLabelAvro`` records; anything else parses
    as CSV — ``request_id,label`` or ``request_id,record_index,label``,
    with an optional header row (sniffed: a non-numeric last cell).
    First label wins per key; later ones count as duplicates at join
    time.
    """
    labels: dict[tuple[str, int], float] = {}
    dupes = 0
    if path.endswith(".avro"):
        from photon_ml_tpu.io.avro import iter_avro_file

        for rec in iter_avro_file(path):
            key = (str(rec["requestId"]), int(rec.get("recordIndex", 0)))
            if key in labels:
                dupes += 1
                continue
            labels[key] = float(rec["label"])
    else:
        with open(path, newline="") as f:
            for row in csv.reader(f):
                if not row:
                    continue
                try:
                    value = float(row[-1])
                except ValueError:
                    continue  # header row
                rid = row[0].strip()
                idx = int(row[1]) if len(row) >= 3 else 0
                if (rid, idx) in labels:
                    dupes += 1
                    continue
                labels[(rid, idx)] = value
    if dupes:
        _LATE.labels(reason="duplicate").inc(dupes)
    return labels


def join_feedback(reqlog_dirs: "str | Sequence[str]",
                  labels: Union[str, Mapping[tuple[str, int], float], None],
                  output_path: str, *,
                  codec: str = "null") -> JoinResult:
    """Join ``labels`` to the logged score records under ``reqlog_dirs``
    and write the joined rows as ``TrainingExampleAvro`` at
    ``output_path`` (written even when empty — a valid, zero-row file,
    so downstream readers fail loudly on min-rows policy, not on a
    missing path). Returns the full :class:`JoinResult` accounting.

    ``labels`` is a path (CSV/Avro, :func:`load_labels`), an in-memory
    mapping, or None (inline labels only). Deterministic: same log +
    same labels → byte-identical output.
    """
    from photon_ml_tpu.io.data_reader import write_training_examples

    dirs = [reqlog_dirs] if isinstance(reqlog_dirs, str) else list(reqlog_dirs)
    # chaos site: a faulted join aborts THIS pass cleanly — the log and
    # serving are untouched, and the next drift event retries the join
    fault_point("feedback.join", dirs=",".join(dirs))
    label_map: Mapping[tuple[str, int], float]
    if labels is None:
        label_map = {}
    elif isinstance(labels, str):
        label_map = load_labels(labels)
    else:
        label_map = labels
    result = JoinResult(output_path=output_path)
    emitted: set[tuple[str, int]] = set()
    matched_labels: set[tuple[str, int]] = set()

    def examples() -> Iterable[dict]:
        for log_dir in sorted(dirs):
            for entry in iter_reqlog(log_dir):
                if entry.get("kind", "score") != "score":
                    continue  # ranked requests carry no per-record truth
                rid = str(entry["requestId"])
                result.requests += 1
                for i, rec in enumerate(entry.get("records") or ()):
                    key = (rid, i)
                    label = rec.get("label")
                    if label is None:
                        label = label_map.get(key)
                        if label is not None:
                            matched_labels.add(key)
                    if label is None:
                        result.unjoined += 1
                        continue
                    if key in emitted:
                        # a replica double-logged the request — one row
                        # per observation, the rest are counted
                        result.duplicates += 1
                        continue
                    emitted.add(key)
                    result.joined += 1
                    ts = float(entry.get("ts") or 0.0)
                    if result.last_ts is None or ts > result.last_ts:
                        result.last_ts = ts
                    yield {
                        "uid": f"{rid}#{i}",
                        "response": float(label),
                        "offset": rec.get("offset"),
                        "weight": None,
                        "features": [
                            {"name": f.get("name", ""),
                             "term": f.get("term") or "",
                             "value": float(f.get("value", 0.0))}
                            for f in (rec.get("features") or ())],
                        "metadataMap": rec.get("metadataMap"),
                    }

    os.makedirs(os.path.dirname(os.path.abspath(output_path)),
                exist_ok=True)
    # a pinned sync marker makes the byte-identical promise above hold —
    # the container is otherwise identical but Avro's marker is random
    import hashlib

    sync = hashlib.blake2s(b"photon-feedback-join",
                           digest_size=16).digest()
    write_training_examples(output_path, examples(), codec=codec,
                            sync=sync)
    result.late = len(set(label_map) - matched_labels)
    if result.joined:
        _JOINED.inc(result.joined)
    if result.unjoined:
        _UNJOINED.inc(result.unjoined)
    if result.late:
        _LATE.labels(reason="unknown_request").inc(result.late)
    if result.duplicates:
        _LATE.labels(reason="duplicate").inc(result.duplicates)
    return result
