"""GLM optimization problems: objective + optimizer + regularization in one box.

Re-design of the reference's optimization-problem layer
(``photon-api/.../optimization/game/GeneralizedLinearOptimizationProblem.scala``,
``DistributedOptimizationProblem.scala``, ``SingleNodeOptimizationProblem.scala``
and ``optimization/GLMOptimizationConfiguration.scala``).

The reference splits distributed vs single-node problems because the former
aggregates over an RDD and the latter over a local Iterable. Here both are the
*same* pure functions — the distinction collapses to whether the value/grad
closure contains a ``psum`` (see :mod:`photon_ml_tpu.parallel.distributed`).
One ``OptimizationProblem`` serves the fixed effect on a pod and, vmapped, a
million random-effect entities.

Optimizer dispatch follows the reference exactly: an L1/elastic-net
regularization context selects OWLQN (the L1 part handled by orthant
projection, never differentiated); TRON may be requested explicitly and uses
exact autodiff Hessian-vector products; otherwise L-BFGS. The regularization
weight ``lam`` is a *dynamic* scalar so a single XLA compilation serves the
whole warm-start lambda sweep.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.ops.objective import GLMData, GLMObjective
from photon_ml_tpu.ops.regularization import NoRegularization, RegularizationContext
from photon_ml_tpu.optimize import (
    OptimizerConfig,
    OptimizerResult,
    minimize_lbfgs,
    minimize_owlqn,
    minimize_tron,
)
from photon_ml_tpu.types import OptimizerType, VarianceComputationType

Array = jax.Array

#: Optional wrapper installed around raw (value, grad)/(hvp) closures —
#: the distributed layer injects psum here (photon_ml_tpu.parallel).
ObjectiveWrapper = Callable[[Callable], Callable]


@dataclasses.dataclass(frozen=True)
class GLMOptimizationConfiguration:
    """Per-problem optimization settings (reference
    ``GLMOptimizationConfiguration.scala``)."""

    optimizer: OptimizerType = OptimizerType.LBFGS
    regularization: RegularizationContext = NoRegularization
    optimizer_config: OptimizerConfig = OptimizerConfig()
    variance_type: VarianceComputationType = VarianceComputationType.NONE

    def __post_init__(self) -> None:
        if self.optimizer == OptimizerType.TRON and self.regularization.has_l1:
            raise ValueError(
                "TRON needs a twice-differentiable objective; L1/elastic-net "
                "requires OWLQN (as in the reference)")


@dataclasses.dataclass(frozen=True)
class OptimizationProblem:
    """A ready-to-run GLM solve: minimizes
    ``sum_i w_i l(margin_i, y_i) + 0.5*l2*||w||^2 (+ l1*||w||_1)``.

    All methods are pure and jit/vmap-safe; ``lam`` (the total regularization
    weight, split into l1/l2 by the regularization context) is a traced
    scalar.
    """

    objective: GLMObjective
    config: GLMOptimizationConfiguration = GLMOptimizationConfiguration()

    def _split(self, lam) -> tuple[Array, Array]:
        reg = self.config.regularization
        lam = jnp.asarray(lam, jnp.result_type(float))
        return reg.l1_weight(lam), reg.l2_weight(lam)

    def run(self, data: GLMData, w0: Array, lam=0.0) -> OptimizerResult:
        """Solve from ``w0`` (the warm-start hook) at regularization ``lam``."""
        l1, l2 = self._split(lam)
        fun = lambda w: self.objective.value_and_grad(w, data, l2)
        cfg = self.config.optimizer_config
        if self.config.optimizer == OptimizerType.TRON:
            hvp = lambda w, v: self.objective.hvp(w, v, data, l2)
            # operator form only when it pays: the fused one-pass Hvp
            # kernel per CG product, d2 pass hoisted per outer iteration
            # (measured 1.5x on the TRON bench shape; forcing it onto the
            # plain closed form measured slower — see hvp_prefers_operator)
            prefers = getattr(self.objective, "hvp_prefers_operator", None)
            hvp_at = ((lambda w: self.objective.hvp_operator(w, data, l2))
                      if prefers is not None and prefers(data) else None)
            return minimize_tron(fun, hvp, w0, cfg, hvp_at=hvp_at)
        if self.config.regularization.has_l1:
            return minimize_owlqn(fun, w0, l1, cfg)
        return minimize_lbfgs(fun, w0, cfg)

    # --- variance (reference VarianceComputationType SIMPLE / FULL) -------
    def compute_variances(self, w: Array, data: GLMData, lam=0.0) -> Optional[Array]:
        """Per-coefficient variance approximations of the reference:

        - SIMPLE: elementwise inverse of the Hessian diagonal
          (``HessianDiagonalAggregator`` path),
        - FULL: diagonal of the full Hessian inverse
          (``HessianMatrixAggregator`` path; small dims only).
        """
        vt = self.config.variance_type
        if vt == VarianceComputationType.NONE:
            return None
        _, l2 = self._split(lam)
        if vt == VarianceComputationType.SIMPLE:
            diag = self.objective.hessian_diagonal(w, data, l2)
            return 1.0 / jnp.maximum(diag, jnp.finfo(diag.dtype).tiny)
        h = self.objective.hessian_matrix(w, data, l2)
        # pinv, not inv: padded/unobserved feature dims (all-zero design
        # columns, e.g. random-effect bucket padding) make H singular; the
        # pseudo-inverse assigns them variance 0 instead of NaN-ing the
        # whole inverse.
        return jnp.diag(jnp.linalg.pinv(h, hermitian=True))

    def run_with_variances(self, data: GLMData, w0: Array, lam=0.0
                           ) -> tuple[Coefficients, OptimizerResult]:
        result = self.run(data, w0, lam)
        variances = self.compute_variances(result.w, data, lam)
        return Coefficients(means=result.w, variances=variances), result
