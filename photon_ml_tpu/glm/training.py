"""Single-model GLM training: the warm-start regularization sweep.

Re-design of the reference's legacy training stage
(``photon-client/src/main/scala/com/linkedin/photon/ml/ModelTraining.scala``):
train one model per regularization weight, descending, each solve warm-started
from the previous lambda's solution, then pick the best by a validation
evaluator (``Evaluation.scala`` + ``ModelSelection``).

TPU shape: the solve for every lambda reuses ONE compiled XLA program (lambda
is a traced scalar), so the sweep costs one compile + k solves. Normalization
is a coefficient-space reparameterization inside the objective; trained
coefficients are mapped back to original feature space before models are
returned, mirroring the reference's back-transformation at output time.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.evaluation import EvaluationResults, Evaluator, evaluate_all
from photon_ml_tpu.glm.problem import GLMOptimizationConfiguration, OptimizationProblem
from photon_ml_tpu.models import Coefficients, GeneralizedLinearModel
from photon_ml_tpu.ops.losses import loss_for_task
from photon_ml_tpu.ops.normalization import NormalizationContext, NoNormalization
from photon_ml_tpu.ops.objective import GLMData, GLMObjective
from photon_ml_tpu.optimize import OptimizerResult
from photon_ml_tpu.types import TaskType

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class TrainedModel:
    """One (lambda, model, optimization trace) entry of the sweep."""

    regularization_weight: float
    model: GeneralizedLinearModel
    result: OptimizerResult
    evaluation: Optional[EvaluationResults] = None


def build_problem(
    task: TaskType,
    config: GLMOptimizationConfiguration,
    normalization: NormalizationContext = NoNormalization,
    reg_mask: Optional[Array] = None,
    mesh=None,
) -> OptimizationProblem:
    """The one place the sweep's optimization problem is assembled — shared
    with the diagnostics stage so bootstrap/fitting solves diagnose exactly
    the objective that trained the model.

    ``fused=True``: on TPU with a dense design and identity normalization,
    value+grad runs the one-pass Pallas kernel (1.35x in-solve — see
    ops/pallas_glm.py); every other combination transparently takes the
    closed-form/autodiff path, so the flag is safe to set unconditionally.

    With a ``mesh`` (carrying a ``data`` axis) the objective becomes the
    shard_map/psum :class:`~photon_ml_tpu.parallel.distributed.
    DistributedGLMObjective` over it — the sweep then expects the stacked
    per-device data layout (``shard_glm_data`` /
    ``global_glm_data_multihost``) and runs one psum per iteration; on a
    multi-controller job every process executes the same sweep in lockstep
    (the reference's per-iteration broadcast + treeAggregate,
    ``ModelTraining.scala``).
    """
    objective = GLMObjective(
        loss=loss_for_task(task), normalization=normalization,
        reg_mask=reg_mask, fused=True)
    if mesh is not None:
        from photon_ml_tpu.parallel.distributed import DistributedGLMObjective

        return OptimizationProblem(
            DistributedGLMObjective(objective=objective, mesh=mesh), config)
    return OptimizationProblem(objective, config)


def train_glm_sweep(
    task: TaskType,
    data: GLMData,
    regularization_weights: Sequence[float],
    config: GLMOptimizationConfiguration = GLMOptimizationConfiguration(),
    normalization: NormalizationContext = NoNormalization,
    reg_mask: Optional[Array] = None,
    initial: Optional[Array] = None,
    warm_start: bool = True,
    mesh=None,
    dim: Optional[int] = None,
) -> list[TrainedModel]:
    """Train one GLM per regularization weight with warm starts.

    Weights are processed in descending order (strongest regularization first,
    the stable warm-start direction the reference uses); the returned list
    follows that order. ``reg_mask`` excludes coefficients (e.g. the
    intercept) from regularization. With ``mesh``, ``data`` must be the
    stacked per-device layout (see :func:`build_problem`) and ``dim`` names
    the coefficient length (the stacked layout's ``dim`` property reflects
    block shapes, not the model).
    """
    for lam in regularization_weights:
        config.regularization.check_weight(lam)
    problem = build_problem(task, config, normalization, reg_mask, mesh=mesh)

    from photon_ml_tpu.telemetry import profiling

    # one compile serves the whole lambda sweep (lambda is a traced
    # scalar); profile_jit makes that visible — photon_compiles_total
    # {fn="glm.sweep_solve"} must move once per sweep, not per lambda
    run = profiling.profile_jit(problem.run, "glm.sweep_solve")
    d = data.dim if dim is None else dim
    w = jnp.zeros((d,)) if initial is None else jnp.asarray(initial)

    # fleet-metrics fold point (no-op unless --metrics-port installed a
    # hook). The lambda loop is the GLM driver's sweep boundary and is
    # collective-symmetric under --multihost: every process runs the
    # identical sorted sweep over the psum'd objective.
    from photon_ml_tpu.telemetry.aggregate import sweep_boundary

    from photon_ml_tpu.resilience import fault_point, fault_value, heartbeat

    out: list[TrainedModel] = []
    for lam in sorted(regularization_weights, reverse=True):
        # per-lambda liveness + injection: the lambda loop is the GLM
        # driver's sweep boundary (what the GAME drivers' per-sweep
        # worker.stall / optimizer.step sites are to coordinate descent)
        heartbeat("glm.sweep")
        fault_point("worker.stall", regularization_weight=float(lam))
        result = run(data, w, jnp.asarray(lam, w.dtype))
        w_solved = fault_value("optimizer.step", result.w,
                               regularization_weight=float(lam))
        variances = problem.compute_variances(w_solved, data, lam)
        coeffs = Coefficients(means=w_solved, variances=variances)
        model = GeneralizedLinearModel(
            coefficients=to_original_space(coeffs, normalization), task=task)
        out.append(TrainedModel(float(lam), model, result))
        if warm_start:
            # an injected-NaN solve must not poison the NEXT lambda's warm
            # start (nan init never recovers); the finiteness sync runs
            # only when a fault actually corrupted the value, so the
            # healthy path keeps its async dispatch untouched
            if w_solved is result.w or bool(jnp.isfinite(w_solved).all()):
                w = w_solved
        sweep_boundary(regularization_weight=float(lam))
    return out


def train_glm_sweep_batched(
    task: TaskType,
    data: GLMData,
    regularization_weights: Sequence[float],
    config: GLMOptimizationConfiguration = GLMOptimizationConfiguration(),
    normalization: NormalizationContext = NoNormalization,
    reg_mask: Optional[Array] = None,
) -> list[TrainedModel]:
    """ALL-lambda batched sweep: one vmapped solve over the lambda axis.

    The TPU-first alternative to :func:`train_glm_sweep`'s sequential
    warm-started loop (the reference's ``ModelTraining.scala`` semantics):
    every optimizer iteration touches the design ONCE for all lambdas, so
    per-element design costs amortize K-fold. The trade: no warm starts
    (lanes are independent, each runs from zero to its own masked
    convergence) and the batched program runs until the SLOWEST lane
    stops. Results are returned in the same descending-lambda order.

    Measured on the axon TPU v5e, 2026-07-31 (5 lambdas '100;10;1;0.1;
    0.01', D2H-sync timing, min of 3) — the verdict is LAYOUT-DEPENDENT:

    - dense 200k x 1024, 50 iters: sequential 0.75 s, batched 1.27 s —
      **0.59x, a loss**. The dense sequential path runs the fused Pallas
      kernel at the HBM wall and warm starts slash late-lane iterations.
      Round 4: the multi-row-margin kernel (``ops/pallas_glm.py::
      fused_value_and_grad_multi``, dispatched automatically through a
      custom-vmap rule when the solve vmaps over lambda) cuts the batched
      dense time to 0.95 s — still 0.78x sequential: lockstep lanes
      cannot beat warm starts on dense, with or without idle-MXU-row use.
    - chunked-sparse 3.2M nnz, d=20k, 30 iters: sequential 4.34 s,
      batched 2.49 s — **1.74x**. Here the per-iteration cost is XLA's
      random gather (~16-20 ns/nnz, tools/layout_crossover.py) whose
      indices are lambda-independent, so the gather hoists out of the
      vmap and K lanes share one pass.

    Use batched for wide-sparse sweeps; keep sequential (the default, and
    the reference's exact semantics) for dense designs.
    """
    for lam in regularization_weights:
        config.regularization.check_weight(lam)
    problem = build_problem(task, config, normalization, reg_mask)
    lams = sorted((float(l) for l in regularization_weights), reverse=True)

    from photon_ml_tpu.telemetry import profiling

    # data/w0 as explicit unbatched args (in_axes=None), NOT a closure: a
    # closed-over device array becomes an HLO constant — a GB-scale design
    # baked into the program (and rejected by remote-compile size limits)
    run = profiling.profile_jit(
        jax.vmap(problem.run, in_axes=(None, None, 0)),
        "glm.sweep_solve_batched")
    batched = run(data, jnp.zeros((data.dim,)),
                  jnp.asarray(lams, jnp.float32))

    out: list[TrainedModel] = []
    for i, lam in enumerate(lams):
        result = jax.tree.map(lambda x: x[i], batched)
        variances = problem.compute_variances(result.w, data, lam)
        coeffs = Coefficients(means=result.w, variances=variances)
        model = GeneralizedLinearModel(
            coefficients=to_original_space(coeffs, normalization), task=task)
        out.append(TrainedModel(float(lam), model, result))
    return out


def to_original_space(coeffs: Coefficients, normalization: NormalizationContext
                      ) -> Coefficients:
    """Map transformed-space coefficients (and variances, which scale by the
    squared factors) back to raw feature space for model output."""
    if normalization.is_identity:
        return coeffs
    means = normalization.model_to_original(coeffs.means)
    variances = coeffs.variances
    if variances is not None and normalization.factors is not None:
        variances = variances * jnp.square(normalization.factors)
    return Coefficients(means=means, variances=variances)


def validate_and_select(
    trained: Sequence[TrainedModel],
    evaluators: Sequence[Evaluator],
    validation: GLMData,
    id_tags=None,
) -> tuple[int, list[TrainedModel]]:
    """Score every swept model on validation data and pick the best by the
    FIRST evaluator (reference ``ModelSelection.selectBestModel``).

    Returns ``(best_index, trained_with_evaluations)``.
    """
    labels = np.asarray(validation.labels)
    weights = np.asarray(validation.weights)
    best_idx, best_val = 0, None
    evaluated: list[TrainedModel] = []
    primary = evaluators[0]
    for i, tm in enumerate(trained):
        scores = np.asarray(tm.model.score(validation.design, validation.offsets))
        ev = evaluate_all(evaluators, scores, labels, weights, id_tags)
        evaluated.append(dataclasses.replace(tm, evaluation=ev))
        val = ev.primary[1]
        if primary.better_than(val, best_val):
            best_idx, best_val = i, val
    return best_idx, evaluated
