from photon_ml_tpu.glm.problem import (  # noqa: F401
    GLMOptimizationConfiguration,
    OptimizationProblem,
)
from photon_ml_tpu.glm.training import (  # noqa: F401
    TrainedModel,
    to_original_space,
    train_glm_sweep,
    validate_and_select,
)
