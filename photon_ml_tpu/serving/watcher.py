"""Registry-driven model discovery: poll a publish directory, apply what
lands there.

The manual ``/reload`` endpoint makes an operator the delivery mechanism;
a continuously refreshing deployment instead PUBLISHES into a directory
(full model dirs from ``train_game``/``refresh_game``, coefficient
patches from ``refresh_game``) and every serving host picks versions up
itself. The watcher polls the directory, applies each new entry — in
sorted name order, so ``v0001…``-style publishers get ordered activation
— through the registry's existing validate-then-activate paths
(:meth:`~photon_ml_tpu.serving.registry.ModelRegistry.reload`, which
routes full dirs vs patches by metadata ``kind``), and keeps serving the
current version when a candidate is rejected.

Publication atomicity is what makes polling safe: the training side's
staged retire-then-rename (``io/pipeline.py``) means a directory either
is absent or is complete — the watcher can never observe a half-written
model. An entry that fails validation — or, under a canary-gated
registry (``serve_game --canary-gate``, quality/canary.py), whose shadow
scores diverge from the incumbent past the bound — is skipped (its
``model_reload_rejected`` event/metric is the operator's signal), but
the seen/rejected set is keyed by CONTENT (:func:`candidate_content_key`,
a stat fold over the entry's tree), not by name alone: a corrected
republish under the SAME directory name changes the key and is
re-attempted on the next poll. The fleet-side watcher
(``fleet/watcher.py``) reuses the same keying.

Waiting uses ``threading.Event.wait`` — serving code never sleeps
(hygiene rule 2) and never reads ``perf_counter`` (telemetry hygiene).
"""

from __future__ import annotations

import hashlib
import logging
import os
import threading
from typing import Optional

from photon_ml_tpu.resilience.faults import fault_point
from photon_ml_tpu.serving.registry import ModelRegistry

logger = logging.getLogger(__name__)


def candidate_content_key(path: str) -> str:
    """Cheap content identity of a candidate directory: a fold of every
    file's (relative path, size, mtime_ns), no data reads. Two publishes
    of byte-identical trees CAN key differently (mtime moves) — that only
    costs a redundant re-validate; what the key must guarantee is the
    converse, that an in-place CHANGE never reuses a rejected entry's key
    (the corrected-republish fix, ISSUE 17). Shared by the single-host
    and the fleet watch-dir pollers so both forget a rejection as soon as
    the entry's content moves."""
    h = hashlib.blake2s(digest_size=12)
    for dirpath, dirnames, filenames in os.walk(path):
        dirnames.sort()
        for name in sorted(filenames):
            fp = os.path.join(dirpath, name)
            try:
                st = os.stat(fp)
            except OSError:
                continue  # racing publisher; next poll re-keys
            h.update(f"{os.path.relpath(fp, path)}|{st.st_size}|"
                     f"{st.st_mtime_ns}\n".encode())
    return h.hexdigest()


class ModelDirectoryWatcher:
    """Polls ``watch_dir`` for new model/patch directories and applies
    them to ``registry`` through validate-then-activate."""

    def __init__(self, registry: ModelRegistry, watch_dir: str, *,
                 poll_s: float = 10.0):
        self.registry = registry
        self.watch_dir = watch_dir
        self.poll_s = float(poll_s)
        #: the poller thread mutates these while tests (and a future
        #: /healthz payload) read them — the lock-discipline pass flagged
        #: the bare mutations, so they now share a lock
        self._lock = threading.Lock()
        #: (entry name, content key) pairs already attempted — content
        #: keyed, so a republish in place re-attempts (module docstring)
        self._seen: set[tuple[str, str]] = set()  # guarded-by: _lock
        self._stop = threading.Event()
        #: start/stop are operator-lifecycle calls from one control thread
        self._thread: Optional[threading.Thread] = None  # guarded-by: caller
        self.n_applied = 0  # guarded-by: _lock
        self.n_rejected = 0  # guarded-by: _lock

    # --- one poll ---------------------------------------------------------
    def scan_once(self) -> int:
        """Apply every unseen entry (sorted by name); returns how many
        activated. Directly callable — the thread loop is just this on a
        timer, and tests drive it synchronously."""
        # chaos site: a faulted tick is swallowed by the poll loop and the
        # NEXT tick picks up whatever this one missed (nothing is marked
        # seen before its reload attempt, so no candidate is lost)
        fault_point("serving.watch_tick", dir=self.watch_dir)
        try:
            names = sorted(
                n for n in os.listdir(self.watch_dir)
                if not n.startswith(".")
                and os.path.isdir(os.path.join(self.watch_dir, n)))
        except FileNotFoundError:
            return 0  # publish dir not created yet — nothing to do
        applied = 0
        for name in names:
            path = os.path.join(self.watch_dir, name)
            # key BEFORE the attempt: a publisher updating the entry
            # mid-attempt changes the key and the next poll re-tries
            key = (name, candidate_content_key(path))
            with self._lock:
                if key in self._seen:
                    continue
            try:
                from photon_ml_tpu.io.model_io import resolve_game_model_dir

                resolve_game_model_dir(path)
            except FileNotFoundError:
                # not a model dir (scratch, logs, …): ignore but DON'T
                # mark seen — a run dir whose best/ publishes later must
                # still be picked up
                continue
            with self._lock:
                self._seen.add(key)
            try:
                sm = self.registry.reload(path)
            except Exception as e:
                # rejected candidates never disturb the active version;
                # the registry already posted model_reload_rejected
                with self._lock:
                    self.n_rejected += 1
                logger.warning("watch-dir candidate %s rejected: %r",
                               path, e)
                continue
            with self._lock:
                self.n_applied += 1
            applied += 1
            if sm.canary is not None:
                logger.info(
                    "watch-dir activated %s as version %d (canary: %s, "
                    "divergence %.4g over %d records)", path, sm.version,
                    sm.canary["verdict"], sm.canary["divergence"],
                    sm.canary["n"])
            else:
                logger.info("watch-dir activated %s as version %d", path,
                            sm.version)
        return applied

    # --- lifecycle --------------------------------------------------------
    def start(self) -> "ModelDirectoryWatcher":
        def loop() -> None:
            # immediate first scan (catch-up on restart), then the timer
            while True:
                try:
                    self.scan_once()
                except Exception:
                    logger.exception("watch-dir scan failed; will retry")
                if self._stop.wait(self.poll_s):
                    return

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="photon-serving-watch")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
