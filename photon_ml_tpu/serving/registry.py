"""Versioned model registry: load, validate, pin, and hot-swap GAME models.

The training side writes model directories
(:func:`photon_ml_tpu.io.model_io.save_game_model`); the serving side must
pick one up, answer traffic from it, and later swap in a newer version
WITHOUT downtime. The registry owns that lifecycle:

- :meth:`ModelRegistry.load` reads a ``train_game`` output dir through the
  shared resolution helpers (``resolve_game_model_dir`` /
  ``find_feature_index_dir``), builds the dense per-entity stores and a
  fresh :class:`~photon_ml_tpu.serving.engine.ScoringEngine`, and registers
  the result under a monotonically increasing version id.
- **Validation before activation** (the checkpoint manager's
  walk-back-past-corrupt discipline, applied forward): the ENTIRE load —
  metadata parse, index maps, every coefficient part file, store packing —
  completes under the resilience ``retry`` policy before the version
  becomes visible. A corrupt candidate raises and the previously active
  version keeps serving, exactly as a corrupt checkpoint step falls back
  to the previous step.
- **Atomic hot-swap**: :meth:`activate` replaces one reference under a
  lock. In-flight requests already hold their version's ``ServingModel``
  (engine + device tables) and finish on it; new requests see the new
  version. Old versions stay registered (instant rollback) until
  :meth:`retire` drops them.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from typing import Mapping, Optional, Sequence

from photon_ml_tpu.events import EventBus, GLOBAL_BUS
from photon_ml_tpu.game.model import FixedEffectModel, GameModel
from photon_ml_tpu.io.data_reader import FeatureShardConfig
from photon_ml_tpu.io.index import IndexMap
from photon_ml_tpu.io.model_io import (
    PATCH_KIND,
    find_feature_index_dir,
    game_model_entity_vocabs,
    load_game_model,
    model_lineage_id,
    resolve_game_model_dir,
)
from photon_ml_tpu.quality import (
    CanaryConfig,
    QualityMonitor,
    RequestReservoir,
    find_baseline,
    load_baseline,
    run_canary,
)
from photon_ml_tpu.serving.engine import ScoringEngine
from photon_ml_tpu.serving.store import TABLE_DTYPES, EntityCoefficientStore
from photon_ml_tpu.telemetry import metrics as _metrics

#: resident bytes of the ACTIVE version's dense coefficient tables (rows +
#: int8 scale vectors), per coordinate and storage dtype — the gauge that
#: proves the quantized-table footprint win (int8 ≥ 3.5x under f32)
_TABLE_BYTES = _metrics.gauge(
    "photon_serving_table_bytes",
    "Device bytes of the active serving coefficient table",
    labels=("coordinate", "dtype"))

#: item-axis size of the ACTIVE version's retrieval index (0 when ranking
#: is disabled) — host-owned like queue depth: each serving process ranks
#: its own item shard, so a fleet aggregate fans this out per process
_RANK_ITEMS = _metrics.gauge(
    "photon_rank_items",
    "Items in the active version's retrieval index (the /rank candidate "
    "vocabulary; 0 = ranking disabled)")
_metrics.mark_host_owned("photon_rank_items")

#: how many probe users the rank-drift reference pins (quality/baseline)
_RANK_PROBE_USERS = 16


@dataclasses.dataclass(frozen=True)
class ServingModel:
    """One immutable, fully materialized model version: everything a
    request needs, so a swap can never tear its state."""

    version: int
    model_dir: str
    model: GameModel
    index_maps: Mapping[str, IndexMap]
    stores: Mapping[str, EntityCoefficientStore]
    engine: ScoringEngine
    #: content identity (io.model_io.model_lineage_id) of the model this
    #: version serves — for a patched version, the patch's ``modelId``
    #: (the equivalent merged full model), so patches chain
    lineage: Optional[str] = None
    #: raw→dense entity-id universe the version's models were loaded
    #: under; a patch's entities are remapped into it before merging
    entity_vocabs: Mapping[str, Mapping[str, int]] = dataclasses.field(
        default_factory=dict)
    #: lineage of the model THIS one was trained from (metadata
    #: ``parentModel`` — the continuous-training chain, surfaced by
    #: ``/healthz`` so a fleet probe sees what refreshed into what)
    parent_lineage: Optional[str] = None
    #: train-time quality profile discovered next to the model dir
    #: (quality/baseline.py); seeds the engine's online monitor
    baseline: object = None
    #: canary annotation of this version's activation (divergence vs the
    #: incumbent over the request reservoir), None when not evaluated
    canary: Optional[Mapping] = None
    #: this version's top-k retrieval engine
    #: (:class:`~photon_ml_tpu.retrieval.engine.RankingEngine`), built
    #: when the registry was configured with a rank coordinate; patches
    #: derive its ItemIndex incrementally and share the parent's
    #: executables. None = ranking disabled.
    rank_engine: object = None
    #: the bucket→shard table this version's stores were packed under
    #: (``fleet/sharding.py::ShardMap``; None on an unsharded host) —
    #: activating the version swaps the registry's active map WITH it,
    #: so a reshard epoch and its rollback move stores and map as one
    shard_map: object = None

    def score(self, records: Sequence[dict]):
        return self.engine.score(records)

    def rank(self, records: Sequence[dict], ks: Sequence[int]):
        if self.rank_engine is None:
            raise RuntimeError("ranking is not enabled on this registry "
                               "(pass rank_coordinate=)")
        return self.rank_engine.rank(records, ks)


class ModelRegistry:
    """Thread-safe version store with one pinned *active* version."""

    def __init__(self, shard_configs: Sequence[FeatureShardConfig], *,
                 max_batch: int = 1024, warmup: bool = False,
                 table_dtype: str = "float32",
                 canary: Optional[CanaryConfig] = None,
                 rank_coordinate: Optional[str] = None,
                 rank_max_k: int = 128,
                 fleet_shard: Optional[tuple] = None,
                 bus: Optional[EventBus] = None):
        if table_dtype not in TABLE_DTYPES:
            raise ValueError(f"unknown table_dtype {table_dtype!r}; "
                             f"expected one of {TABLE_DTYPES}")
        from photon_ml_tpu.fleet.sharding import ShardMap, check_shard

        #: this host's fleet shard ``(index, count)``: every loaded
        #: version's coefficient stores pack only the raw ids hashing to
        #: it (fleet/sharding.py), and per-host coefficient patches
        #: carrying a DIFFERENT ``fleetShard`` are refused at validation.
        #: None = unsharded single-host serving, the historical behavior.
        self.fleet_shard = check_shard(fleet_shard)
        #: the ACTIVE bucket→shard table (None when unsharded). Starts as
        #: the default map — placement identical to plain ``shard_of_id``
        #: — and moves only through :meth:`prepare_reshard` + activation,
        #: so the stores and the map can never disagree.
        self.shard_map = (None if self.fleet_shard is None
                          else ShardMap.default(self.fleet_shard[1]))
        self.shard_configs = tuple(shard_configs)
        self.max_batch = max_batch
        self.warmup = warmup
        #: canary-activation policy (quality/canary.py): None disables
        #: shadow-scoring entirely; CanaryConfig(gate=False) annotates
        #: activations; gate=True refuses divergent candidates
        self.canary = canary
        #: bounded uniform sample of recent live request records — the
        #: canary's shadow-scoring workload (fed by ServingService.score
        #: via observe_requests; harmless and empty when unused)
        self.reservoir = RequestReservoir()
        #: storage format every loaded version's coefficient tables use;
        #: patches derive from the parent store, so the dtype survives
        #: delta activations without re-reading this field
        self.table_dtype = table_dtype
        #: random-effect coordinate whose entity axis ``/rank`` retrieves
        #: over (None = ranking disabled); every loaded version then gets
        #: an ItemIndex + RankingEngine next to its scoring engine
        self.rank_coordinate = rank_coordinate
        self.rank_max_k = int(rank_max_k)
        self.bus = bus if bus is not None else GLOBAL_BUS
        # lifecycle events (model_loaded/activated/rejected) become metrics
        # (reload counters, active-version gauge) via the telemetry bridge;
        # binding here — idempotently — means every registry's bus feeds
        # /metrics without the embedder wiring anything
        from photon_ml_tpu.telemetry import bridge

        bridge.bind(bus=self.bus)
        self._lock = threading.Lock()
        self._versions: dict[int, ServingModel] = {}  # guarded-by: _lock
        self._active: Optional[ServingModel] = None  # guarded-by: _lock
        self._next_version = 1  # guarded-by: _lock

    # --- queries ----------------------------------------------------------
    def active(self) -> ServingModel:
        sm = self._active
        if sm is None:
            raise RuntimeError("no active model version (load one first)")
        return sm

    def active_or_none(self) -> Optional[ServingModel]:
        return self._active

    @property
    def active_version(self) -> Optional[int]:
        sm = self._active
        return None if sm is None else sm.version

    def versions(self) -> list[int]:
        with self._lock:
            return sorted(self._versions)

    def get(self, version: int) -> ServingModel:
        with self._lock:
            return self._versions[version]

    def observe_requests(self, records: Sequence[dict]) -> None:
        """Feed scored request records into the canary reservoir (the
        serving front end calls this per request; cheap bookkeeping)."""
        self.reservoir.add(records)

    @property
    def shard_map_hash(self) -> Optional[str]:
        """Content hash of the ACTIVE shard map (None when unsharded) —
        rides every response next to ``lineage`` and is what the router
        and the host compare to refuse a mixed-map fan-out."""
        sm = self.shard_map
        return None if sm is None else sm.map_hash

    # --- lifecycle --------------------------------------------------------
    def load(self, model_dir: str, *, activate: bool = True) -> ServingModel:
        """Load + validate a candidate dir; register (and by default
        activate) it. Raises without touching the active version when the
        candidate is unreadable or structurally invalid."""
        from photon_ml_tpu.resilience import retry

        name = f"serving.load:{os.path.basename(os.path.normpath(model_dir))}"
        try:
            loaded = retry(lambda: self._load_validated(model_dir), name=name)
            # structural validation passed; now the PREDICTIONS are
            # judged: shadow-score the request reservoir against the
            # incumbent (quality/canary.py). A CanaryRejected under the
            # gate takes the same reject path as a corrupt candidate.
            loaded["canary"] = self._canary_evaluate(loaded)
        except Exception as e:
            # the reject is part of the observable lifecycle: the bridge
            # counts it (photon_model_reload_rejects_total) and operators
            # alert on it — a fleet silently failing to pick up new models
            # is the exact failure /reload was built to surface
            self.bus.post("model_reload_rejected", path=model_dir,
                          error=repr(e))
            raise
        with self._lock:
            version = self._next_version
            self._next_version += 1
            sm = ServingModel(version=version, **loaded)
            self._versions[version] = sm
        if self.warmup:
            # compile every bucket OUTSIDE the swap lock: traffic keeps
            # flowing on the old version while the new one warms
            sm.engine.warmup()
            if sm.rank_engine is not None:
                sm.rank_engine.warmup()
        self.bus.post("model_loaded", version=version,
                      path=sm.model_dir,
                      n_entities={cid: s.n_entities
                                  for cid, s in sm.stores.items()})
        if activate:
            self.activate(version)
        return sm

    def activate(self, version: int) -> ServingModel:
        """Atomically pin ``version`` as active. In-flight requests keep
        the reference they already grabbed — they finish on the old
        version; nothing is torn down here."""
        with self._lock:
            sm = self._versions[version]
            previous = self._active
            self._active = sm
            if sm.shard_map is not None:
                # the map travels WITH the version: a reshard epoch's
                # activation (or its rollback) swaps stores and routing
                # table in the same atomic pin
                self.shard_map = sm.shard_map
        for cid, store in sm.stores.items():
            _TABLE_BYTES.labels(coordinate=cid,
                                dtype=store.table_dtype).set(
                                    store.table_bytes)
        _RANK_ITEMS.set(0 if sm.rank_engine is None
                        else sm.rank_engine.index.n_items)
        self.bus.post("model_activated", version=sm.version,
                      previous=None if previous is None
                      else previous.version)
        return sm

    def reload(self, model_dir: str) -> ServingModel:
        """The ``/reload`` endpoint's verb: load-validate-activate. Routes
        by the candidate's metadata ``kind`` — full model dirs rebuild the
        tables, coefficient patches overlay the active version's
        (:meth:`load_patch`) — so one publish directory can mix both."""
        try:
            from photon_ml_tpu.io.model_io import model_kind
            from photon_ml_tpu.resilience import fault_point

            # chaos site: a faulted reload takes the same reject path as
            # a corrupt candidate — the incumbent version keeps serving
            fault_point("serving.reload", path=model_dir)
            kind = model_kind(resolve_game_model_dir(model_dir))
        except Exception as e:
            self.bus.post("model_reload_rejected", path=model_dir,
                          error=repr(e))
            raise
        if kind == PATCH_KIND:
            return self.load_patch(model_dir, activate=True)
        return self.load(model_dir, activate=True)

    def prepare(self, model_dir: str) -> ServingModel:
        """Phase one of a coordinated two-phase activation (SERVING.md
        "Fleet serving"): fully validate + canary the candidate and
        REGISTER it — warmed, ready to pin — without activating. The
        router gates once over every host's prepare verdict, then drives
        :meth:`activate` (phase two) everywhere, or :meth:`retire` (the
        abort) on any refusal; either way the incumbent keeps serving
        until the whole fleet has agreed. Routes full dirs vs patches by
        metadata ``kind``, exactly like :meth:`reload`."""
        try:
            from photon_ml_tpu.io.model_io import model_kind
            from photon_ml_tpu.resilience import fault_point

            # the same chaos surface as a one-shot reload: a faulted
            # prepare refuses the candidate, the incumbent keeps serving
            fault_point("serving.reload", path=model_dir, phase="prepare")
            kind = model_kind(resolve_game_model_dir(model_dir))
        except Exception as e:
            self.bus.post("model_reload_rejected", path=model_dir,
                          error=repr(e))
            raise
        if kind == PATCH_KIND:
            return self.load_patch(model_dir, activate=False)
        return self.load(model_dir, activate=False)

    def prepare_reshard(self, shard_map) -> "tuple[ServingModel, dict]":
        """Phase one of a LIVE RESHARD epoch: repack the active version's
        stores under a candidate bucket→shard table and register the
        result — warmed, ready to pin — without activating. Returns
        ``(prepared, moved)`` where ``moved`` counts this host's row
        movement per direction (``moved_in`` / ``moved_out`` /
        ``retained``): only ids whose BUCKET was reassigned appear in the
        moved tallies — the O(moved) contract chaos asserts. The model
        content is untouched (same lineage, same coefficients); a
        coordinate whose membership did not change shares the incumbent's
        device table outright and costs zero recompiles. Runs the same
        ``serving.reload`` fault surface as a model prepare, so an
        injected refusal aborts the fleet epoch with the incumbent map
        serving everywhere."""
        from photon_ml_tpu.fleet.sharding import ShardMap
        from photon_ml_tpu.resilience import fault_point

        if not isinstance(shard_map, ShardMap):
            shard_map = ShardMap.from_dict(shard_map)
        parent = self.active()
        if self.fleet_shard is None:
            raise ValueError(
                "reshard needs a fleet-sharded host (serve with "
                "--fleet-shard/--fleet-shard-count); an unsharded host "
                "has no bucket table to move")
        if shard_map.n_shards != self.fleet_shard[1]:
            raise ValueError(
                f"shard map names {shard_map.n_shards} shards, this "
                f"fleet has {self.fleet_shard[1]} hosts per replica "
                f"group — resizing the host set is a topology change, "
                f"not a map move")
        index = self.fleet_shard[0]
        moved = {"moved_in": 0, "moved_out": 0, "retained": 0}
        try:
            fault_point("serving.reload",
                        path=f"shard-map:{shard_map.map_hash}",
                        phase="prepare")
            stores: dict[str, EntityCoefficientStore] = {}
            for cid, store in parent.stores.items():
                t = store.random_effect_type
                vocab = parent.entity_vocabs.get(t, {})
                old_ids = set(store.row_of_id)
                new_ids = {raw for raw in vocab
                           if shard_map.owns(raw, index)}
                moved["moved_in"] += len(new_ids - old_ids)
                moved["moved_out"] += len(old_ids - new_ids)
                moved["retained"] += len(old_ids & new_ids)
                if new_ids == old_ids:
                    # membership unchanged: alias the incumbent device
                    # table (zero bytes moved, zero recompiles) — only
                    # the governing map reference advances
                    stores[cid] = dataclasses.replace(
                        store, shard_map=shard_map)
                else:
                    stores[cid] = EntityCoefficientStore.build(
                        parent.model.coordinates[cid], vocab,
                        table_dtype=self.table_dtype,
                        shard=self.fleet_shard, shard_map=shard_map)
            engine = ScoringEngine(
                parent.model, self.shard_configs, parent.index_maps,
                stores, max_batch=self.max_batch,
                share_from=parent.engine)
            rank_engine = None
            if self.rank_coordinate is not None:
                rank_store = stores.get(self.rank_coordinate)
                unchanged = (
                    rank_store is not None
                    and parent.stores.get(self.rank_coordinate) is not None
                    and rank_store.table
                    is parent.stores[self.rank_coordinate].table)
                rank_engine = self._build_rank_engine(
                    engine, stores,
                    index=(parent.rank_engine.index
                           if unchanged and parent.rank_engine is not None
                           else None),
                    share_from=(parent.rank_engine if unchanged else None))
            engine.monitor = QualityMonitor(parent.baseline)
        except Exception as e:
            self.bus.post("model_reload_rejected",
                          path=f"shard-map:{shard_map.map_hash}",
                          error=repr(e))
            raise
        with self._lock:
            version = self._next_version
            self._next_version += 1
            sm = ServingModel(
                version=version, model_dir=parent.model_dir,
                model=parent.model, index_maps=parent.index_maps,
                stores=stores, engine=engine, lineage=parent.lineage,
                entity_vocabs=parent.entity_vocabs,
                parent_lineage=parent.parent_lineage,
                baseline=parent.baseline, canary=None,
                rank_engine=rank_engine, shard_map=shard_map)
            self._versions[version] = sm
        if self.warmup:
            sm.engine.warmup()
            if sm.rank_engine is not None:
                sm.rank_engine.warmup()
        self.bus.post("model_loaded", version=version, path=sm.model_dir,
                      n_entities={cid: s.n_entities
                                  for cid, s in sm.stores.items()})
        return sm, moved

    def load_patch(self, patch_dir: str, *,
                   activate: bool = True) -> ServingModel:
        """Derive version N+1 from the ACTIVE version by overlaying an
        entity-level coefficient patch: only the touched rows of the dense
        device tables are overwritten (``EntityCoefficientStore.
        apply_patch``), untouched coordinates share the parent's tables
        outright. Validated like any candidate — metadata checks, lineage
        match against the active version, every part file read — before
        anything registers; a failure (including an ``io.delta_publish``
        injected fault) leaves the active version serving and the registry
        unchanged."""
        from photon_ml_tpu.resilience import retry

        name = f"serving.patch:{os.path.basename(os.path.normpath(patch_dir))}"
        try:
            loaded = retry(lambda: self._load_patch_validated(patch_dir),
                           name=name)
            loaded["canary"] = self._canary_evaluate(loaded)
        except Exception as e:
            self.bus.post("model_reload_rejected", path=patch_dir,
                          error=repr(e))
            raise
        with self._lock:
            version = self._next_version
            self._next_version += 1
            sm = ServingModel(version=version, **loaded)
            self._versions[version] = sm
        if self.warmup:
            sm.engine.warmup()
            if sm.rank_engine is not None:
                # a shared-executable patch engine warms for free (every
                # shape is already in the parent's cache)
                sm.rank_engine.warmup()
        self.bus.post("model_loaded", version=version, path=sm.model_dir,
                      n_entities={cid: s.n_entities
                                  for cid, s in sm.stores.items()})
        if activate:
            self.activate(version)
        return sm

    def retire(self, version: int) -> None:
        """Drop a non-active version (frees its device tables once
        in-flight holders release their references)."""
        with self._lock:
            if self._active is not None and self._active.version == version:
                raise ValueError(f"version {version} is active; activate "
                                 "another version before retiring it")
            self._versions.pop(version, None)

    # --- internals --------------------------------------------------------
    def _load_validated(self, model_dir: str) -> dict:
        model_dir = resolve_game_model_dir(model_dir)
        index_dir = find_feature_index_dir(model_dir)
        with open(os.path.join(model_dir, "model-metadata.json")) as f:
            metadata = json.load(f)
        self._check_metadata(model_dir, metadata)
        index_maps = {
            cfg.shard_id: IndexMap.load(
                os.path.join(index_dir, f"{cfg.shard_id}.json"))
            for cfg in self.shard_configs}
        # model-derived entity vocabs: the model's saved per-entity records
        # are serving's id universe (there is no dataset to build one from)
        vocabs = game_model_entity_vocabs(model_dir, metadata)
        model = load_game_model(model_dir, index_maps, vocabs)
        stores = {
            cid: EntityCoefficientStore.build(
                cm, vocabs[cm.random_effect_type],
                table_dtype=self.table_dtype, shard=self.fleet_shard,
                shard_map=self.shard_map)
            for cid, cm in model.coordinates.items()
            if not isinstance(cm, FixedEffectModel)}
        # a reloaded model with the incumbent's coordinate structure
        # reuses its jitted program outright (tables ride as arguments —
        # engine.py::_ScoreProgram): a same-shape hot-swap or canary
        # candidate warms with zero compiles, which is most production
        # reloads and every patch
        incumbent = self._active
        engine = ScoringEngine(
            model, self.shard_configs, index_maps, stores,
            max_batch=self.max_batch,
            share_from=None if incumbent is None else incumbent.engine)
        rank_engine = self._build_rank_engine(
            engine, stores,
            share_from=None if incumbent is None
            else incumbent.rank_engine)
        # train-time quality profile, published at the run root by the
        # training/refresh drivers; absent baselines degrade the online
        # monitor (no score bins), never the load
        baseline = load_baseline(find_baseline(model_dir))
        # a FULL load pins the rank-drift reference: the probe users'
        # top-k as this model ranks them (patches inherit it, so a
        # patched table's ranking shift shows up as rank_overlap drift)
        baseline = self._pin_rank_reference(baseline, rank_engine, stores)
        engine.monitor = QualityMonitor(baseline)
        return {"model_dir": model_dir, "model": model,
                "index_maps": index_maps, "stores": stores,
                "engine": engine, "rank_engine": rank_engine,
                "lineage": model_lineage_id(model_dir),
                "parent_lineage": metadata.get("parentModel"),
                "baseline": baseline,
                "entity_vocabs": vocabs,
                "shard_map": self.shard_map}

    # --- ranking ----------------------------------------------------------
    def _build_rank_engine(self, engine: ScoringEngine, stores, *,
                           index=None, share_from=None):
        """The version's RankingEngine (None when ranking is disabled).
        ``index`` overrides the from-scratch ItemIndex build (the patch
        path passes the incrementally derived one); ``share_from`` reuses
        a compatible parent engine's executables."""
        if self.rank_coordinate is None:
            return None
        from photon_ml_tpu.retrieval import ItemIndex, RankingEngine

        store = stores.get(self.rank_coordinate)
        if store is None:
            raise ValueError(
                f"rank coordinate {self.rank_coordinate!r} is not a "
                f"random-effect coordinate of this model "
                f"(have {sorted(stores)})")
        if index is None:
            index = ItemIndex.build(store, self.rank_coordinate)
        return RankingEngine(engine, index, max_k=self.rank_max_k,
                             share_from=share_from)

    def _pin_rank_reference(self, baseline, rank_engine, stores):
        """Attach the rank-drift reference (deterministic probe users →
        their current top-k ids) to a freshly loaded FULL model's
        baseline. Needs both a baseline and a rank engine; k is bounded
        by the vocabulary. Ranking here happens at load time, before
        activation — never on the request path."""
        if baseline is None or rank_engine is None \
                or baseline.rank_probes is not None \
                or rank_engine.index.n_items == 0:
            return baseline
        from photon_ml_tpu.quality import (
            rank_probe_records,
            rank_probe_sample,
        )

        user_ids: list = []
        for cid in rank_engine._rank_re_order:
            user_ids.extend(stores[cid].row_of_id)
        if not user_ids:
            # single-coordinate models rank every user cold; the probes
            # are synthetic unknown ids (still a valid, stable reference)
            user_ids = [f"__rank_probe_{i}" for i in range(_RANK_PROBE_USERS)]
        probes = rank_probe_sample(user_ids, _RANK_PROBE_USERS)
        k = min(10, rank_engine.max_k, rank_engine.index.n_items)
        results = rank_engine.rank(
            rank_probe_records(probes, rank_engine.user_entity_types),
            [k] * len(probes))
        return dataclasses.replace(
            baseline, rank_k=k,
            rank_probes={u: tuple(ids)
                         for u, (ids, _) in zip(probes, results)})

    def _load_patch_validated(self, patch_dir: str) -> dict:
        from photon_ml_tpu.resilience import fault_point

        parent = self.active_or_none()
        if parent is None:
            raise RuntimeError(
                "patch activation needs an active parent version (load a "
                "full model first)")
        model_dir = resolve_game_model_dir(patch_dir)
        with open(os.path.join(model_dir, "model-metadata.json")) as f:
            metadata = json.load(f)
        if metadata.get("kind") != PATCH_KIND:
            raise ValueError(
                f"{model_dir}: not a coefficient patch "
                f"(kind={metadata.get('kind')!r})")
        want = metadata.get("parentModel")
        if not want or want != parent.lineage:
            raise ValueError(
                f"{model_dir}: patch parentModel {want!r} does not match "
                f"the active version's lineage {parent.lineage!r} — a "
                f"patch only overlays the exact model it was computed "
                f"against (refresh from the currently served model, or "
                f"publish a full model instead)")
        patch_shard = metadata.get("fleetShard")
        patch_count = metadata.get("fleetShardCount")
        if patch_count is not None:
            # a per-host patch (refresh_game --fleet-shards) names the ONE
            # shard whose rows it carries; applying it anywhere else would
            # silently leave that host's slice stale while claiming the
            # merged model's lineage — refuse foreign shards outright
            want_shard = (int(patch_shard), int(patch_count))
            if self.fleet_shard is None:
                raise ValueError(
                    f"{model_dir}: patch is for fleet shard "
                    f"{want_shard[0]}/{want_shard[1]} but this host is "
                    f"unsharded — serve with --fleet-shard/"
                    f"--fleet-shard-count or publish a global patch")
            if want_shard != self.fleet_shard:
                raise ValueError(
                    f"{model_dir}: patch is for fleet shard "
                    f"{want_shard[0]}/{want_shard[1]}, this host holds "
                    f"shard {self.fleet_shard[0]}/{self.fleet_shard[1]} "
                    f"— a foreign shard's patch never applies")
        self._check_metadata(model_dir, metadata)
        patch_vocabs = game_model_entity_vocabs(model_dir, metadata)
        # the patch rides its parent's feature space by contract (the
        # refresh presets the parent's index maps), so the parent's loaded
        # maps ARE the patch's — no re-read, and no way to drift
        patch_model = load_game_model(model_dir, parent.index_maps,
                                      patch_vocabs)
        # the activation-side fault window: everything validated, nothing
        # registered — an injected fault here must leave the active
        # version serving and the registry consistent
        fault_point("io.delta_publish", path=model_dir)
        # union id universe: the parent's vocab extended by new entities
        vocabs: dict = {t: dict(v)
                        for t, v in parent.entity_vocabs.items()}
        for t, pv in patch_vocabs.items():
            tgt = vocabs.setdefault(t, {})
            for raw in pv:
                tgt.setdefault(raw, len(tgt))
        removed_by_cid = {
            cid: info.get("removedEntities") or []
            for cid, info in metadata["coordinates"].items()}
        coordinates = dict(parent.model.coordinates)
        stores: dict[str, EntityCoefficientStore] = {}
        for cid, cm in parent.model.coordinates.items():
            if isinstance(cm, FixedEffectModel):
                if cid in patch_model.coordinates:
                    coordinates[cid] = patch_model.coordinates[cid]
                continue
            upd = patch_model.coordinates.get(cid)
            removed = removed_by_cid.get(cid, [])
            if upd is None and not removed:
                # untouched coordinate: the parent's device table is
                # shared, not copied — versions alias immutable arrays
                stores[cid] = parent.stores[cid]
                continue
            t = cm.random_effect_type
            drop_dense = [vocabs[t][raw] for raw in removed
                          if raw in vocabs[t]]
            if upd is not None:
                # host-side model merge keeps ServingModel.model truthful
                # (the engine scores from the stores; the model backs
                # introspection and any batch-path reuse)
                lut = {int(patch_vocabs[t][raw]): int(vocabs[t][raw])
                       for raw in patch_vocabs[t]}
                upd_union = upd.remap_entities(lut)
            else:
                upd_union = dataclasses.replace(
                    cm, keys=cm.keys[:0], coeffs=cm.coeffs[:0],
                    variances=None, coeffs_device=None)
            coordinates[cid] = cm.merge(upd_union,
                                        drop_entities=drop_dense)
            stores[cid] = parent.stores[cid].apply_patch(
                upd, patch_vocabs.get(t, {}), removed=removed)
        model = GameModel(coordinates=coordinates,
                          task=parent.model.task)
        # the derived engine SHARES the parent's jitted executables (the
        # coordinate structure is identical; tables ride as arguments), so
        # a patch that appends no new table rows activates with zero
        # compiles — on a fleet, every untouched host swaps for free
        engine = ScoringEngine(model, self.shard_configs,
                               parent.index_maps, stores,
                               max_batch=self.max_batch,
                               share_from=parent.engine)
        rank_engine = None
        if self.rank_coordinate is not None:
            parent_rank = parent.rank_engine
            cid = self.rank_coordinate
            index = None if parent_rank is None else parent_rank.index
            if index is not None and stores[cid] is not parent.stores[cid]:
                # the patch touched the item coordinate: re-gather ONLY
                # the touched rows into the next index (new items append
                # inside the padding headroom — same shapes, no retrace)
                t = model.coordinates[cid].random_effect_type
                touched = list(patch_vocabs.get(t, {})) \
                    + list(removed_by_cid.get(cid, []))
                index = index.apply_patch(stores[cid], touched)
            rank_engine = self._build_rank_engine(
                engine, stores, index=index, share_from=parent_rank)
        # the refresh publishes its baseline at ITS run root (the patch's
        # parent dir); when the patch was shipped alone, inherit the
        # incumbent's baseline rather than serve unmonitored
        baseline = load_baseline(find_baseline(model_dir)) or parent.baseline
        if baseline is not None and baseline.rank_probes is None \
                and parent.baseline is not None \
                and parent.baseline.rank_probes is not None:
            # the rank-drift reference chains through patches: a patched
            # table's ranking shift is measured against the reference the
            # full parent load pinned, not re-pinned to itself
            baseline = dataclasses.replace(
                baseline, rank_k=parent.baseline.rank_k,
                rank_probes=parent.baseline.rank_probes)
        engine.monitor = QualityMonitor(baseline)
        return {"model_dir": model_dir, "model": model,
                "index_maps": parent.index_maps, "stores": stores,
                "engine": engine, "rank_engine": rank_engine,
                "lineage": metadata.get("modelId"),
                "parent_lineage": metadata.get("parentModel"),
                "baseline": baseline,
                "entity_vocabs": vocabs,
                "shard_map": parent.shard_map
                if parent.shard_map is not None else self.shard_map}

    def _canary_evaluate(self, loaded: dict) -> Optional[dict]:
        """Shadow-score the request reservoir through the validated
        candidate vs the incumbent. None (skipped) without a canary
        config, an incumbent, or enough reservoir traffic; raises
        CanaryRejected past the bound when the config gates."""
        cfg = self.canary
        if cfg is None:
            return None
        incumbent = self._active
        if incumbent is None:
            return None
        records = self.reservoir.sample()
        if len(records) < cfg.min_records:
            return None
        return run_canary(
            incumbent.engine.score, loaded["engine"].score, records,
            bound=cfg.bound_for(self.table_dtype), gate=cfg.gate,
            candidate_dir=loaded["model_dir"], bus=self.bus)

    def _check_metadata(self, model_dir: str, metadata: dict) -> None:
        """Structural validation before any heavy load — mirrors the
        checkpoint manifest checks: coordinate types known, shard ids
        covered by the serving config, every part file present."""
        known = {cfg.shard_id for cfg in self.shard_configs}
        coords = metadata.get("coordinates")
        if not coords:
            raise ValueError(f"{model_dir}: metadata names no coordinates")
        for cid, info in coords.items():
            if info.get("type") not in ("fixed-effect", "random-effect"):
                raise ValueError(
                    f"{model_dir}: coordinate {cid!r} has unknown type "
                    f"{info.get('type')!r}")
            if info.get("featureShardId") not in known:
                raise ValueError(
                    f"{model_dir}: coordinate {cid!r} uses feature shard "
                    f"{info.get('featureShardId')!r}, not in the serving "
                    f"--feature-shards config {sorted(known)}")
            part = os.path.join(model_dir, info["type"], cid,
                                "coefficients", "part-00000.avro")
            if not os.path.exists(part):
                raise FileNotFoundError(
                    f"{model_dir}: missing coefficient file {part}")
