"""Entity coefficient store: per-entity models packed for O(1) online lookup.

The batch path joins random-effect coefficients against a dataset with one
``searchsorted`` over the whole score set
(:meth:`photon_ml_tpu.game.model.RandomEffectModel.lookup`); a serving
request has no dataset — it names one entity by its RAW id and needs that
entity's coefficient row *now*. So each random-effect coordinate's sparse
``(entity·dim + feature) → coeff`` table is repacked once at model-load time
into a dense ``(n_entities + 1, dim)`` device array plus a host
``raw id → row`` dict: request-time lookup is one dict probe and one device
gather. The extra last row is all-zero — the landing slot for entities the
model has never seen, which therefore score exactly 0 from this coordinate
(the GLMix cold-start contract: unseen entities fall back to the fixed
effect alone, same as the batch path's not-found join).
"""

from __future__ import annotations

import dataclasses
from typing import Mapping, Optional, Sequence

import numpy as np

from photon_ml_tpu.game.model import RandomEffectModel


@dataclasses.dataclass(frozen=True)
class EntityCoefficientStore:
    """Dense per-entity coefficient table for one random-effect coordinate.

    ``table`` is ``(n_entities + 1, dim)`` float32 on device; row
    ``n_entities`` is the all-zero fallback row. ``row_of_id`` maps the raw
    entity id string to its table row.
    """

    random_effect_type: str
    feature_shard_id: str
    dim: int
    table: object  # jax.Array (n_entities + 1, dim) float32
    row_of_id: Mapping[str, int]

    @property
    def n_entities(self) -> int:
        return len(self.row_of_id)

    @property
    def fallback_row(self) -> int:
        return int(self.table.shape[0]) - 1

    def rows_for(self, raw_ids: Sequence[Optional[str]]) -> np.ndarray:
        """Table row per raw entity id; unseen/missing ids land on the
        zero fallback row."""
        fb = self.fallback_row
        get = self.row_of_id.get
        return np.fromiter(
            (fb if r is None else get(r, fb) for r in raw_ids),
            np.int32, count=len(raw_ids))

    @staticmethod
    def build(model: RandomEffectModel,
              entity_vocab: Mapping[str, int]) -> "EntityCoefficientStore":
        """Pack a loaded :class:`RandomEffectModel`'s sparse table densely.

        ``entity_vocab`` is the model-derived raw→dense id map
        (:func:`photon_ml_tpu.io.model_io.game_model_entity_vocabs`). Models
        fresh off disk are always in shard space (export back-projects), so
        a projector here is a usage error, not a supported layout.
        """
        import jax.numpy as jnp

        if model.projector is not None:
            raise ValueError(
                "serving expects shard-space models (call to_shard_space() "
                "before building a store); saved models are already "
                "back-projected by export")
        keys = np.asarray(model.keys, np.int64)
        ent = keys // model.dim
        feat = keys % model.dim
        uniq = np.unique(ent)
        dense = np.zeros((len(uniq) + 1, model.dim), np.float32)
        if len(keys):
            pos = np.searchsorted(uniq, ent)
            dense[pos, feat] = model.coeffs
        # dense entity id -> packed row, then raw id -> packed row; vocab
        # entries without coefficients (possible when coordinates sharing a
        # re_type merged vocabs) deliberately map to the fallback zeros row
        row_of_dense = {int(e): i for i, e in enumerate(uniq)}
        fallback = len(uniq)
        row_of_id = {raw: row_of_dense.get(d, fallback)
                     for raw, d in entity_vocab.items()}
        return EntityCoefficientStore(
            random_effect_type=model.random_effect_type,
            feature_shard_id=model.feature_shard_id,
            dim=model.dim, table=jnp.asarray(dense), row_of_id=row_of_id)
