"""Entity coefficient store: per-entity models packed for O(1) online lookup.

The batch path joins random-effect coefficients against a dataset with one
``searchsorted`` over the whole score set
(:meth:`photon_ml_tpu.game.model.RandomEffectModel.lookup`); a serving
request has no dataset — it names one entity by its RAW id and needs that
entity's coefficient row *now*. So each random-effect coordinate's sparse
``(entity·dim + feature) → coeff`` table is repacked once at model-load time
into a dense ``(n_entities + 1, dim)`` device array plus a host
``raw id → row`` dict: request-time lookup is one dict probe and one device
gather. The extra last row is all-zero — the landing slot for entities the
model has never seen, which therefore score exactly 0 from this coordinate
(the GLMix cold-start contract: unseen entities fall back to the fixed
effect alone, same as the batch path's not-found join).

Quantized tables (``table_dtype``): the dense table is the serving host's
dominant resident payload — at "hundreds of millions of entities" the f32
rows are what caps entities-per-host. ``bfloat16`` halves the bytes with a
plain cast; ``int8`` quarters them with per-row symmetric quantization
(``q = round(row / scale)``, ``scale = max|row| / 127`` per row — one f32
scale per entity, amortized over ``dim`` coefficients). Dequantization is
fused into the jitted score path (:func:`gather_rows` — gather int8 rows,
cast, multiply by the gathered scales), so the full-precision table is
NEVER materialized. Parity contract: ``float32`` stays bit-identical to
the batch scorer; ``bfloat16`` holds ~1e-2 relative score error and
``int8`` ~5e-2 (locked by the serving score-parity gates). This module is
the ONE home of table construction AND of the quantize/dequantize numeric
format (hygiene rule 5, ``tools/check_resilience_hygiene.py``): an ad-hoc
cast or scale-multiply of a ``.table`` array elsewhere would silently
disagree with the scale semantics here.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping, Optional, Sequence

import numpy as np

from photon_ml_tpu.fleet import sharding as _sharding
from photon_ml_tpu.game.model import RandomEffectModel

#: supported on-device table storage formats, in decreasing precision
TABLE_DTYPES = ("float32", "bfloat16", "int8")


def quantize_rows(rows: np.ndarray) -> "tuple[np.ndarray, np.ndarray]":
    """Per-row symmetric int8 quantization: ``(q int8 rows, f32 scales)``
    with ``row ≈ q * scale``. All-zero rows get scale 1.0 (any scale
    reconstructs zeros; 1.0 keeps the dequant multiply well-conditioned) —
    which makes the fallback row's dequantized value EXACTLY zero, so the
    cold-start contract survives quantization bit-for-bit."""
    rows = np.asarray(rows, np.float32)
    amax = (np.max(np.abs(rows), axis=1) if rows.size
            else np.zeros((rows.shape[0],), np.float32))
    scales = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.rint(rows / scales[:, None]), -127, 127).astype(np.int8)
    return q, scales


def _pack_table(dense: np.ndarray, table_dtype: str):
    """Host f32 dense rows → ``(device table, device scales | None)`` in
    the requested storage format. The single constructor both
    :meth:`EntityCoefficientStore.build` and the patch path's row
    requantization route through."""
    import jax.numpy as jnp

    if table_dtype == "float32":
        return jnp.asarray(dense, jnp.float32), None
    if table_dtype == "bfloat16":
        return jnp.asarray(dense, jnp.bfloat16), None
    if table_dtype == "int8":
        q, scales = quantize_rows(dense)
        return jnp.asarray(q), jnp.asarray(scales)
    raise ValueError(
        f"unknown table_dtype {table_dtype!r}; expected one of {TABLE_DTYPES}")


def gather_rows(params, rows, dtype):
    """Dequantizing row gather for the jitted score path: ``params`` is
    :attr:`EntityCoefficientStore.device_params` ``(table, scales)``;
    returns ``(n, dim)`` rows in ``dtype``. Traced inside the engine's
    scoring program, so the dequant (cast + per-row scale multiply for
    int8) fuses with the margin contraction — the full-precision table
    never exists in HBM. With f32 tables this is exactly the plain
    ``table[rows].astype(dtype)`` the engine always did: the f32
    online/batch bit-parity contract is untouched."""
    table, scales = params
    out = table[rows].astype(dtype)
    if scales is not None:
        out = out * scales[rows][:, None].astype(dtype)
    return out


@dataclasses.dataclass(frozen=True)
class EntityCoefficientStore:
    """Dense per-entity coefficient table for one random-effect coordinate.

    ``table`` is ``(n_entities + 1, dim)`` on device in ``table_dtype``
    storage (float32 / bfloat16 / int8); row ``n_entities`` is the
    fallback row (zeros — dequantizes to exact zeros in every format).
    ``row_of_id`` maps the raw entity id string to its table row.
    ``scales`` is the ``(n_entities + 1,)`` f32 per-row dequantization
    scale vector for int8 tables, ``None`` otherwise.
    """

    random_effect_type: str
    feature_shard_id: str
    dim: int
    table: object  # jax.Array (n_entities + 1, dim) in table_dtype
    row_of_id: Mapping[str, int]
    table_dtype: str = "float32"
    scales: object = None  # jax.Array (n_entities + 1,) f32 — int8 only
    #: fleet shard view ``(index, count)``: the table holds ONLY the raw
    #: ids hashing to this shard (``fleet/sharding.py::shard_of_id``);
    #: every other id lands on the fallback zeros row exactly like an
    #: unseen entity. None = unsharded (the single-host identity).
    shard: Optional[tuple] = None
    #: the explicit bucket→shard table governing ownership
    #: (``fleet/sharding.py::ShardMap``); None = the default map (plain
    #: ``shard_of_id`` hashing — identical placement). Carried so a
    #: post-reshard store patches and answers ownership by the MAP, not
    #: the default hash.
    shard_map: Optional[object] = None

    @property
    def n_entities(self) -> int:
        return len(self.row_of_id)

    @property
    def fallback_row(self) -> int:
        return int(self.table.shape[0]) - 1

    def shard_of(self, raw_id: str) -> Optional[int]:
        """Which fleet shard owns this raw id (None on an unsharded
        store). Delegates to the one hashing home,
        :func:`photon_ml_tpu.fleet.sharding` — the explicit
        :class:`~photon_ml_tpu.fleet.sharding.ShardMap` when one governs
        this store, the default hash otherwise."""
        if self.shard is None:
            return None
        if self.shard_map is not None:
            return self.shard_map.shard_of(raw_id)
        return _sharding.shard_of_id(raw_id, self.shard[1])

    def owns(self, raw_id: str) -> bool:
        """Is this raw id in this store's shard slice? (Unsharded stores
        own everything.) A sharded store still SCORES foreign ids — they
        fall back to the zeros row — but never packs rows for them."""
        if self.shard is not None and self.shard_map is not None:
            return self.shard_map.owns(raw_id, self.shard[0])
        return _sharding.owns_id(raw_id, self.shard)

    @property
    def device_params(self):
        """``(table, scales)`` — the engine's jit argument pytree; consume
        through :func:`gather_rows`."""
        return (self.table, self.scales)

    @property
    def table_bytes(self) -> int:
        """Resident device bytes of this coordinate's table (dense rows +
        int8 scale vector) — the ``photon_serving_table_bytes`` gauge."""
        n = int(np.prod(self.table.shape)) * self.table.dtype.itemsize
        if self.scales is not None:
            n += int(self.scales.shape[0]) * 4
        return n

    def rows_for(self, raw_ids: Sequence[Optional[str]]) -> np.ndarray:
        """Table row per raw entity id; unseen/missing ids land on the
        zero fallback row."""
        fb = self.fallback_row
        n = len(raw_ids)
        if n == 1:
            # the microbatched / single-lookup hot path: no generator, no
            # fromiter machinery for one probe
            r = raw_ids[0]
            return np.array([fb if r is None else self.row_of_id.get(r, fb)],
                            np.int32)
        get = self.row_of_id.get
        if all(r is None for r in raw_ids):
            # id-less traffic (/rank-style candidate batches, warmup
            # padding): one fill beats n dict probes through a generator
            return np.full(n, fb, np.int32)
        return np.fromiter(
            (fb if r is None else get(r, fb) for r in raw_ids),
            np.int32, count=n)

    def apply_patch(self, update: Optional[RandomEffectModel],
                    update_vocab: Mapping[str, int],
                    removed: Sequence[str] = (),
                    ) -> "EntityCoefficientStore":
        """Derive the NEXT version's table by overwriting only the touched
        rows — the continuous-training delta-activation path, O(touched)
        instead of the O(all entities) rebuild :meth:`build` performs.

        ``update`` is the patch's partial model (only re-solved entities)
        in ITS OWN dense-id space, with ``update_vocab`` mapping raw ids
        to those dense ids; rows are matched by RAW id, the stable
        cross-version identity. Entities already in this store have their
        row overwritten; new entities append fresh rows; ``removed`` raw
        ids (models dropped by the refresh's active-data bounds) have
        their rows zeroed, scoring exactly like the cold-start fallback.
        The update is FUNCTIONAL — this store's device table is never
        mutated (in-flight requests hold it), a new array is derived and
        the previous version stays instantly restorable. The derived
        store keeps this store's ``table_dtype``: touched rows are
        re-quantized in isolation (per-row scales make that exact — no
        other row's scale shifts), untouched rows are carried
        bit-identically.

        This method and :meth:`build` are the only sanctioned writers of
        serving device tables (hygiene rule 5,
        ``tools/check_resilience_hygiene.py``).
        """
        import jax.numpy as jnp

        if update is not None:
            if update.projector is not None:
                raise ValueError("patches must be shard-space models")
            if update.dim != self.dim:
                raise ValueError(
                    f"patch dim {update.dim} != store dim {self.dim}")
            if update.random_effect_type != self.random_effect_type:
                raise ValueError(
                    f"patch random-effect type "
                    f"{update.random_effect_type!r} != store "
                    f"{self.random_effect_type!r}")
        n_old = self.fallback_row
        updates: dict[int, np.ndarray] = {}
        new_raws: list[str] = []

        def target_row(raw: str) -> int:
            r = self.row_of_id.get(raw)
            if r is None or r == n_old:
                # unseen raw id, or a vocab-merge entry parked on the
                # fallback zeros row (never writable): append a fresh row
                new_raws.append(raw)
                return n_old + len(new_raws) - 1
            return r

        # removals first so an id both removed and re-added resolves to
        # the update's row, not the zeroing
        for raw in removed:
            r = self.row_of_id.get(raw)
            if r is not None and r != n_old:
                updates[r] = np.zeros(self.dim, np.float32)
        if update is not None and len(update.keys):
            ent = np.unique(np.asarray(update.keys) // update.dim)
            reverse = {int(d): raw for raw, d in update_vocab.items()}
            block = update.entity_rows(ent)
            for i, e in enumerate(ent):
                raw = reverse.get(int(e))
                if raw is None:
                    raise ValueError(
                        f"patch entity {int(e)} has no vocabulary entry")
                if not self.owns(raw):
                    # a sharded store applies only its slice of a global
                    # patch: foreign entities belong to (and are patched
                    # on) another host — appending them here would grow
                    # this host back toward the full table
                    continue
                updates[target_row(raw)] = block[i]
        body = self.table[:n_old]
        sbody = None if self.scales is None else self.scales[:n_old]
        if new_raws:
            body = jnp.concatenate(
                [body, jnp.zeros((len(new_raws), self.dim), body.dtype)])
            if sbody is not None:
                sbody = jnp.concatenate(
                    [sbody, jnp.ones((len(new_raws),), jnp.float32)])
        if updates:
            rows = np.fromiter(updates.keys(), np.int32, len(updates))
            vals = np.stack(list(updates.values()))
            rows_d = jnp.asarray(rows)
            if self.table_dtype == "int8":
                q, s = quantize_rows(vals)
                body = body.at[rows_d].set(jnp.asarray(q))
                sbody = sbody.at[rows_d].set(jnp.asarray(s))
            else:
                body = body.at[rows_d].set(
                    jnp.asarray(vals).astype(body.dtype))
        table = jnp.concatenate(
            [body, jnp.zeros((1, self.dim), body.dtype)])
        scales = (None if sbody is None
                  else jnp.concatenate([sbody, jnp.ones((1,), jnp.float32)]))
        fallback = n_old + len(new_raws)
        row_of_id = {raw: (fallback if r == n_old else r)
                     for raw, r in self.row_of_id.items()}
        for i, raw in enumerate(new_raws):
            row_of_id[raw] = n_old + i
        return EntityCoefficientStore(
            random_effect_type=self.random_effect_type,
            feature_shard_id=self.feature_shard_id, dim=self.dim,
            table=table, row_of_id=row_of_id,
            table_dtype=self.table_dtype, scales=scales,
            shard=self.shard, shard_map=self.shard_map)

    @staticmethod
    def build(model: RandomEffectModel,
              entity_vocab: Mapping[str, int],
              table_dtype: str = "float32",
              shard: Optional[tuple] = None,
              shard_map=None) -> "EntityCoefficientStore":
        """Pack a loaded :class:`RandomEffectModel`'s sparse table densely,
        in ``table_dtype`` storage (see the module docstring for the
        quantization format and parity contract).

        ``entity_vocab`` is the model-derived raw→dense id map
        (:func:`photon_ml_tpu.io.model_io.game_model_entity_vocabs`). Models
        fresh off disk are always in shard space (export back-projects), so
        a projector here is a usage error, not a supported layout.

        ``shard=(index, count)`` builds the FLEET shard view: only raw ids
        hashing to this shard (``fleet/sharding.py::shard_of_id``) get
        rows, so the host packs ~``1/count`` of the dense table — the
        entities-per-host lever at "hundreds of millions of entities".
        Every other id (foreign shard or globally unseen alike) resolves
        to the fallback zeros row: cold-start semantics are unchanged,
        and the routing tier is what makes a foreign id never land here.

        ``shard_map`` (a ``fleet/sharding.py::ShardMap``) replaces the
        default hash placement with the explicit bucket→shard table —
        the live-reshard repack path; ownership questions on the built
        store answer by the same map.
        """
        if table_dtype not in TABLE_DTYPES:
            raise ValueError(f"unknown table_dtype {table_dtype!r}; "
                             f"expected one of {TABLE_DTYPES}")
        if model.projector is not None:
            raise ValueError(
                "serving expects shard-space models (call to_shard_space() "
                "before building a store); saved models are already "
                "back-projected by export")
        shard = _sharding.check_shard(shard)
        entity_vocab = _sharding.map_shard_vocab(entity_vocab, shard_map,
                                                 shard)
        keys = np.asarray(model.keys, np.int64)
        ent = keys // model.dim
        feat = keys % model.dim
        if shard is not None and len(keys):
            # keep only the shard's entities' coefficients: the dense
            # table (the device payload) is what sharding shrinks
            kept_dense = np.fromiter(
                (int(d) for d in entity_vocab.values()), np.int64,
                count=len(entity_vocab))
            mask = np.isin(ent, kept_dense)
            keys, ent, feat = keys[mask], ent[mask], feat[mask]
            coeffs = np.asarray(model.coeffs)[mask]
        else:
            coeffs = model.coeffs
        uniq = np.unique(ent)
        dense = np.zeros((len(uniq) + 1, model.dim), np.float32)
        if len(keys):
            pos = np.searchsorted(uniq, ent)
            dense[pos, feat] = coeffs
        # dense entity id -> packed row, then raw id -> packed row; vocab
        # entries without coefficients (possible when coordinates sharing a
        # re_type merged vocabs) deliberately map to the fallback zeros row
        row_of_dense = {int(e): i for i, e in enumerate(uniq)}
        fallback = len(uniq)
        row_of_id = {raw: row_of_dense.get(d, fallback)
                     for raw, d in entity_vocab.items()}
        table, scales = _pack_table(dense, table_dtype)
        return EntityCoefficientStore(
            random_effect_type=model.random_effect_type,
            feature_shard_id=model.feature_shard_id,
            dim=model.dim, table=table, row_of_id=row_of_id,
            table_dtype=table_dtype, scales=scales, shard=shard,
            shard_map=shard_map)
