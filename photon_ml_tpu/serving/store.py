"""Entity coefficient store: per-entity models packed for O(1) online lookup.

The batch path joins random-effect coefficients against a dataset with one
``searchsorted`` over the whole score set
(:meth:`photon_ml_tpu.game.model.RandomEffectModel.lookup`); a serving
request has no dataset — it names one entity by its RAW id and needs that
entity's coefficient row *now*. So each random-effect coordinate's sparse
``(entity·dim + feature) → coeff`` table is repacked once at model-load time
into a dense ``(n_entities + 1, dim)`` device array plus a host
``raw id → row`` dict: request-time lookup is one dict probe and one device
gather. The extra last row is all-zero — the landing slot for entities the
model has never seen, which therefore score exactly 0 from this coordinate
(the GLMix cold-start contract: unseen entities fall back to the fixed
effect alone, same as the batch path's not-found join).
"""

from __future__ import annotations

import dataclasses
from typing import Mapping, Optional, Sequence

import numpy as np

from photon_ml_tpu.game.model import RandomEffectModel


@dataclasses.dataclass(frozen=True)
class EntityCoefficientStore:
    """Dense per-entity coefficient table for one random-effect coordinate.

    ``table`` is ``(n_entities + 1, dim)`` float32 on device; row
    ``n_entities`` is the all-zero fallback row. ``row_of_id`` maps the raw
    entity id string to its table row.
    """

    random_effect_type: str
    feature_shard_id: str
    dim: int
    table: object  # jax.Array (n_entities + 1, dim) float32
    row_of_id: Mapping[str, int]

    @property
    def n_entities(self) -> int:
        return len(self.row_of_id)

    @property
    def fallback_row(self) -> int:
        return int(self.table.shape[0]) - 1

    def rows_for(self, raw_ids: Sequence[Optional[str]]) -> np.ndarray:
        """Table row per raw entity id; unseen/missing ids land on the
        zero fallback row."""
        fb = self.fallback_row
        get = self.row_of_id.get
        return np.fromiter(
            (fb if r is None else get(r, fb) for r in raw_ids),
            np.int32, count=len(raw_ids))

    def apply_patch(self, update: Optional[RandomEffectModel],
                    update_vocab: Mapping[str, int],
                    removed: Sequence[str] = (),
                    ) -> "EntityCoefficientStore":
        """Derive the NEXT version's table by overwriting only the touched
        rows — the continuous-training delta-activation path, O(touched)
        instead of the O(all entities) rebuild :meth:`build` performs.

        ``update`` is the patch's partial model (only re-solved entities)
        in ITS OWN dense-id space, with ``update_vocab`` mapping raw ids
        to those dense ids; rows are matched by RAW id, the stable
        cross-version identity. Entities already in this store have their
        row overwritten; new entities append fresh rows; ``removed`` raw
        ids (models dropped by the refresh's active-data bounds) have
        their rows zeroed, scoring exactly like the cold-start fallback.
        The update is FUNCTIONAL — this store's device table is never
        mutated (in-flight requests hold it), a new array is derived and
        the previous version stays instantly restorable.

        This method and :meth:`build` are the only sanctioned writers of
        serving device tables (hygiene rule 5,
        ``tools/check_resilience_hygiene.py``).
        """
        import jax.numpy as jnp

        if update is not None:
            if update.projector is not None:
                raise ValueError("patches must be shard-space models")
            if update.dim != self.dim:
                raise ValueError(
                    f"patch dim {update.dim} != store dim {self.dim}")
            if update.random_effect_type != self.random_effect_type:
                raise ValueError(
                    f"patch random-effect type "
                    f"{update.random_effect_type!r} != store "
                    f"{self.random_effect_type!r}")
        n_old = self.fallback_row
        updates: dict[int, np.ndarray] = {}
        new_raws: list[str] = []

        def target_row(raw: str) -> int:
            r = self.row_of_id.get(raw)
            if r is None or r == n_old:
                # unseen raw id, or a vocab-merge entry parked on the
                # fallback zeros row (never writable): append a fresh row
                new_raws.append(raw)
                return n_old + len(new_raws) - 1
            return r

        # removals first so an id both removed and re-added resolves to
        # the update's row, not the zeroing
        for raw in removed:
            r = self.row_of_id.get(raw)
            if r is not None and r != n_old:
                updates[r] = np.zeros(self.dim, np.float32)
        if update is not None and len(update.keys):
            ent = np.unique(np.asarray(update.keys) // update.dim)
            reverse = {int(d): raw for raw, d in update_vocab.items()}
            block = update.entity_rows(ent)
            for i, e in enumerate(ent):
                raw = reverse.get(int(e))
                if raw is None:
                    raise ValueError(
                        f"patch entity {int(e)} has no vocabulary entry")
                updates[target_row(raw)] = block[i]
        body = self.table[:n_old]
        if new_raws:
            body = jnp.concatenate(
                [body, jnp.zeros((len(new_raws), self.dim), jnp.float32)])
        if updates:
            rows = np.fromiter(updates.keys(), np.int32, len(updates))
            vals = np.stack(list(updates.values()))
            body = body.at[jnp.asarray(rows)].set(jnp.asarray(vals))
        table = jnp.concatenate(
            [body, jnp.zeros((1, self.dim), jnp.float32)])
        fallback = n_old + len(new_raws)
        row_of_id = {raw: (fallback if r == n_old else r)
                     for raw, r in self.row_of_id.items()}
        for i, raw in enumerate(new_raws):
            row_of_id[raw] = n_old + i
        return EntityCoefficientStore(
            random_effect_type=self.random_effect_type,
            feature_shard_id=self.feature_shard_id, dim=self.dim,
            table=table, row_of_id=row_of_id)

    @staticmethod
    def build(model: RandomEffectModel,
              entity_vocab: Mapping[str, int]) -> "EntityCoefficientStore":
        """Pack a loaded :class:`RandomEffectModel`'s sparse table densely.

        ``entity_vocab`` is the model-derived raw→dense id map
        (:func:`photon_ml_tpu.io.model_io.game_model_entity_vocabs`). Models
        fresh off disk are always in shard space (export back-projects), so
        a projector here is a usage error, not a supported layout.
        """
        import jax.numpy as jnp

        if model.projector is not None:
            raise ValueError(
                "serving expects shard-space models (call to_shard_space() "
                "before building a store); saved models are already "
                "back-projected by export")
        keys = np.asarray(model.keys, np.int64)
        ent = keys // model.dim
        feat = keys % model.dim
        uniq = np.unique(ent)
        dense = np.zeros((len(uniq) + 1, model.dim), np.float32)
        if len(keys):
            pos = np.searchsorted(uniq, ent)
            dense[pos, feat] = model.coeffs
        # dense entity id -> packed row, then raw id -> packed row; vocab
        # entries without coefficients (possible when coordinates sharing a
        # re_type merged vocabs) deliberately map to the fallback zeros row
        row_of_dense = {int(e): i for i, e in enumerate(uniq)}
        fallback = len(uniq)
        row_of_id = {raw: row_of_dense.get(d, fallback)
                     for raw, d in entity_vocab.items()}
        return EntityCoefficientStore(
            random_effect_type=model.random_effect_type,
            feature_shard_id=model.feature_shard_id,
            dim=model.dim, table=jnp.asarray(dense), row_of_id=row_of_id)
