"""Durable request/score log: bounded, sampled Avro segments per request.

The :class:`~photon_ml_tpu.quality.canary.RequestReservoir` keeps a small
in-memory sample of live traffic for activation-time shadow scoring; the
feedback-join loop (ROADMAP "Close the loop") needs the on-disk
generalization — *what* was served, by *which* model content, and where
each millisecond went. This module is that log:

- one Avro record per served request (``RequestLogAvro``,
  :mod:`photon_ml_tpu.io.schemas`): request id, wall timestamp, model
  version + content lineage, the front end's per-stage timings, and the
  full scored records (features, entity ids, offset, f32 score widened to
  double — exact). ``tools/reqlog_replay.py`` re-scores the logged inputs
  against the named lineage and asserts bit-parity;
- **sampled** deterministically by request id (``crc32(id)`` against
  ``sample_rate`` — the same request either logs on every host or on
  none, so a fleet's logs join without duplicate-rate skew);
- **segmented + rotated**: records buffer in memory and flush as complete
  Avro container files (``reqlog-NNNNNNNN.avro``) every
  ``segment_records`` requests; ``max_bytes`` bounds the directory by
  deleting the oldest segments (retention, counted separately from loss);
- **off the request path**: segment writes run on a
  :class:`~photon_ml_tpu.io.pipeline.BackgroundSaver` pool under
  ``io.save.reqlog`` spans; the log path never blocks scoring. If the
  writer falls behind the ``max_buffered`` budget, new requests are
  DROPPED and counted — backpressure degrades the log, never the traffic;
- budget metrics: ``photon_reqlog_records_total`` /
  ``photon_reqlog_bytes_total`` / ``photon_reqlog_dropped_total``
  (dropped = buffer-budget or write-error losses; sampling is not a
  drop), all scrape-visible and mirrored into ``/healthz``.

Telemetry hygiene rule 7 makes this module the ONE place that writes
``RequestLogAvro`` files (``tools/check_telemetry_hygiene.py``): a second
writer would fork the log format away from the replay tool and the
feedback joiner.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Mapping, Optional, Sequence

from photon_ml_tpu.fleet.sharding import crc_bucket
from photon_ml_tpu.io.avro import write_avro_file
from photon_ml_tpu.io.pipeline import BackgroundSaver
from photon_ml_tpu.io.schemas import REQUEST_LOG_AVRO
from photon_ml_tpu.resilience.faults import fault_point
from photon_ml_tpu.serving import overload as _overload
from photon_ml_tpu.telemetry import metrics as _metrics

_RECORDS_TOTAL = _metrics.counter(
    "photon_reqlog_records_total",
    "Request-log records durably written (post-sampling)")
_BYTES_TOTAL = _metrics.counter(
    "photon_reqlog_bytes_total",
    "Bytes of request-log Avro segments written")
_DROPPED_TOTAL = _metrics.counter(
    "photon_reqlog_dropped_total",
    "Request-log records LOST after sampling selected them: writer "
    "backpressure past the buffer budget, or failed segment writes")

#: sampling hash granularity: crc32(request id) % _SAMPLE_MOD < rate * MOD
_SAMPLE_MOD = 1 << 16


class RequestLog:
    """Bounded, sampled, background-written Avro request/score log.

    Thread-safe. ``saver=None`` builds a private single-writer
    :class:`BackgroundSaver` (closed with the log); passing the server's
    shared pool is also fine — segment writes are tracked and pruned via
    :meth:`BackgroundSaver.collect`, so a process-lifetime log never grows
    the pool's pending list unboundedly.
    """

    def __init__(self, log_dir: str, *, sample_rate: float = 1.0,
                 segment_records: int = 256,
                 max_bytes: int = 64 << 20,
                 max_buffered: Optional[int] = None,
                 saver: Optional[BackgroundSaver] = None):
        if not 0.0 <= sample_rate <= 1.0:
            raise ValueError(
                f"sample_rate must be in [0, 1], got {sample_rate}")
        if segment_records < 1:
            raise ValueError(
                f"segment_records must be >= 1, got {segment_records}")
        self.log_dir = os.path.abspath(log_dir)
        os.makedirs(self.log_dir, exist_ok=True)
        self.sample_rate = float(sample_rate)
        self.segment_records = int(segment_records)
        self.max_bytes = int(max_bytes)
        #: backpressure budget: records allowed in not-yet-durable buffers
        #: (the in-memory buffer plus submitted-but-unfinished segments)
        self.max_buffered = (8 * self.segment_records
                             if max_buffered is None else int(max_buffered))
        self._saver = saver if saver is not None else BackgroundSaver(
            part_workers=1, save_workers=1)
        self._own_saver = saver is None
        self._lock = threading.Lock()
        self._buffer: list[dict] = []  # guarded-by: _lock
        #: records submitted, not yet confirmed written
        self._in_flight = 0  # guarded-by: _lock
        self._seq = 0  # guarded-by: _lock
        #: [(path, records, bytes)] of live segments, oldest first —
        #: what rotation walks (bytes filled in post-write)
        self._segments: list[list] = []  # guarded-by: _lock  # photon-lint: disable=res-bounded-queue -- bounded by max_bytes: _rotate()'s pop(0) IS the bound (retention, not a request queue)
        self._closed = False  # guarded-by: _lock
        #: this log's own outstanding segment futures (pruned as they
        #: complete; a shared pool's other writes are never touched)
        self._futures: list = []  # guarded-by: _lock
        self.n_records = 0  # guarded-by: _lock
        self.n_bytes = 0  # guarded-by: _lock
        self.n_dropped = 0  # guarded-by: _lock
        self.n_rotated = 0  # guarded-by: _lock

    # --- sampling ---------------------------------------------------------
    def should_log(self, request_id: str) -> bool:
        """Deterministic per-id sampling decision (same id → same verdict
        on every host and every retry). Brownout level 1+ suspends
        sampling entirely — the request log is the FIRST optional work
        shed under overload (SERVING.md ladder), restored automatically
        on recovery."""
        if _overload.is_shed("reqlog"):
            return False
        if self.sample_rate >= 1.0:
            return True
        if self.sample_rate <= 0.0:
            return False
        # the one crc32 bucketing home (fleet/sharding.py) — same hash
        # the fleet shards by, so log joins and shard joins agree
        h = crc_bucket(str(request_id), _SAMPLE_MOD)
        return h < int(self.sample_rate * _SAMPLE_MOD)

    # --- logging ----------------------------------------------------------
    def log(self, *, request_id: str, records: Sequence[dict],
            scores: Sequence[float], version: int,
            lineage: Optional[str] = None,
            stage_ms: Optional[Mapping[str, float]] = None,
            kind: str = "score",
            topk: Optional[Mapping] = None) -> bool:
        """Append one served request (post-sampling; callers may skip the
        call entirely when :meth:`should_log` says no). Returns True when
        the request was accepted into the log, False when sampled out or
        dropped on backpressure. ``kind`` marks the workload (``score`` |
        ``rank``); ranked requests log the REQUEST record in ``records``
        (score 0.0) and the returned result in ``topk``
        (``{"k", "ids", "scores"}``) so the replay tool can re-rank them
        bit-identically."""
        if not self.should_log(request_id):
            return False
        entry = {
            "requestId": str(request_id),
            "ts": time.time(),
            "kind": str(kind),
            "modelVersion": int(version if version is not None else -1),
            "modelLineage": lineage,
            "stageMs": {k: float(v) for k, v in (stage_ms or {}).items()},
            "records": [{
                "features": [{"name": f.get("name", ""),
                              "term": f.get("term") or "",
                              "value": float(f.get("value", 0.0))}
                             for f in (rec.get("features") or [])],
                "metadataMap": rec.get("metadataMap"),
                "offset": (None if rec.get("offset") is None
                           else float(rec["offset"])),
                "score": float(s),
                # inline ground truth (backfill/replay clients); live
                # traffic leaves it null — the feedback joiner attaches
                # labels from the external source instead
                "label": (None if rec.get("label") is None
                          else float(rec["label"])),
            } for rec, s in zip(records, scores)],
            "topk": None if topk is None else {
                "k": int(topk["k"]),
                "ids": [str(i) for i in topk["ids"]],
                # f32 scores widened to double — exact, replay bit-level
                "scores": [float(s) for s in topk["scores"]],
            },
        }
        flush_batch = None
        with self._lock:
            if self._closed:
                return False
            if len(self._buffer) + self._in_flight >= self.max_buffered:
                # the writer is behind budget: shed the LOG record, never
                # the request — and make the loss scrape-visible
                self.n_dropped += 1
                _DROPPED_TOTAL.inc()
                return False
            self._buffer.append(entry)
            if len(self._buffer) >= self.segment_records:
                flush_batch = self._take_buffer_locked()
        if flush_batch is not None:
            self._submit_segment(flush_batch)
        return True

    def flush(self) -> None:
        """Submit whatever is buffered as a (possibly short) segment."""
        with self._lock:
            batch = self._take_buffer_locked()
        if batch is not None:
            self._submit_segment(batch)

    # --- segment machinery ------------------------------------------------
    def _take_buffer_locked(self):
        if not self._buffer:
            return None
        batch, self._buffer = self._buffer, []
        self._seq += 1
        self._in_flight += len(batch)
        return (self._seq, batch)

    def _submit_segment(self, seq_batch) -> None:
        seq, batch = seq_batch
        path = os.path.join(self.log_dir, f"reqlog-{seq:08d}.avro")

        def write() -> None:
            import logging

            tmp = path + ".tmp"
            try:
                # chaos site: a failed segment write must surface as LOSS
                # in the dropped counter and never disturb serving
                fault_point("io.save.reqlog", path=path)
                write_avro_file(tmp, batch, REQUEST_LOG_AVRO)
                os.replace(tmp, path)
            except Exception as e:
                # a failed segment is LOSS, surfaced through the budget
                # counter — the log must never fail serving or shutdown
                if os.path.exists(tmp):
                    os.unlink(tmp)
                with self._lock:
                    self._in_flight -= len(batch)
                    self.n_dropped += len(batch)
                _DROPPED_TOTAL.inc(len(batch))
                logging.getLogger(__name__).error(
                    "reqlog segment write %s failed: %r", path, e)
                return
            size = os.path.getsize(path)
            with self._lock:
                self._in_flight -= len(batch)
                self._segments.append([path, len(batch), size])
                self.n_records += len(batch)
                self.n_bytes += size
            _RECORDS_TOTAL.inc(len(batch))
            _BYTES_TOTAL.inc(size)
            self._rotate()

        fut = self._saver.submit(write, label="io.save.reqlog", path=path)
        with self._lock:
            self._futures = [f for f in self._futures if not f.done()]
            self._futures.append(fut)
        if self._own_saver:
            # keep the private pool's pending list bounded for the life of
            # the process (a shared pool's owner does its own join, which
            # collect() must not pre-empt — it would swallow their errors)
            self._saver.collect()

    def _rotate(self) -> None:
        """Retention: delete oldest segments while the directory exceeds
        ``max_bytes``. Rotated-out records are retention, not loss — they
        were durably written (and counted) first."""
        while True:
            with self._lock:
                total = sum(s[2] for s in self._segments)
                if total <= self.max_bytes or len(self._segments) <= 1:
                    return
                path, n, _size = self._segments.pop(0)
                self.n_rotated += n
            try:
                os.unlink(path)
            except OSError:
                pass

    # --- introspection ----------------------------------------------------
    @property
    def saver(self):
        """The background writer pool — what the capacity plane's
        ``saver_pool`` probe watches."""
        return self._saver

    def stats(self) -> dict:
        """The ``/healthz`` payload: budget counters + config."""
        with self._lock:
            return {
                "dir": self.log_dir,
                "sample_rate": self.sample_rate,
                "records": self.n_records,
                "bytes": self.n_bytes,
                "dropped": self.n_dropped,
                "rotated": self.n_rotated,
                "buffered": len(self._buffer) + self._in_flight,
                "segments": len(self._segments),
            }

    def segment_paths(self) -> list[str]:
        with self._lock:
            return [s[0] for s in self._segments]

    def close(self) -> None:
        """Flush the tail segment and wait for this log's writes. Write
        errors land in the dropped counter inside the write jobs (the log
        must never fail the server's shutdown path)."""
        with self._lock:
            if self._closed:
                return
        self.flush()
        with self._lock:
            self._closed = True
            futures, self._futures = self._futures, []
        for fut in futures:
            try:
                fut.result()
            except Exception:
                pass  # already counted as dropped by the write job
        if self._own_saver:
            self._saver.collect()
            self._saver.close()


def iter_reqlog(log_dir: str):
    """Yield every logged request record across a directory's segments,
    oldest segment first (the replay tool's and feedback joiner's read
    path; resilient to a concurrent writer — half-written ``.tmp`` staging
    files are invisible by construction)."""
    from photon_ml_tpu.io.avro import iter_avro_file

    for name in sorted(os.listdir(log_dir)):
        if not (name.startswith("reqlog-") and name.endswith(".avro")):
            continue
        yield from iter_avro_file(os.path.join(log_dir, name))
