"""Overload protection for the serving path: typed load-shedding and
brownout degradation.

"Heavy traffic from millions of users" is survived, not outrun: an
overloaded host must degrade PREDICTABLY instead of queueing forever.
This module owns the three mechanisms (SERVING.md "Serving under
overload"):

- **Typed shedding.** :class:`Shed` is the one error admission control
  raises — at the batcher's bounded queue (``reason="queue_full"``), at a
  deadline check (``reason="deadline"``), or under max brownout
  (``reason="brownout"``). ``http.py`` maps it to **429** with a
  ``Retry-After`` hint; every shed lands in
  ``photon_shed_total{reason=...}`` exactly once, at the raise site
  (:func:`shed` builds the error AND counts it). A shed request must
  never reach the engine's execute stage — the tier-1 stage-histogram
  test locks that.
- **Brownout ladder.** Under sustained pressure the controller sheds
  *optional* work in a documented order before it sheds traffic, one
  level per tick, restoring in reverse on recovery:

  ======  ======================================================
  level   degradation (cumulative)
  ======  ======================================================
  0       full service
  1       request-log sampling suspended (``reqlog.should_log``)
  2       \\+ quality accumulation suspended (engine monitor)
  3       \\+ span tracing suspended (``serving.*`` spans)
  4       \\+ traffic shed (``/score`` → 429 ``reason=brownout``;
          ``/readyz`` reports 503)
  ======  ======================================================

  The level is scrape-visible as the host-owned gauge
  ``photon_brownout_level``; every transition posts a
  ``brownout_changed`` event the telemetry bridge turns into
  ``photon_brownout_changes_total{direction}``.
- **The controller.** :class:`OverloadController` watches the one signal
  overload actually produces — microbatcher queue pressure: depth
  against ``max_queue`` plus the windowed p99 of the ``queue_wait``
  stage histogram — and moves the level one step per tick. Hysteresis is
  the high/low watermark gap; no flapping on a single hot scrape.

State is process-global (like the metrics registry it feeds): one host
has one brownout level, whichever component asks.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from photon_ml_tpu.telemetry import metrics as _metrics

logger = logging.getLogger(__name__)

#: every shed request, by why it was shed — the serving twin of the
#: training side's retry/divergence counters. Counted at the raise site
#: (:func:`shed`), exactly once per shed request.
_SHED_TOTAL = _metrics.counter(
    "photon_shed_total",
    "Requests shed by serving admission control, by reason "
    "(queue_full | deadline | brownout | connections | upstream — the "
    "last two map to 503: the connection budget or the fleet capacity "
    "is exhausted, the caller did nothing wrong)",
    labels=("reason",))

#: current brownout degradation level (0 = full service, MAX_LEVEL =
#: shedding traffic). Host-owned: each serving process degrades on its
#: own pressure, so a fleet aggregate fans this out per process.
_BROWNOUT_LEVEL = _metrics.gauge(
    "photon_brownout_level",
    "Serving brownout degradation level (0 = full service; see "
    "SERVING.md 'Serving under overload' for the per-level ladder)")
_metrics.mark_host_owned("photon_brownout_level")

#: the closed shed-reason vocabulary (materialized at import so /metrics
#: shows every reason at zero before the first shed). ``upstream`` is the
#: fleet router's reason — a per-host fan-out leg failed (dead host, slow
#: host past the fan-out timeout, injected ``fleet.fanout`` fault) — and
#: maps to **503** rather than 429: the caller did nothing wrong and the
#: capacity is gone, not busy. ``connections`` is the ``--max-connections``
#: budget refusing a socket past the ceiling (SERVING.md "Connection
#: budget"): also a 503, sent with ``Connection: close`` so the client
#: retries against a host with socket headroom.
SHED_REASONS = ("queue_full", "deadline", "brownout", "connections",
                "upstream")
for _r in SHED_REASONS:
    _SHED_TOTAL.labels(reason=_r)

#: optional-work features, in the order brownout sheds them (and the
#: reverse order recovery restores them)
FEATURES = ("reqlog", "quality", "tracing")

#: the level at which traffic itself is shed (every optional feature is
#: already gone by then)
MAX_LEVEL = len(FEATURES) + 1


class Shed(RuntimeError):
    """A request refused by admission control (never an engine failure).

    ``reason`` is one of :data:`SHED_REASONS`; ``retry_after_s`` is the
    hint ``http.py`` surfaces as the ``Retry-After`` header.
    """

    def __init__(self, reason: str, message: str = "",
                 retry_after_s: float = 1.0):
        self.reason = reason
        self.retry_after_s = float(retry_after_s)
        super().__init__(message or f"request shed ({reason})")


def shed(reason: str, message: str = "", retry_after_s: float = 1.0) -> Shed:
    """Count one shed request and build the typed error (the caller
    raises it — or sets it on the request's Future). Counting at the
    build site keeps the invariant: one Shed == one counter increment,
    however many layers the error then crosses."""
    if reason not in SHED_REASONS:
        raise ValueError(f"unknown shed reason {reason!r}; expected one "
                         f"of {SHED_REASONS}")
    _SHED_TOTAL.labels(reason=reason).inc()
    with _STATE_LOCK:
        _SHED_COUNTS[reason] += 1
    return Shed(reason, message, retry_after_s)


# ---------------------------------------------------------------------------
# process-global brownout state
# ---------------------------------------------------------------------------

_STATE_LOCK = threading.Lock()
_SHED_COUNTS: dict = {r: 0 for r in SHED_REASONS}
_LEVEL = 0


def level() -> int:
    """The current brownout level (0 = full service)."""
    with _STATE_LOCK:
        return _LEVEL


def shed_counts() -> dict:
    """Per-reason shed tallies (the ``/healthz`` / ``/readyz`` payload —
    the scrape equivalent is ``photon_shed_total``)."""
    with _STATE_LOCK:
        return dict(_SHED_COUNTS)


def is_shed(feature: str) -> bool:
    """Is this optional feature currently browned out? Call sites
    (reqlog sampling, quality accumulation, serving spans) check this on
    their hot path — one lock, no allocation."""
    with _STATE_LOCK:
        lvl = _LEVEL
    return feature in FEATURES and FEATURES.index(feature) < lvl


def traffic_shed() -> bool:
    """True at max brownout: new requests are shed, not queued."""
    return level() >= MAX_LEVEL


def set_level(new_level: int, bus=None) -> int:
    """Clamp and apply a brownout level; posts ``brownout_changed`` (and
    moves the gauge) only on an actual transition. Returns the applied
    level."""
    global _LEVEL
    new_level = max(0, min(int(new_level), MAX_LEVEL))
    with _STATE_LOCK:
        prev = _LEVEL
        if new_level == prev:
            return prev
        _LEVEL = new_level
    _BROWNOUT_LEVEL.set(new_level)
    if bus is None:
        from photon_ml_tpu.events import GLOBAL_BUS as bus
    bus.post("brownout_changed", level=new_level, previous=prev,
             shed_features=list(FEATURES[:min(new_level, len(FEATURES))]),
             traffic_shed=new_level >= MAX_LEVEL)
    logger.warning("brownout level %d -> %d (shedding: %s%s)", prev,
                   new_level,
                   ", ".join(FEATURES[:min(new_level, len(FEATURES))])
                   or "nothing",
                   " + traffic" if new_level >= MAX_LEVEL else "")
    return new_level


class OverloadController:
    """Queue-pressure watcher driving the brownout ladder.

    Each tick reads the microbatcher's queue utilization (depth over
    ``max_queue``) and the ``queue_wait`` stage histogram's p99 over the
    tick window, then moves the level ONE step: up past the high
    watermark, down below the low watermark (hysteresis — the gap between
    the two absorbs noise). ``start()`` runs ticks on a background
    thread (``Event.wait``, never a bare sleep); tests drive
    :meth:`tick` synchronously.
    """

    def __init__(self, batcher, *, high_util: float = 0.75,
                 low_util: float = 0.25,
                 wait_p99_ms: Optional[float] = None,
                 poll_s: float = 1.0, bus=None,
                 connections=None):
        self.batcher = batcher
        #: optional ConnectionTracker (serving/http.py): a host whose
        #: ``--max-connections`` budget is nearly spent is under pressure
        #: even with a shallow batcher queue, so connection utilization
        #: feeds the same watermarks queue utilization does
        self.connections = connections
        self.high_util = float(high_util)
        self.low_util = float(low_util)
        #: optional queue-wait p99 threshold (ms) that escalates even
        #: when the queue is deep-but-under-capacity
        self.wait_p99_ms = wait_p99_ms
        self.poll_s = float(poll_s)
        self.bus = bus
        self._stop = threading.Event()
        #: start/stop are operator-lifecycle calls from one control thread
        self._thread: Optional[threading.Thread] = None  # guarded-by: caller
        self._wait_hist = _metrics.histogram(
            "photon_serving_stage_seconds",
            "Serving request time per request-path stage "
            "(parse | queue_wait | batch_assemble | execute | respond)",
            labels=("stage",)).labels(stage="queue_wait")
        #: previous cumulative bucket snapshot — only the tick path (one
        #: thread, or tests ticking synchronously) touches it
        self._prev_wait = self._wait_hist.snapshot()[0]  # guarded-by: caller
        self.n_ticks = 0  # guarded-by: caller

    # --- one decision -----------------------------------------------------
    def _windowed_wait_p99_ms(self) -> Optional[float]:
        """p99 of queue_wait over THIS tick window (bucket-count deltas),
        None when the window saw no requests."""
        cum, _, _ = self._wait_hist.snapshot()
        prev, self._prev_wait = self._prev_wait, cum
        delta = [c - p for c, p in zip(cum, prev)]
        if delta[-1] <= 0:
            return None
        return _metrics.quantile_from_buckets(
            self._wait_hist.uppers, delta, 0.99) * 1e3

    def tick(self) -> int:
        """One control decision; returns the (possibly new) level."""
        self.n_ticks += 1
        depth = self.batcher.queue_depth()
        cap = self.batcher.max_queue
        util = (depth / cap) if cap else 0.0
        conn_util = (0.0 if self.connections is None
                     else self.connections.utilization())
        wait_p99 = self._windowed_wait_p99_ms()
        hot = util >= self.high_util or conn_util >= self.high_util or (
            self.wait_p99_ms is not None and wait_p99 is not None
            and wait_p99 >= self.wait_p99_ms)
        cool = util <= self.low_util and conn_util <= self.low_util and (
            self.wait_p99_ms is None or wait_p99 is None
            or wait_p99 < self.wait_p99_ms)
        cur = level()
        if hot and cur < MAX_LEVEL:
            return set_level(cur + 1, bus=self.bus)
        if cool and cur > 0:
            return set_level(cur - 1, bus=self.bus)
        return cur

    # --- lifecycle --------------------------------------------------------
    def start(self) -> "OverloadController":
        def loop() -> None:
            while not self._stop.wait(self.poll_s):
                try:
                    self.tick()
                except Exception:
                    logger.exception("overload tick failed; will retry")

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="photon-serving-overload")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        # a stopping server restores full service: brownout is pressure
        # response, not configuration
        set_level(0, bus=self.bus)
