"""Online serving: low-latency GAME scoring with hot-swappable versions.

The piece the reference never shipped in photon-ml itself — the paper's
per-entity models exist to be APPLIED at request time (SURVEY §0, §5.5) —
built here as four layers (see SERVING.md for the architecture doc):

- :mod:`~photon_ml_tpu.serving.registry` — versioned model registry:
  validate-then-activate loading of ``train_game`` output dirs, atomic
  hot-swap, instant rollback.
- :mod:`~photon_ml_tpu.serving.store` — per-entity coefficients packed
  dense on device with O(1) raw-id lookup and a zeros fallback row (the
  GLMix cold-start contract).
- :mod:`~photon_ml_tpu.serving.engine` — jitted scoring with power-of-two
  batch buckets: zero steady-state recompiles, batch-path bit-parity.
- :mod:`~photon_ml_tpu.serving.batcher` / :mod:`~photon_ml_tpu.serving.http`
  — microbatching queue and the stdlib JSON endpoint
  (``/score`` / ``/healthz`` / ``/reload``) behind
  ``python -m photon_ml_tpu serve_game``.
- :mod:`~photon_ml_tpu.serving.reqlog` — durable, sampled Avro
  request/score log with rotation and a scrape-visible byte/record
  budget, written off the request path through the background writer
  pool (``serve_game --reqlog-dir``; replayed bit-identically by
  ``tools/reqlog_replay.py``).
- :mod:`~photon_ml_tpu.serving.watcher` — registry-driven discovery:
  poll a publish directory and activate new versions (full model dirs
  or continuous-training coefficient patches — see CONTINUOUS.md)
  through the same validate-then-activate path
  (``serve_game --watch-dir``).
- :mod:`~photon_ml_tpu.serving.overload` — overload protection: typed
  load shedding (:class:`Shed` → 429 + ``Retry-After``, counted in
  ``photon_shed_total{reason}``), deadline budgets
  (``X-Photon-Deadline-Ms``), and the brownout controller that sheds
  optional work (reqlog → quality → tracing) before traffic
  (SERVING.md "Serving under overload").

The ranked-retrieval workload (``GET /rank?user=...&k=...`` — one device
matmul + ``top_k`` over the full item axis, under the same admission
control, logging and zero-recompile contracts) lives in the sibling
:mod:`photon_ml_tpu.retrieval` package and plugs in through the registry
(``ModelRegistry(rank_coordinate=...)``; SERVING.md "Ranked retrieval").
"""

from photon_ml_tpu.serving.overload import (  # noqa: F401
    OverloadController,
    Shed,
)
from photon_ml_tpu.serving.batcher import MicroBatcher  # noqa: F401
from photon_ml_tpu.serving.engine import (  # noqa: F401
    RequestBatch,
    ScoringEngine,
    next_bucket,
)
from photon_ml_tpu.serving.http import (  # noqa: F401
    REQUEST_ID_HEADER,
    GameServer,
    ServingService,
)
from photon_ml_tpu.serving.reqlog import RequestLog, iter_reqlog  # noqa: F401
from photon_ml_tpu.serving.registry import (  # noqa: F401
    ModelRegistry,
    ServingModel,
)
from photon_ml_tpu.serving.store import EntityCoefficientStore  # noqa: F401
from photon_ml_tpu.serving.watcher import ModelDirectoryWatcher  # noqa: F401
