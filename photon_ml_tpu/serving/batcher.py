"""Microbatching queue: coalesce single requests into engine-sized batches.

Online GLMix traffic is dominated by batch-size-1 requests, but the engine's
per-call overhead (pack, pad, dispatch) amortizes across a batch — and the
power-of-two buckets mean a batch of 8 costs barely more than a batch of 1.
The batcher trades a bounded wait (``max_wait_ms``, default 2 ms) for that
amortization: submitters enqueue and get a Future; a single worker thread
drains up to ``max_batch`` requests per scoring call, waiting at most
``max_wait_ms`` after the first request of a batch arrives before firing.

Swap interaction: the score function is resolved PER BATCH (the registry's
active engine), so a hot-swap takes effect at the next batch boundary and a
batch never mixes versions.
"""

from __future__ import annotations

import collections
import threading
from concurrent.futures import Future
from typing import Callable, Optional, Sequence

import numpy as np

from photon_ml_tpu.telemetry import metrics as _metrics

#: how well the linger window coalesces traffic — the distribution should
#: shift right as load rises (that's the amortization working)
_BATCH_SIZE = _metrics.histogram(
    "photon_serving_batch_size",
    "Coalesced records per microbatcher scoring call",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256))
#: requests parked in the queue right now (sampled at enqueue/drain).
#: Host-owned: in a serving fleet each process has its own queue, so a
#: fleet aggregate fans this out under a ``process`` label.
_QUEUE_DEPTH = _metrics.gauge(
    "photon_serving_queue_depth", "Microbatcher queue depth")
_metrics.mark_host_owned("photon_serving_queue_depth")


class MicroBatcher:
    """Single-worker request coalescer in front of a scoring callable.

    ``score_fn(records) -> np.ndarray`` scores one homogeneous batch (the
    registry's active version). Thread-safe; :meth:`submit` never blocks
    beyond the queue lock.
    """

    def __init__(self, score_fn: Callable[[Sequence[dict]], np.ndarray], *,
                 max_batch: int = 64, max_wait_ms: float = 2.0):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self._score_fn = score_fn
        self.max_batch = max_batch
        self.max_wait_s = max_wait_ms / 1000.0
        self._cond = threading.Condition()
        self._queue: collections.deque = collections.deque()
        self._closed = False
        self.n_batches = 0
        self.n_coalesced = 0  # requests that shared a batch with others
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name="photon-serving-batcher")
        self._worker.start()

    def submit(self, record: dict) -> "Future[float]":
        """Enqueue one record; the Future resolves to its float score."""
        fut: Future = Future()
        with self._cond:
            if self._closed:
                raise RuntimeError("batcher is closed")
            self._queue.append((record, fut))
            _QUEUE_DEPTH.set(len(self._queue))
            self._cond.notify()
        return fut

    def score(self, record: dict,
              timeout: Optional[float] = None) -> float:
        """Blocking convenience wrapper around :meth:`submit`."""
        return self.submit(record).result(timeout=timeout)

    def close(self) -> None:
        """Drain outstanding work, then stop the worker."""
        with self._cond:
            self._closed = True
            self._cond.notify()
        self._worker.join()

    # --- worker -----------------------------------------------------------
    def _run(self) -> None:
        while True:
            batch = self._next_batch()
            if batch is None:
                return
            records = [r for r, _ in batch]
            _BATCH_SIZE.observe(len(records))
            try:
                scores = self._score_fn(records)
            except Exception as e:  # score failure fails THIS batch only
                for _, fut in batch:
                    fut.set_exception(e)
                continue
            self.n_batches += 1
            if len(batch) > 1:
                self.n_coalesced += len(batch)
            for (_, fut), s in zip(batch, np.asarray(scores)):
                fut.set_result(float(s))

    def _next_batch(self):
        """Block for the first request, then linger ``max_wait_s`` for
        followers (or until ``max_batch`` is reached). None = closed and
        drained."""
        import time

        with self._cond:
            while not self._queue:
                if self._closed:
                    return None
                self._cond.wait()
            if self.max_wait_s > 0:
                deadline = time.monotonic() + self.max_wait_s
                while (len(self._queue) < self.max_batch
                       and not self._closed):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(timeout=remaining)
            out = []
            while self._queue and len(out) < self.max_batch:
                out.append(self._queue.popleft())
            _QUEUE_DEPTH.set(len(self._queue))
            return out
