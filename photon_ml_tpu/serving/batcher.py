"""Microbatching queue: coalesce single requests into engine-sized batches.

Online GLMix traffic is dominated by batch-size-1 requests, but the engine's
per-call overhead (pack, pad, dispatch) amortizes across a batch — and the
power-of-two buckets mean a batch of 8 costs barely more than a batch of 1.
The batcher trades a bounded wait (``max_wait_ms``, default 2 ms) for that
amortization: submitters enqueue and get a Future; a single worker thread
drains up to ``max_batch`` requests per scoring call, waiting at most
``max_wait_ms`` after the first request of a batch arrives before firing.

Swap interaction: the score function is resolved PER BATCH (the registry's
active engine), so a hot-swap takes effect at the next batch boundary and a
batch never mixes versions.

Admission control (SERVING.md "Serving under overload"): ``max_queue``
bounds the queue — a submit against a full queue is refused with a typed
:class:`~photon_ml_tpu.serving.overload.Shed` (``reason="queue_full"``,
mapped to 429 by the HTTP layer) instead of parking behind work the host
cannot catch up on. Requests may carry a monotonic ``deadline``; the
drain checks it as each batch assembles and sheds expired entries
(``reason="deadline"``) rather than scoring for a caller that already gave
up — a shed request NEVER reaches the engine's execute stage. A
``score(timeout=)`` caller that times out cancels its Future, and the
drain discards cancelled (abandoned) entries without letting them consume
a batch slot.

Worker-death contract: an ordinary scoring exception fails only its batch
(the Futures get the exception, the worker keeps draining). Anything that
escapes that per-batch handling — a BaseException out of the score fn, a
bug in the drain loop itself — would previously strand every enqueued
Future forever and accept new submissions into a queue nothing drains.
Now the dying worker fails the in-flight batch and every queued Future
with a ``RuntimeError`` naming the cause, and later :meth:`submit` calls
raise the same error instead of enqueueing into a dead batcher.

Observability: each request's time parked in the queue lands in
``photon_serving_stage_seconds{stage="queue_wait"}`` — one stage of the
request-path critical path (OBSERVABILITY.md "Request path"). Enqueue
stamps ``time.monotonic()`` (a scheduling clock; the hygiene-sanctioned
source for cross-thread deadlines/waits) and the drain observes the delta
into the registry histogram.
"""

from __future__ import annotations

import collections
import threading
from concurrent.futures import Future, InvalidStateError
from concurrent.futures import TimeoutError as FutureTimeoutError
from typing import Callable, Optional, Sequence

import numpy as np

from photon_ml_tpu.serving import overload as _overload
from photon_ml_tpu.serving import stages as _stages
from photon_ml_tpu.telemetry import metrics as _metrics

#: how well the linger window coalesces traffic — the distribution should
#: shift right as load rises (that's the amortization working)
_BATCH_SIZE = _metrics.histogram(
    "photon_serving_batch_size",
    "Coalesced records per microbatcher scoring call",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256))
#: requests parked in the queue right now (sampled at enqueue/drain).
#: Host-owned: in a serving fleet each process has its own queue, so a
#: fleet aggregate fans this out under a ``process`` label.
_QUEUE_DEPTH = _metrics.gauge(
    "photon_serving_queue_depth", "Microbatcher queue depth")
_metrics.mark_host_owned("photon_serving_queue_depth")
#: per-stage request-path critical path (parse, queue_wait, batch_assemble,
#: execute, respond) — this module owns the queue_wait stage
_STAGE_SECONDS = _metrics.histogram(
    "photon_serving_stage_seconds",
    "Serving request time per request-path stage "
    "(parse | queue_wait | batch_assemble | execute | respond)",
    labels=("stage",))


class BatcherClosed(RuntimeError):
    """Raised by :meth:`MicroBatcher.submit` once :meth:`close` ran —
    the host is draining. The HTTP layer maps it to a typed 503
    ``reason=stopping`` (and closes the connection) so a fleet router
    retries the leg on a replica instead of surfacing a 500 from a
    stopping host."""


def _resolve(fut: Future, *, result=None, exception=None) -> None:
    """Set a Future's outcome, tolerating cancelled futures — a submitter
    that gave up must not take the worker (or the abort path) down."""
    try:
        if exception is not None:
            fut.set_exception(exception)
        else:
            fut.set_result(result)
    except InvalidStateError:
        pass


class MicroBatcher:
    """Single-worker request coalescer in front of a scoring callable.

    ``score_fn(records) -> np.ndarray`` scores one homogeneous batch (the
    registry's active version). Thread-safe; :meth:`submit` never blocks
    beyond the queue lock. ``max_queue=None`` leaves the queue unbounded
    (embedder's choice — ``serve_game`` always bounds it). ``coerce``
    maps each per-record result onto its Future (default ``float`` — the
    historical scalar-score contract); the ranked path passes records as
    opaque ``(record, k)`` tuples with a ``score_fn`` returning a
    1-D object array of ``(ids, scores)`` results and an identity
    ``coerce``.
    """

    def __init__(self, score_fn: Callable[[Sequence[dict]], np.ndarray], *,
                 max_batch: int = 64, max_wait_ms: float = 2.0,
                 max_queue: Optional[int] = None,
                 coerce: Callable = float):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_queue is not None and max_queue < 1:
            raise ValueError(f"max_queue must be >= 1 (or None for "
                             f"unbounded), got {max_queue}")
        self._score_fn = score_fn
        self._coerce = coerce
        self.max_batch = max_batch
        self.max_wait_s = max_wait_ms / 1000.0
        self.max_queue = max_queue
        self._cond = threading.Condition()
        # bounded by the max_queue admission check in submit() (a maxlen
        # deque would silently evict — shedding must be loud and typed)
        self._queue: collections.deque = collections.deque()  # guarded-by: _cond  # photon-lint: disable=res-bounded-queue -- bounded by the explicit max_queue Shed check in submit(); maxlen would drop silently
        self._closed = False  # guarded-by: _cond
        #: the BaseException that killed the worker, None while healthy
        self._dead: Optional[BaseException] = None  # guarded-by: _cond
        #: the batch the worker is scoring right now — failed alongside the
        #: queue if the worker dies mid-score
        self._inflight: list = []  # guarded-by: _cond
        self.n_batches = 0  # guarded-by: _cond
        #: requests that shared a batch with others
        self.n_coalesced = 0  # guarded-by: _cond
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name="photon-serving-batcher")
        self._worker.start()

    @property
    def dead(self) -> Optional[BaseException]:
        """The exception that killed the worker, None while healthy (the
        ``/readyz`` liveness signal)."""
        with self._cond:
            return self._dead

    def queue_depth(self) -> int:
        with self._cond:
            return len(self._queue)

    def submit(self, record: dict,
               deadline: Optional[float] = None,
               stage_out: Optional[dict] = None) -> "Future[float]":
        """Enqueue one record; the Future resolves to its float score.
        ``deadline`` is an absolute ``time.monotonic()`` instant — an
        entry still queued past it is shed at drain time. ``stage_out``,
        when given, receives this request's stage seconds (its own
        queue_wait plus the batch's assemble/execute — every rider of a
        micro-batch paid the whole batch's wall) for the fleet
        leg-summary side channel; ContextVars don't cross the worker
        thread, so the sink rides the entry. Raises
        :class:`~photon_ml_tpu.serving.overload.Shed` when the bounded
        queue is full, RuntimeError once the batcher is closed or its
        worker has died."""
        import time

        fut: Future = Future()
        with self._cond:
            if self._dead is not None:
                raise RuntimeError(
                    f"batcher worker died: {self._dead!r}") from self._dead
            if self._closed:
                raise BatcherClosed("batcher is closed")
            if (self.max_queue is not None
                    and len(self._queue) >= self.max_queue):
                # admission control: refuse NOW (429 + Retry-After at the
                # HTTP layer) instead of queueing work the host is too far
                # behind to finish before the caller gives up
                raise _overload.shed(
                    "queue_full",
                    message=f"queue full ({len(self._queue)}/"
                            f"{self.max_queue} requests waiting)",
                    retry_after_s=max(self.max_wait_s * 2, 0.05))
            self._queue.append(
                (record, fut, time.monotonic(), deadline, stage_out))
            _QUEUE_DEPTH.set(len(self._queue))
            self._cond.notify()
        return fut

    def score(self, record: dict, timeout: Optional[float] = None,
              deadline: Optional[float] = None,
              stage_out: Optional[dict] = None) -> float:
        """Blocking convenience wrapper around :meth:`submit`. On timeout
        the Future is cancelled so the abandoned entry is discarded at
        drain time instead of consuming a batch slot."""
        fut = self.submit(record, deadline=deadline, stage_out=stage_out)
        try:
            return fut.result(timeout=timeout)
        except FutureTimeoutError:
            fut.cancel()
            raise

    def close(self) -> None:
        """Drain outstanding work, then stop the worker."""
        with self._cond:
            self._closed = True
            self._cond.notify()
        self._worker.join()

    # --- worker -----------------------------------------------------------
    def _run(self) -> None:
        try:
            while True:
                batch = self._next_batch()
                if batch is None:
                    return
                self._process(batch)
        except BaseException as e:
            # the drain loop itself died (BaseException out of the score
            # fn, a bug in the batching machinery): without this, queued
            # Futures hang forever and submitters keep feeding a queue
            # nothing reads
            self._abort(e)
            raise

    def _process(self, batch: list) -> None:
        import time

        records = [r for r, _, _, _, _ in batch]
        _BATCH_SIZE.observe(len(records))
        now = time.monotonic()
        wait_hist = _STAGE_SECONDS.labels(stage="queue_wait")
        for _, _, t_enq, _, stage_out in batch:
            waited = max(now - t_enq, 0.0)
            wait_hist.observe(waited)
            if stage_out is not None:
                stage_out["queue_wait"] = waited
        with self._cond:
            self._inflight = batch
        # NOTE: _inflight is cleared only on the resolved paths below — a
        # BaseException escaping this method must leave it set so _abort
        # can fail the very batch that killed the worker
        batch_stages: dict = {}
        try:
            with _stages.collect(batch_stages):
                scores = self._score_fn(records)
        except Exception as e:  # score failure fails THIS batch only
            self._finish(batch, exception=e)
            return
        # the engine timed assemble/execute once for the whole batch;
        # every rider waited on that same wall, so each sink gets the
        # batch-level seconds (leg-summary semantics, not attribution)
        for _, _, _, _, stage_out in batch:
            if stage_out is not None:
                stage_out.update(batch_stages)
        arr = np.asarray(scores)
        if arr.shape[:1] != (len(batch),):
            # contract violation from the score fn: fail the batch loudly
            # instead of silently zip-truncating some Futures into an
            # eternal hang
            self._finish(batch, exception=RuntimeError(
                f"score_fn returned {arr.shape[:1] or (0,)} scores "
                f"for a batch of {len(batch)}"))
            return
        with self._cond:
            # the worker is the only writer, but healthz/tests read these
            # stats from other threads — the lock-discipline pass flagged
            # the bare increments
            self.n_batches += 1
            if len(batch) > 1:
                self.n_coalesced += len(batch)
        self._finish(batch, scores=arr)

    def _finish(self, batch: list, *, scores=None, exception=None) -> None:
        if exception is not None:
            for _, fut, _, _, _ in batch:
                _resolve(fut, exception=exception)
        else:
            for (_, fut, _, _, _), s in zip(batch, scores):
                _resolve(fut, result=self._coerce(s))
        with self._cond:
            self._inflight = []

    def _abort(self, exc: BaseException) -> None:
        """Worker death: fail the in-flight batch and every queued Future,
        and poison future submissions."""
        with self._cond:
            self._dead = exc
            pending = list(self._inflight) + list(self._queue)
            self._inflight = []
            self._queue.clear()
            _QUEUE_DEPTH.set(0)
            self._cond.notify_all()
        err = RuntimeError(f"batcher worker died: {exc!r}")
        err.__cause__ = exc
        for _, fut, _, _, _ in pending:
            _resolve(fut, exception=err)

    def _next_batch(self):
        """Block for the first request, then linger ``max_wait_s`` for
        followers (or until ``max_batch`` is reached). Expired-deadline
        entries are shed here — at queue drain, before any batch
        assembly — and cancelled (abandoned) entries are discarded;
        neither consumes a batch slot or reaches the score fn. None =
        closed and drained."""
        import time

        while True:
            expired = []
            with self._cond:
                while not self._queue:
                    if self._closed:
                        return None
                    self._cond.wait()
                if self.max_wait_s > 0:
                    linger = time.monotonic() + self.max_wait_s
                    while (len(self._queue) < self.max_batch
                           and not self._closed):
                        remaining = linger - time.monotonic()
                        if remaining <= 0:
                            break
                        self._cond.wait(timeout=remaining)
                out = []
                now = time.monotonic()
                while self._queue and len(out) < self.max_batch:
                    entry = self._queue.popleft()
                    _, fut, _, deadline, _ = entry
                    if fut.cancelled():
                        # abandoned by a timed-out score() caller: the
                        # request has no listener — don't spend a slot
                        continue
                    if deadline is not None and now >= deadline:
                        expired.append(entry)
                        continue
                    out.append(entry)
                _QUEUE_DEPTH.set(len(self._queue))
            for _, fut, _, _, _ in expired:
                # shed, not scored: the caller's budget is already gone
                _resolve(fut, exception=_overload.shed(
                    "deadline",
                    message="deadline expired while queued"))
            if out:
                return out
            # everything drained this round was expired or abandoned —
            # go back to waiting for live work
