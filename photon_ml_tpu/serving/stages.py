"""Per-request stage-seconds side channel.

The five-stage critical-path histogram (``photon_serving_stage_seconds``)
aggregates across requests; the fleet router needs the SAME numbers per
request so each fan-out leg can report a compact stage summary back for
cross-host trace stitching (OBSERVABILITY.md "Fleet observability").
This module is that side channel: a ContextVar-scoped sink dict that
stage owners write into when — and only when — a collector is active.

Two hand-off patterns compose here:

- same-thread stages (parse/respond in http.py, assemble/execute on the
  direct scoring path) run inside :func:`collect`, so :func:`record`
  finds the sink through the ContextVar;
- batched stages cross the batcher's worker thread, where ContextVars do
  NOT propagate — the batcher carries an explicit per-entry ``stage_out``
  dict and re-enters :func:`collect` around the batch execution, then
  copies the batch-level stages to every rider (each request in a
  micro-batch honestly paid the whole batch's assemble+execute wall).

Keys are stage names from the critical-path histogram; values are
seconds (float). When no collector is active every call is a cheap
no-op, so steady-state single-host serving pays nothing.
"""

from __future__ import annotations

import contextlib
from contextvars import ContextVar
from typing import Dict, Iterator, Optional

_SINK: ContextVar[Optional[Dict[str, float]]] = ContextVar(
    "photon_stage_sink", default=None)


@contextlib.contextmanager
def collect(sink: Dict[str, float]) -> Iterator[Dict[str, float]]:
    """Route :func:`record` calls in this context into ``sink``."""
    token = _SINK.set(sink)
    try:
        yield sink
    finally:
        _SINK.reset(token)


def record(stage: str, seconds: float) -> None:
    """Add ``seconds`` to ``stage`` in the active sink (no-op if none).

    Accumulates rather than overwrites: a chunked execute (or a retried
    assemble) reports its total, matching what the histogram observed.
    """
    sink = _SINK.get()
    if sink is not None:
        sink[stage] = sink.get(stage, 0.0) + float(seconds)
