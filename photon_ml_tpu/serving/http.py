"""Stdlib-HTTP front end for online GAME scoring.

Three JSON endpoints over ``http.server`` (no web framework in the image,
and none needed — handlers are thin marshaling around the registry/batcher):

- ``POST /score``  — ``{"records": [...]}`` (or ``{"record": {...}}``) →
  ``{"scores": [...], "version": v, "latency_ms": ..., "request_id": ...}``.
  Records are TrainingExampleAvro-shaped dicts (``features`` list,
  ``metadataMap``, optional ``offset``). Single records route through the
  microbatcher when enabled; explicit batches go straight to the engine.
- ``GET /healthz`` — liveness + the serving counters the bench asserts on
  (active version, engine compile count, requests/scores served, canary
  reservoir size, request-log budget).
- ``GET /metrics`` — Prometheus text exposition of the process-global
  telemetry registry (request latency histogram, per-stage request-path
  histogram, per-bucket score latency, recompile counter, ...).
- ``POST /reload`` — ``{"model_dir": "..."} `` (optional; defaults to the
  dir served at startup) → validate + hot-swap. A corrupt candidate
  returns 409 and the active version keeps serving.

**Per-request observability** (OBSERVABILITY.md "Request path"): every
request gets an id at this layer — honored from an inbound
``X-Photon-Request-Id`` header, else generated (``uuid4`` hex; telemetry
hygiene rule 7 confines request-id generation HERE so one request never
carries two identities) — echoed back both as a response header and in the
``/score`` JSON body. A ``serving.request`` span (tagged with the id) wraps
the whole handler with ``serving.parse`` / ``serving.score`` /
``serving.respond`` children, and every stage of the critical path lands in
``photon_serving_stage_seconds{stage=parse|queue_wait|batch_assemble|
execute|respond}`` (the queue/engine stages are fed by batcher.py /
engine.py). When a :class:`~photon_ml_tpu.serving.reqlog.RequestLog` is
attached, scored requests are sampled into the durable Avro request log
with the id, model lineage and stage timings.

Every scored request posts a ``serving_request`` event on the registry's
:class:`~photon_ml_tpu.events.EventBus` (latency, batch size, version) —
the same bus training lifecycle events ride, so one metrics exporter
observes both halves of the system. Request latency itself is measured by
the telemetry registry's histogram timer (the hygiene rule: serving code
never calls ``time.perf_counter`` directly — see
``tools/check_telemetry_hygiene.py``).
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Mapping, Optional

from photon_ml_tpu.serving.batcher import MicroBatcher
from photon_ml_tpu.serving.registry import ModelRegistry
from photon_ml_tpu.serving.reqlog import RequestLog
from photon_ml_tpu.telemetry import metrics as _metrics
from photon_ml_tpu.telemetry import tracing as _tracing

#: end-to-end /score handling time (pack + engine + marshaling), the
#: server-side complement of the bench's client-observed latency
_REQUEST_LATENCY = _metrics.histogram(
    "photon_serving_request_latency_seconds",
    "End-to-end /score request handling time")

#: per-stage request-path critical path — this module owns the parse and
#: respond stages (batcher.py owns queue_wait; engine.py owns
#: batch_assemble and execute)
_STAGE_SECONDS = _metrics.histogram(
    "photon_serving_stage_seconds",
    "Serving request time per request-path stage "
    "(parse | queue_wait | batch_assemble | execute | respond)",
    labels=("stage",))

#: the inbound/outbound request-id header
REQUEST_ID_HEADER = "X-Photon-Request-Id"


def new_request_id() -> str:
    """The ONE place a serving request id is minted (hygiene rule 7)."""
    return uuid.uuid4().hex


class ServingService:
    """Endpoint logic, HTTP-free (testable directly; the handler is thin)."""

    def __init__(self, registry: ModelRegistry, *,
                 default_model_dir: Optional[str] = None,
                 batcher: Optional[MicroBatcher] = None,
                 reqlog: Optional[RequestLog] = None):
        self.registry = registry
        self.default_model_dir = default_model_dir
        self.batcher = batcher
        self.reqlog = reqlog
        self._lock = threading.Lock()
        self.n_requests = 0  # guarded-by: _lock
        self.n_scored = 0  # guarded-by: _lock
        # monotonic: uptime is a DURATION (immune to wall-clock jumps, and
        # telemetry hygiene rule 5 bans wall-clock arithmetic for durations)
        self._started_monotonic = time.monotonic()

    # --- endpoints --------------------------------------------------------
    def score(self, payload: dict,
              request_id: Optional[str] = None,
              stage_ms: Optional[Mapping[str, float]] = None) -> dict:
        """Score one request. ``request_id`` is assigned by the HTTP layer
        (direct embedders may omit it — one is minted here so the span and
        the request log never carry an empty identity); ``stage_ms`` folds
        the HTTP layer's already-measured stages (parse) into the logged
        timings."""
        if request_id is None:
            request_id = new_request_id()
        if "record" in payload:
            records = [payload["record"]]
        else:
            records = payload.get("records")
        if not isinstance(records, list) or not records:
            raise ValueError("payload needs 'records': [non-empty list] "
                             "or 'record': {...}")
        with _REQUEST_LATENCY.time() as timer, \
                _tracing.span("serving.score", request_id=request_id,
                              batch=len(records)) as sp:
            version = self.registry.active_version
            if self.batcher is not None and len(records) == 1:
                scores = [self.batcher.score(records[0])]
            else:
                scores = [float(s)
                          for s in self.registry.active().score(records)]
            sp.set(version=version)
        latency_ms = timer.seconds * 1e3
        with self._lock:
            self.n_requests += 1
            self.n_scored += len(records)
        # scored records feed the canary reservoir: the shadow-scoring
        # workload future /reload candidates are judged against
        self.registry.observe_requests(records)
        if self.reqlog is not None:
            timings = dict(stage_ms or {})
            timings["score"] = latency_ms
            self.reqlog.log(request_id=request_id, records=records,
                            scores=scores, version=version,
                            lineage=self._active_lineage(),
                            stage_ms=timings)
        self.registry.bus.post("serving_request", batch=len(records),
                               latency_ms=latency_ms, version=version,
                               request_id=request_id)
        return {"scores": scores, "version": version,
                "latency_ms": round(latency_ms, 3),
                "request_id": request_id}

    def _active_lineage(self) -> Optional[str]:
        active = self.registry.active_or_none()
        return None if active is None else active.lineage

    def healthz(self) -> dict:
        active = self.registry.active_or_none()
        out = {
            "status": "ok" if active is not None else "no_model",
            "version": self.registry.active_version,
            "versions": self.registry.versions(),
            # content lineage of the ACTIVE version + the model it was
            # refreshed from: a fleet probe can now see which hosts serve
            # which model content, and what each refreshed into what,
            # without scraping /metrics
            "model_lineage_id": None if active is None else active.lineage,
            "parentModel": (None if active is None
                            else active.parent_lineage),
            "quality_baseline": (active is not None
                                 and active.baseline is not None),
            "compiles": (0 if active is None
                         else active.engine.compile_count),
            "requests": self.n_requests,
            "scored": self.n_scored,
            # the canary's shadow-scoring workload size — how much live
            # traffic the next /reload candidate will be judged against
            "reservoir": len(self.registry.reservoir),
            "uptime_s": round(time.monotonic() - self._started_monotonic, 1),
        }
        if self.reqlog is not None:
            out["reqlog"] = self.reqlog.stats()
        if active is not None and active.canary is not None:
            out["canary"] = active.canary
        return out

    def reload(self, payload: dict) -> dict:
        model_dir = payload.get("model_dir") or self.default_model_dir
        if not model_dir:
            raise ValueError("payload needs 'model_dir' (no default "
                             "configured)")
        previous = self.registry.active_version
        sm = self.registry.reload(model_dir)
        out = {"version": sm.version, "previous": previous,
               "model_dir": sm.model_dir}
        if sm.canary is not None:
            # canary annotation of this activation (divergence vs the
            # incumbent over the request reservoir, quality/canary.py)
            out["canary"] = sm.canary
        return out

    def close(self) -> None:
        if self.batcher is not None:
            self.batcher.close()
        if self.reqlog is not None:
            self.reqlog.close()


def _make_handler(service: ServingService):
    class Handler(BaseHTTPRequestHandler):
        # per-request log lines go nowhere useful under test/bench load
        def log_message(self, fmt, *args):  # noqa: D102
            pass

        def _request_id(self) -> str:
            """Honor the inbound header; mint otherwise. Echoed on every
            response by :meth:`_reply_raw`."""
            inbound = self.headers.get(REQUEST_ID_HEADER)
            self.request_id = inbound.strip() if inbound else new_request_id()
            return self.request_id

        def _reply(self, status: int, body: dict) -> None:
            self._reply_raw(status, json.dumps(body).encode(),
                            "application/json")

        def _reply_raw(self, status: int, data: bytes,
                       content_type: str) -> None:
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(data)))
            rid = getattr(self, "request_id", None)
            if rid is not None:
                self.send_header(REQUEST_ID_HEADER, rid)
            self.end_headers()
            self.wfile.write(data)

        def _payload(self) -> dict:
            length = int(self.headers.get("Content-Length") or 0)
            if not length:
                return {}
            return json.loads(self.rfile.read(length) or b"{}")

        def do_GET(self):  # noqa: N802
            self._request_id()
            if self.path == "/healthz":
                self._reply(200, service.healthz())
            elif self.path == "/metrics":
                from photon_ml_tpu.telemetry.prometheus import (
                    CONTENT_TYPE,
                    render,
                )

                self._reply_raw(200, render().encode(), CONTENT_TYPE)
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

        def do_POST(self):  # noqa: N802
            rid = self._request_id()
            with _tracing.span("serving.request", request_id=rid,
                               path=self.path):
                self._post_traced(rid)

        def _post_traced(self, rid: str) -> None:
            with _tracing.span("serving.parse", request_id=rid), \
                    _STAGE_SECONDS.labels(stage="parse").time() as parse_t:
                try:
                    payload = self._payload()
                    parse_error = None
                except (ValueError, json.JSONDecodeError) as e:
                    parse_error = e
            if parse_error is not None:
                self._reply(400, {"error": f"bad JSON: {parse_error}"})
                return
            if self.path == "/score":
                try:
                    out = service.score(
                        payload, request_id=rid,
                        stage_ms={"parse": parse_t.seconds * 1e3})
                    status = 200
                except ValueError as e:
                    out, status = {"error": str(e)}, 400
                except Exception as e:
                    out, status = {"error": repr(e)}, 500
                with _tracing.span("serving.respond", request_id=rid), \
                        _STAGE_SECONDS.labels(stage="respond").time():
                    self._reply(status, out)
            elif self.path == "/reload":
                try:
                    self._reply(200, service.reload(payload))
                except Exception as e:
                    # swap REJECTED: the active version is untouched, so
                    # this is a conflict, not a server death
                    self._reply(409, {
                        "error": repr(e),
                        "version": service.registry.active_version})
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

    return Handler


class GameServer:
    """Threaded HTTP server wrapper with a test-friendly lifecycle."""

    def __init__(self, service: ServingService, *, host: str = "127.0.0.1",
                 port: int = 0):
        self.service = service
        self._httpd = ThreadingHTTPServer((host, port),
                                          _make_handler(service))
        #: start/stop are operator-lifecycle calls from one control thread
        self._thread: Optional[threading.Thread] = None  # guarded-by: caller

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "GameServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name="photon-serving-http")
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self._httpd.serve_forever()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join()
        self.service.close()
