"""Stdlib-HTTP front end for online GAME scoring.

Three JSON endpoints over ``http.server`` (no web framework in the image,
and none needed — handlers are thin marshaling around the registry/batcher):

- ``POST /score``  — ``{"records": [...]}`` (or ``{"record": {...}}``) →
  ``{"scores": [...], "version": v, "latency_ms": ...}``. Records are
  TrainingExampleAvro-shaped dicts (``features`` list, ``metadataMap``,
  optional ``offset``). Single records route through the microbatcher when
  enabled; explicit batches go straight to the engine.
- ``GET /healthz`` — liveness + the serving counters the bench asserts on
  (active version, engine compile count, requests/scores served).
- ``GET /metrics`` — Prometheus text exposition of the process-global
  telemetry registry (request latency histogram, per-bucket score
  latency, recompile counter, active version gauge, ...).
- ``POST /reload`` — ``{"model_dir": "..."} `` (optional; defaults to the
  dir served at startup) → validate + hot-swap. A corrupt candidate
  returns 409 and the active version keeps serving.

Every scored request posts a ``serving_request`` event on the registry's
:class:`~photon_ml_tpu.events.EventBus` (latency, batch size, version) —
the same bus training lifecycle events ride, so one metrics exporter
observes both halves of the system. Request latency itself is measured by
the telemetry registry's histogram timer (the hygiene rule: serving code
never calls ``time.perf_counter`` directly — see
``tools/check_telemetry_hygiene.py``).
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from photon_ml_tpu.serving.batcher import MicroBatcher
from photon_ml_tpu.serving.registry import ModelRegistry
from photon_ml_tpu.telemetry import metrics as _metrics

#: end-to-end /score handling time (pack + engine + marshaling), the
#: server-side complement of the bench's client-observed latency
_REQUEST_LATENCY = _metrics.histogram(
    "photon_serving_request_latency_seconds",
    "End-to-end /score request handling time")


class ServingService:
    """Endpoint logic, HTTP-free (testable directly; the handler is thin)."""

    def __init__(self, registry: ModelRegistry, *,
                 default_model_dir: Optional[str] = None,
                 batcher: Optional[MicroBatcher] = None):
        self.registry = registry
        self.default_model_dir = default_model_dir
        self.batcher = batcher
        self._lock = threading.Lock()
        self.n_requests = 0
        self.n_scored = 0
        # monotonic: uptime is a DURATION (immune to wall-clock jumps, and
        # telemetry hygiene rule 5 bans wall-clock arithmetic for durations)
        self._started_monotonic = time.monotonic()

    # --- endpoints --------------------------------------------------------
    def score(self, payload: dict) -> dict:
        if "record" in payload:
            records = [payload["record"]]
        else:
            records = payload.get("records")
        if not isinstance(records, list) or not records:
            raise ValueError("payload needs 'records': [non-empty list] "
                             "or 'record': {...}")
        with _REQUEST_LATENCY.time() as timer:
            version = self.registry.active_version
            if self.batcher is not None and len(records) == 1:
                scores = [self.batcher.score(records[0])]
            else:
                scores = [float(s)
                          for s in self.registry.active().score(records)]
        latency_ms = timer.seconds * 1e3
        with self._lock:
            self.n_requests += 1
            self.n_scored += len(records)
        # scored records feed the canary reservoir: the shadow-scoring
        # workload future /reload candidates are judged against
        self.registry.observe_requests(records)
        self.registry.bus.post("serving_request", batch=len(records),
                               latency_ms=latency_ms, version=version)
        return {"scores": scores, "version": version,
                "latency_ms": round(latency_ms, 3)}

    def healthz(self) -> dict:
        active = self.registry.active_or_none()
        out = {
            "status": "ok" if active is not None else "no_model",
            "version": self.registry.active_version,
            "versions": self.registry.versions(),
            # content lineage of the ACTIVE version + the model it was
            # refreshed from: a fleet probe can now see which hosts serve
            # which model content, and what each refreshed into what,
            # without scraping /metrics
            "model_lineage_id": None if active is None else active.lineage,
            "parentModel": (None if active is None
                            else active.parent_lineage),
            "quality_baseline": (active is not None
                                 and active.baseline is not None),
            "compiles": (0 if active is None
                         else active.engine.compile_count),
            "requests": self.n_requests,
            "scored": self.n_scored,
            "uptime_s": round(time.monotonic() - self._started_monotonic, 1),
        }
        if active is not None and active.canary is not None:
            out["canary"] = active.canary
        return out

    def reload(self, payload: dict) -> dict:
        model_dir = payload.get("model_dir") or self.default_model_dir
        if not model_dir:
            raise ValueError("payload needs 'model_dir' (no default "
                             "configured)")
        previous = self.registry.active_version
        sm = self.registry.reload(model_dir)
        out = {"version": sm.version, "previous": previous,
               "model_dir": sm.model_dir}
        if sm.canary is not None:
            # canary annotation of this activation (divergence vs the
            # incumbent over the request reservoir, quality/canary.py)
            out["canary"] = sm.canary
        return out

    def close(self) -> None:
        if self.batcher is not None:
            self.batcher.close()


def _make_handler(service: ServingService):
    class Handler(BaseHTTPRequestHandler):
        # per-request log lines go nowhere useful under test/bench load
        def log_message(self, fmt, *args):  # noqa: D102
            pass

        def _reply(self, status: int, body: dict) -> None:
            self._reply_raw(status, json.dumps(body).encode(),
                            "application/json")

        def _reply_raw(self, status: int, data: bytes,
                       content_type: str) -> None:
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _payload(self) -> dict:
            length = int(self.headers.get("Content-Length") or 0)
            if not length:
                return {}
            return json.loads(self.rfile.read(length) or b"{}")

        def do_GET(self):  # noqa: N802
            if self.path == "/healthz":
                self._reply(200, service.healthz())
            elif self.path == "/metrics":
                from photon_ml_tpu.telemetry.prometheus import (
                    CONTENT_TYPE,
                    render,
                )

                self._reply_raw(200, render().encode(), CONTENT_TYPE)
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

        def do_POST(self):  # noqa: N802
            try:
                payload = self._payload()
            except (ValueError, json.JSONDecodeError) as e:
                self._reply(400, {"error": f"bad JSON: {e}"})
                return
            if self.path == "/score":
                try:
                    self._reply(200, service.score(payload))
                except ValueError as e:
                    self._reply(400, {"error": str(e)})
                except Exception as e:
                    self._reply(500, {"error": repr(e)})
            elif self.path == "/reload":
                try:
                    self._reply(200, service.reload(payload))
                except Exception as e:
                    # swap REJECTED: the active version is untouched, so
                    # this is a conflict, not a server death
                    self._reply(409, {
                        "error": repr(e),
                        "version": service.registry.active_version})
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

    return Handler


class GameServer:
    """Threaded HTTP server wrapper with a test-friendly lifecycle."""

    def __init__(self, service: ServingService, *, host: str = "127.0.0.1",
                 port: int = 0):
        self.service = service
        self._httpd = ThreadingHTTPServer((host, port),
                                          _make_handler(service))
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "GameServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name="photon-serving-http")
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self._httpd.serve_forever()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join()
        self.service.close()
