"""Stdlib-HTTP front end for online GAME scoring.

Three JSON endpoints over ``http.server`` (no web framework in the image,
and none needed — handlers are thin marshaling around the registry/batcher):

- ``POST /score``  — ``{"records": [...]}`` (or ``{"record": {...}}``) →
  ``{"scores": [...], "version": v, "latency_ms": ..., "request_id": ...}``.
  Records are TrainingExampleAvro-shaped dicts (``features`` list,
  ``metadataMap``, optional ``offset``). Single records route through the
  microbatcher when enabled; explicit batches go straight to the engine.
  A request refused by admission control — full bounded queue, expired
  ``X-Photon-Deadline-Ms`` budget, max brownout — returns **429** with a
  ``Retry-After`` header and a ``reason`` body field (never a hang; see
  SERVING.md "Serving under overload"). A deadline's remaining budget is
  echoed back (header + ``deadline_ms``) like the request id.
- ``GET /rank?user=...&k=...`` (also ``POST /rank`` with a full
  ``record``) — top-k retrieval over the configured item coordinate
  (``serve_game --rank-item-coordinate``; SERVING.md "Ranked
  retrieval"): ``{"ids": [...], "scores": [...], "k", "version",
  "latency_ms", "request_id"}``. Same admission control, deadline and
  brownout semantics as ``/score``; ranked requests land in the request
  log as ``kind="rank"`` with their returned top-k.
- ``GET /healthz`` — liveness + the serving counters the bench asserts on
  (active version, engine compile count, requests/scores served, ranked
  request/item counters when ranking is on, canary reservoir size,
  request-log budget, queue depth / shed tallies / brownout level).
- ``GET /readyz`` — readiness: 503 (with reasons) while there is no
  active model, the batcher worker is dead, or brownout is at max level;
  what load balancers and ``bench_serving`` gate on.
- ``GET /metrics`` — Prometheus text exposition of the process-global
  telemetry registry (request latency histogram, per-stage request-path
  histogram, per-bucket score latency, recompile counter, ...).
- ``POST /reload`` — ``{"model_dir": "..."} `` (optional; defaults to the
  dir served at startup) → validate + hot-swap. A corrupt candidate
  returns 409 and the active version keeps serving.

**Per-request observability** (OBSERVABILITY.md "Request path"): every
request gets an id at this layer — honored from an inbound
``X-Photon-Request-Id`` header, else generated (``uuid4`` hex; telemetry
hygiene rule 7 confines request-id generation HERE so one request never
carries two identities) — echoed back both as a response header and in the
``/score`` JSON body. A ``serving.request`` span (tagged with the id) wraps
the whole handler with ``serving.parse`` / ``serving.score`` /
``serving.respond`` children, and every stage of the critical path lands in
``photon_serving_stage_seconds{stage=parse|queue_wait|batch_assemble|
execute|respond}`` (the queue/engine stages are fed by batcher.py /
engine.py). When a :class:`~photon_ml_tpu.serving.reqlog.RequestLog` is
attached, scored requests are sampled into the durable Avro request log
with the id, model lineage and stage timings.

Every scored request posts a ``serving_request`` event on the registry's
:class:`~photon_ml_tpu.events.EventBus` (latency, batch size, version) —
the same bus training lifecycle events ride, so one metrics exporter
observes both halves of the system. Request latency itself is measured by
the telemetry registry's histogram timer (the hygiene rule: serving code
never calls ``time.perf_counter`` directly — see
``tools/check_telemetry_hygiene.py``).
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlsplit
from typing import Mapping, Optional

from photon_ml_tpu.resilience.faults import fault_point
from photon_ml_tpu.serving import overload as _overload
from photon_ml_tpu.serving import stages as _stages
from photon_ml_tpu.serving.batcher import BatcherClosed, MicroBatcher
from photon_ml_tpu.serving.registry import ModelRegistry
from photon_ml_tpu.serving.reqlog import RequestLog
from photon_ml_tpu.telemetry import metrics as _metrics
from photon_ml_tpu.telemetry import tracing as _tracing

#: end-to-end /score handling time (pack + engine + marshaling), the
#: server-side complement of the bench's client-observed latency
_REQUEST_LATENCY = _metrics.histogram(
    "photon_serving_request_latency_seconds",
    "End-to-end /score request handling time")

#: per-stage request-path critical path — this module owns the parse and
#: respond stages (batcher.py owns queue_wait; engine.py owns
#: batch_assemble and execute)
_STAGE_SECONDS = _metrics.histogram(
    "photon_serving_stage_seconds",
    "Serving request time per request-path stage "
    "(parse | queue_wait | batch_assemble | execute | respond)",
    labels=("stage",))

#: end-to-end /rank handling time — the ranked twin of the /score
#: histogram (shed requests are discarded from it, same as /score)
_RANK_REQUEST_LATENCY = _metrics.histogram(
    "photon_rank_request_latency_seconds",
    "End-to-end /rank request handling time")

#: requested-k distribution of admitted /rank requests (power-of-two
#: buckets — the same buckets the ranking engine's executables pad to)
_RANK_K = _metrics.histogram(
    "photon_rank_k",
    "Requested k per admitted /rank request",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024))

# --- connection plane (OBSERVABILITY.md "Saturation & capacity") -----------
# THE one sanctioned home for serving socket accounting (lint rule
# tel-conn-home): the baseline instrument the future event-loop front end
# must preserve — accepts/closes/refusals, open vs idle keep-alive
# sockets, connection lifetime and requests-per-connection.

_CONN_ACCEPTED = _metrics.counter(
    "photon_connections_accepted_total",
    "Client connections accepted by the serving front end")
_CONN_CLOSED = _metrics.counter(
    "photon_connections_closed_total",
    "Accepted client connections since closed (accepted == closed + "
    "open, the accounting identity the chaos harness asserts)")
_CONN_REFUSED = _metrics.counter(
    "photon_connections_refused_total",
    "Connections refused by the --max-connections budget (each is "
    "answered with one typed 503 reason=connections + Connection: close)")

#: instantaneous socket accounting — host-owned: each process holds its
#: own sockets, so a fleet fold fans these out per host
_CONN_OPEN = _metrics.gauge(
    "photon_connections_open",
    "Client connections currently open (accepted, not yet closed)")
_CONN_IDLE = _metrics.gauge(
    "photon_connections_idle",
    "Open keep-alive connections with no request in flight")
_CONN_PEAK = _metrics.gauge(
    "photon_connections_peak",
    "High-water mark of concurrently open client connections")
for _g in ("photon_connections_open", "photon_connections_idle",
           "photon_connections_peak"):
    _metrics.mark_host_owned(_g)

#: keep-alive connections live far longer than requests — wider bounds
#: than the latency buckets
_CONN_LIFETIME = _metrics.histogram(
    "photon_connection_lifetime_seconds",
    "Lifetime of each closed client connection (accept to close)",
    buckets=(0.001, 0.01, 0.1, 0.5, 1.0, 5.0, 30.0, 60.0, 300.0,
             1800.0, 3600.0))
_CONN_REQUESTS = _metrics.histogram(
    "photon_connection_requests",
    "Requests served per closed client connection (keep-alive reuse)",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024))


class ConnectionTracker:
    """Lock-disciplined accounting for the serving front end's client
    sockets — the one place (``tel-conn-home``) connection counts live,
    whatever the I/O model behind them.

    Invariant, held under one lock and asserted by the chaos harness:
    ``accepted == closed + open``. ``max_connections`` (0 = unlimited)
    is the admission budget: a connection past the ceiling is REFUSED —
    counted here, answered by the handler with a typed 503
    ``reason=connections`` + ``Connection: close`` — never queued and
    never hung, exactly like every other admission refusal."""

    def __init__(self, max_connections: int = 0):
        self.max_connections = max(0, int(max_connections))
        self._lock = threading.Lock()
        self.accepted = 0  # guarded-by: _lock
        self.closed = 0  # guarded-by: _lock
        self.refused = 0  # guarded-by: _lock
        self.open = 0  # guarded-by: _lock
        self.active = 0  # guarded-by: _lock
        self.peak = 0  # guarded-by: _lock

    def connect(self) -> bool:
        """Account one inbound connection; False = over budget (the
        caller owes the client one typed refusal before closing)."""
        with self._lock:
            if self.max_connections and self.open >= self.max_connections:
                self.refused += 1
                _CONN_REFUSED.inc()
                return False
            self.accepted += 1
            self.open += 1
            if self.open > self.peak:
                self.peak = self.open
                _CONN_PEAK.set(self.peak)
            _CONN_ACCEPTED.inc()
            _CONN_OPEN.set(self.open)
            _CONN_IDLE.set(self.open - self.active)
            return True

    def disconnect(self, lifetime_s: float, n_requests: int,
                   admitted: bool = True) -> None:
        if not admitted:
            return  # refused connections were never counted open
        with self._lock:
            self.closed += 1
            self.open = max(0, self.open - 1)
            _CONN_CLOSED.inc()
            _CONN_OPEN.set(self.open)
            _CONN_IDLE.set(max(0, self.open - self.active))
        _CONN_LIFETIME.observe(max(0.0, float(lifetime_s)))
        _CONN_REQUESTS.observe(max(0, int(n_requests)))

    def request_begin(self) -> None:
        with self._lock:
            self.active += 1
            _CONN_IDLE.set(max(0, self.open - self.active))

    def request_end(self) -> None:
        with self._lock:
            self.active = max(0, self.active - 1)
            _CONN_IDLE.set(max(0, self.open - self.active))

    def utilization(self) -> float:
        """Open connections over the budget (0.0 when unlimited) — the
        ``http_connections`` saturation probe and the overload
        controller's connection-pressure input."""
        with self._lock:
            if not self.max_connections:
                return 0.0
            return min(1.0, self.open / self.max_connections)

    def exhausted(self) -> bool:
        """At (or past) the budget ceiling — what flips ``/readyz`` to
        503 ``connections_exhausted``."""
        with self._lock:
            return bool(self.max_connections
                        and self.open >= self.max_connections)

    def stats(self) -> dict:
        """The ``/healthz`` connection block (scrape equivalents are the
        ``photon_connections_*`` families)."""
        with self._lock:
            return {"open": self.open,
                    "idle": max(0, self.open - self.active),
                    "active": self.active,
                    "peak": self.peak,
                    "budget": self.max_connections,
                    "accepted": self.accepted,
                    "closed": self.closed,
                    "refused": self.refused}

#: the inbound/outbound request-id header
REQUEST_ID_HEADER = "X-Photon-Request-Id"

#: inbound: the caller's remaining latency budget in milliseconds, stamped
#: against the monotonic clock at parse time; outbound: the budget still
#: remaining when the response was written (echoed like the request id)
DEADLINE_HEADER = "X-Photon-Deadline-Ms"

#: the bucket→shard map content hash (``ShardMap.map_hash``). Outbound on
#: every sharded host's /score + /rank response (next to ``lineage``);
#: inbound from the fleet router, checked against this host's ACTIVE map —
#: a disagreement is refused (503, ``reason=shard_map_mismatch``) exactly
#: like a mixed-lineage fan-out, because answering under the wrong map
#: would silently score rows this host no longer owns
SHARD_MAP_HEADER = "X-Photon-Shard-Map"

#: outbound on 200 ``/score`` + ``/rank`` responses: this request's
#: per-stage seconds and the host-side span id, compactly encoded
#: (``span=<id>;parse=<s>;queue_wait=<s>;...``), so a fleet router can
#: stitch each fan-out leg's remote stage breakdown into its own trace
#: tree (OBSERVABILITY.md "Fleet observability"). Single-host clients may
#: ignore it; absent stages are simply omitted.
LEG_SUMMARY_HEADER = "X-Photon-Leg-Summary"

#: the CLOSED stage vocabulary a leg summary may carry — exactly the
#: request-path critical-path stages. Parsing a (possibly foreign)
#: header must never mint unbounded span-attribute or label values, so
#: both directions are restricted to these keys (the
#: ``tel-span-attr-cardinality`` lint guards the consumers).
LEG_SUMMARY_STAGES = (
    "parse", "queue_wait", "batch_assemble", "execute", "respond")


def format_leg_summary(stages: Mapping[str, float]) -> str:
    """Encode a stage-seconds mapping (plus optional ``span`` id) as the
    ``X-Photon-Leg-Summary`` header value. Only the closed stage
    vocabulary is emitted; seconds carry microsecond precision."""
    parts = []
    span_id = stages.get("span")
    if span_id is not None:
        parts.append(f"span={int(span_id)}")
    for key in LEG_SUMMARY_STAGES:
        value = stages.get(key)
        if value is not None:
            parts.append(f"{key}={float(value):.6f}")
    return ";".join(parts)


def parse_leg_summary(value: "Optional[str]") -> dict:
    """Decode a leg-summary header → ``{stage: seconds}`` (+ ``span``
    int). Defensive by design: unknown keys and malformed values are
    DROPPED, not surfaced — this dict feeds span attributes, and an
    arbitrary upstream must not be able to inject unbounded attribute
    keys or non-numeric values into the trace."""
    out: dict = {}
    for part in (value or "").split(";"):
        key, eq, raw = part.partition("=")
        if not eq:
            continue
        key = key.strip()
        if key == "span":
            try:
                out["span"] = int(raw)
            except ValueError:
                pass
        elif key in LEG_SUMMARY_STAGES:
            try:
                out[key] = float(raw)
            except ValueError:
                pass
    return out


class ShardMapMismatch(RuntimeError):
    """Router and host disagree on the bucket→shard map. Refused like
    mixed lineage (SERVING.md "Fleet serving"): mid-reshard, a request
    routed under one map must never be answered under another."""


def new_request_id() -> str:
    """The ONE place a serving request id is minted (hygiene rule 7)."""
    return uuid.uuid4().hex


class _NullSpan:
    """Span stand-in while brownout sheds tracing (level 3+)."""

    def set(self, **attrs) -> None:
        pass


_NULL_SPAN = _NullSpan()


def shed_status(e: "_overload.Shed") -> int:
    """HTTP status for a typed shed: 429 (busy — retry the same place)
    for admission-control refusals, **503** for ``reason="upstream"``
    (the fleet router lost a host leg) and ``reason="connections"`` (the
    socket budget is spent) — in both the capacity is gone, not busy,
    so the client should go elsewhere rather than hammer this host."""
    return 503 if e.reason in ("upstream", "connections") else 429


@contextlib.contextmanager
def _maybe_span(name: str, **attrs):
    """A ``serving.*`` span — unless brownout has shed span tracing
    (optional work goes before traffic; SERVING.md overload ladder)."""
    if _overload.is_shed("tracing"):
        yield _NULL_SPAN
        return
    with _tracing.span(name, **attrs) as sp:
        yield sp


class ServingService:
    """Endpoint logic, HTTP-free (testable directly; the handler is thin)."""

    def __init__(self, registry: ModelRegistry, *,
                 default_model_dir: Optional[str] = None,
                 batcher: Optional[MicroBatcher] = None,
                 rank_batcher: Optional[MicroBatcher] = None,
                 reqlog: Optional[RequestLog] = None,
                 default_timeout_ms: float = 0.0,
                 overload=None,
                 connections: Optional[ConnectionTracker] = None):
        self.registry = registry
        self.default_model_dir = default_model_dir
        self.batcher = batcher
        #: the /rank coalescing queue (identity-coerced MicroBatcher over
        #: (record, k) tuples) — same bounded-queue admission control and
        #: deadline-at-drain shedding as the /score batcher
        self.rank_batcher = rank_batcher
        self.reqlog = reqlog
        #: server-side deadline applied to requests that carry no
        #: X-Photon-Deadline-Ms of their own (0 = none)
        self.default_timeout_ms = float(default_timeout_ms)
        #: optional OverloadController (serving/overload.py), owned here:
        #: closed with the service, surfaced by /readyz
        self.overload = overload
        #: the connection-plane accounting (always on — the budget is
        #: what's optional): every handler setup/finish and request
        #: passes through it, and /healthz + /readyz surface its stats
        self.connections = connections if connections is not None \
            else ConnectionTracker()
        self._lock = threading.Lock()
        self.n_requests = 0  # guarded-by: _lock
        self.n_scored = 0  # guarded-by: _lock
        self.n_ranked = 0  # guarded-by: _lock
        # monotonic: uptime is a DURATION (immune to wall-clock jumps, and
        # telemetry hygiene rule 5 bans wall-clock arithmetic for durations)
        self._started_monotonic = time.monotonic()

    # --- deadlines --------------------------------------------------------
    def resolve_deadline(self,
                         budget_ms: "str | float | None") -> Optional[float]:
        """Stamp a request's latency budget against the monotonic clock —
        called AT PARSE TIME so queueing and scoring spend the same
        budget the caller measures. ``budget_ms`` is the raw
        ``X-Photon-Deadline-Ms`` header (or a number); absent, the
        server-side ``default_timeout_ms`` applies; neither → None (no
        deadline). Raises ValueError on an unparsable header."""
        if budget_ms is None or budget_ms == "":
            budget_ms = (self.default_timeout_ms
                         if self.default_timeout_ms > 0 else None)
        if budget_ms is None:
            return None
        try:
            budget = float(budget_ms)
        except (TypeError, ValueError):
            raise ValueError(
                f"bad {DEADLINE_HEADER} header {budget_ms!r} (want a "
                f"millisecond budget)") from None
        return time.monotonic() + budget / 1e3

    @staticmethod
    def remaining_ms(deadline: Optional[float]) -> Optional[float]:
        if deadline is None:
            return None
        return max(0.0, (deadline - time.monotonic()) * 1e3)

    # --- shard map --------------------------------------------------------
    def check_shard_map(self, claimed: "Optional[str]") -> None:
        """Refuse a request routed under a different bucket→shard map
        than this host's active one (``X-Photon-Shard-Map`` header).
        Absent header → no check (plain clients and unsharded hosts are
        unaffected); a stale/foreign hash raises
        :class:`ShardMapMismatch` → 503 ``reason=shard_map_mismatch``."""
        if not claimed:
            return
        have = getattr(self.registry, "shard_map_hash", None)
        if have is not None and claimed != have:
            raise ShardMapMismatch(
                f"request routed under shard map {claimed} but this host "
                f"serves {have} — refusing rather than answering for "
                f"rows it may not own")

    # --- endpoints --------------------------------------------------------
    def score(self, payload: dict,
              request_id: Optional[str] = None,
              stage_ms: Optional[Mapping[str, float]] = None,
              deadline: Optional[float] = None,
              stage_sink: Optional[dict] = None) -> dict:
        """Score one request. ``request_id`` is assigned by the HTTP layer
        (direct embedders may omit it — one is minted here so the span and
        the request log never carry an empty identity); ``stage_ms`` folds
        the HTTP layer's already-measured stages (parse) into the logged
        timings; ``deadline`` is the absolute monotonic instant from
        :meth:`resolve_deadline`. Raises
        :class:`~photon_ml_tpu.serving.overload.Shed` (→ 429) when the
        request is refused by admission control — an expired deadline, a
        full microbatcher queue, or max brownout — WITHOUT it ever
        reaching the engine's execute stage or the latency histogram.
        ``stage_sink``, when given, receives this request's stage
        seconds + the score span id — the leg-summary side channel the
        fleet router stitches into its trace."""
        if request_id is None:
            request_id = new_request_id()
        if "record" in payload:
            records = [payload["record"]]
        else:
            records = payload.get("records")
        if not isinstance(records, list) or not records:
            raise ValueError("payload needs 'records': [non-empty list] "
                             "or 'record': {...}")
        # margins=true (the fleet router's merge protocol): respond with
        # the per-coordinate f32 margins + offsets next to the scores, so
        # a routing tier can recombine coordinates owned by different
        # shards through the same sum_coordinate_margins reduction
        with_margins = bool(payload.get("margins"))
        if deadline is not None and time.monotonic() >= deadline:
            # the caller already gave up — scoring would be pure waste
            raise _overload.shed(
                "deadline", message="deadline expired before scoring")
        if _overload.traffic_shed():
            raise _overload.shed(
                "brownout",
                message=f"brownout level {_overload.level()} is shedding "
                        f"traffic",
                retry_after_s=2.0)
        margins = offsets = None
        # the stage side channel: same-thread stages reach the sink via
        # the collect() contextvar; the batched path crosses the worker
        # thread, so the sink also rides the batcher entry (stage_out)
        sink = stage_sink if stage_sink is not None else {}
        with _REQUEST_LATENCY.time() as timer, \
                _maybe_span("serving.score", request_id=request_id,
                            batch=len(records)) as sp, \
                _stages.collect(sink):
            version = self.registry.active_version
            try:
                if with_margins:
                    # margin responses bypass the batcher: the margin set
                    # is per-request shaped, not coalescible
                    raw, offsets, margins = \
                        self.registry.active().engine.score_margins(records)
                    scores = [float(s) for s in raw]
                elif self.batcher is not None and len(records) == 1:
                    scores = [self.batcher.score(records[0],
                                                 deadline=deadline,
                                                 stage_out=stage_sink)]
                else:
                    scores = [float(s)
                              for s in self.registry.active().score(records)]
            except _overload.Shed:
                # shed while queued (queue_full at submit, deadline at
                # drain): excluded from the latency distribution — a
                # refusal is not a serving latency
                timer.discard()
                raise
            sp.set(version=version)
            if stage_sink is not None:
                span_id = getattr(sp, "span_id", None)
                if span_id is not None:
                    stage_sink["span"] = span_id
        latency_ms = timer.seconds * 1e3
        with self._lock:
            self.n_requests += 1
            self.n_scored += len(records)
        # scored records feed the canary reservoir: the shadow-scoring
        # workload future /reload candidates are judged against
        self.registry.observe_requests(records)
        if self.reqlog is not None:
            timings = dict(stage_ms or {})
            timings["score"] = latency_ms
            self.reqlog.log(request_id=request_id, records=records,
                            scores=scores, version=version,
                            lineage=self._active_lineage(),
                            stage_ms=timings)
        self.registry.bus.post("serving_request", batch=len(records),
                               latency_ms=latency_ms, version=version,
                               request_id=request_id)
        out = {"scores": scores, "version": version,
               # content lineage rides every response so a routing tier
               # can PROVE no reply ever mixes model generations (the
               # fleet's no-mixed-lineage invariant is checked per fan-out)
               "lineage": self._active_lineage(),
               "latency_ms": round(latency_ms, 3),
               "request_id": request_id}
        smh = getattr(self.registry, "shard_map_hash", None)
        if smh is not None:
            # the map hash rides next to lineage: the router proves no
            # fan-out mixes bucket→shard generations, same as model content
            out["shard_map"] = smh
        if with_margins:
            # f32 widened to double — exact, so the router re-running
            # sum_coordinate_margins reproduces this host's totals
            out["margins"] = [[cid, [float(v) for v in m]]
                              for cid, m in margins]
            out["offsets"] = [float(v) for v in offsets]
        if deadline is not None:
            # echo the remaining budget like the request id: the caller
            # (or a downstream hop) sees how much headroom survived
            out["deadline_ms"] = round(self.remaining_ms(deadline), 1)
        return out

    def rank(self, payload: dict,
             request_id: Optional[str] = None,
             stage_ms: Optional[Mapping[str, float]] = None,
             deadline: Optional[float] = None,
             stage_sink: Optional[dict] = None) -> dict:
        """Rank one user against the active version's item axis
        (SERVING.md "Ranked retrieval"). ``payload`` carries ``k`` plus
        either ``user`` (a raw entity id — ranked featureless, applied to
        every non-item coordinate's entity type) or a full ``record``.
        Same admission contract as :meth:`score`: an expired deadline, a
        full rank queue, or max brownout raises
        :class:`~photon_ml_tpu.serving.overload.Shed` (→ 429) without the
        request ever reaching the engine's execute stage, and sheds are
        excluded from the latency histogram."""
        if request_id is None:
            request_id = new_request_id()
        active = self.registry.active()
        engine = active.rank_engine
        if engine is None:
            raise ValueError("ranking is not enabled (start serve_game "
                             "with --rank-item-coordinate)")
        try:
            # absent k defaults to 10, clamped by the engine bound so a
            # bare GET /rank?user=... works on any configuration
            k = int(payload.get("k", min(10, engine.max_k)))
        except (TypeError, ValueError):
            raise ValueError(
                f"bad k {payload.get('k')!r} (want an integer)") from None
        if not 1 <= k <= engine.max_k:
            raise ValueError(f"k must be in [1, {engine.max_k}], got {k}")
        record = payload.get("record")
        if record is None:
            user = payload.get("user")
            if not user:
                raise ValueError("payload needs 'user' (raw entity id) "
                                 "or 'record' ({features, metadataMap})")
            record = {"features": [],
                      "metadataMap": {t: str(user)
                                      for t in engine.user_entity_types},
                      "offset": None}
        if deadline is not None and time.monotonic() >= deadline:
            raise _overload.shed(
                "deadline", message="deadline expired before ranking")
        if _overload.traffic_shed():
            raise _overload.shed(
                "brownout",
                message=f"brownout level {_overload.level()} is shedding "
                        f"traffic",
                retry_after_s=2.0)
        sink = stage_sink if stage_sink is not None else {}
        with _RANK_REQUEST_LATENCY.time() as timer, \
                _maybe_span("serving.rank", request_id=request_id,
                            k=k) as sp, \
                _stages.collect(sink):
            version = self.registry.active_version
            try:
                if self.rank_batcher is not None:
                    ids, scores = self.rank_batcher.score(
                        (record, k), deadline=deadline,
                        stage_out=stage_sink)
                else:
                    ((ids, scores),) = active.rank([record], [k])
            except _overload.Shed:
                timer.discard()
                raise
            sp.set(version=version, n=len(ids))
            if stage_sink is not None:
                span_id = getattr(sp, "span_id", None)
                if span_id is not None:
                    stage_sink["span"] = span_id
        _RANK_K.observe(k)
        latency_ms = timer.seconds * 1e3
        with self._lock:
            self.n_requests += 1
            self.n_ranked += 1
        if self.reqlog is not None:
            timings = dict(stage_ms or {})
            timings["rank"] = latency_ms
            self.reqlog.log(
                request_id=request_id, records=[record], scores=[0.0],
                version=version, lineage=self._active_lineage(),
                stage_ms=timings, kind="rank",
                topk={"k": k, "ids": list(ids),
                      "scores": [float(s) for s in scores]})
        self.registry.bus.post("rank_request", k=k, n=len(ids),
                               latency_ms=latency_ms, version=version,
                               request_id=request_id)
        out = {"ids": list(ids), "scores": [float(s) for s in scores],
               "k": k, "version": version,
               "lineage": self._active_lineage(),
               "latency_ms": round(latency_ms, 3),
               "request_id": request_id}
        smh = getattr(self.registry, "shard_map_hash", None)
        if smh is not None:
            out["shard_map"] = smh
        if deadline is not None:
            out["deadline_ms"] = round(self.remaining_ms(deadline), 1)
        return out

    def _active_lineage(self) -> Optional[str]:
        active = self.registry.active_or_none()
        return None if active is None else active.lineage

    def healthz(self) -> dict:
        active = self.registry.active_or_none()
        out = {
            "status": "ok" if active is not None else "no_model",
            "version": self.registry.active_version,
            "versions": self.registry.versions(),
            # content lineage of the ACTIVE version + the model it was
            # refreshed from: a fleet probe can now see which hosts serve
            # which model content, and what each refreshed into what,
            # without scraping /metrics
            "model_lineage_id": None if active is None else active.lineage,
            "parentModel": (None if active is None
                            else active.parent_lineage),
            "quality_baseline": (active is not None
                                 and active.baseline is not None),
            # the fleet topology facts a router needs: which shard this
            # host holds, and the model's coordinate walk (id, entity
            # type or null for the fixed effect) IN ORDER — the router's
            # margin merge re-runs sum_coordinate_margins in exactly this
            # order, and shard resolution hashes these entity types' ids
            "fleet_shard": (None if self.registry.fleet_shard is None
                            else list(self.registry.fleet_shard)),
            # the governing bucket→shard map (sharded hosts only): its
            # content hash + version, so a router/probe can audit that
            # every host serves the same map generation
            "shard_map": (None if getattr(self.registry, "shard_map",
                                          None) is None
                          else {"hash": self.registry.shard_map.map_hash,
                                "version": self.registry.shard_map.version,
                                "nShards": self.registry.shard_map.n_shards}),
            "coordinates": (None if active is None else [
                [cid, getattr(cm, "random_effect_type", None)]
                for cid, cm in active.model.coordinates.items()]),
            "compiles": (0 if active is None
                         else active.engine.compile_count),
            "requests": self.n_requests,
            "scored": self.n_scored,
            # the canary's shadow-scoring workload size — how much live
            # traffic the next /reload candidate will be judged against
            "reservoir": len(self.registry.reservoir),
            "uptime_s": round(time.monotonic() - self._started_monotonic, 1),
            # the overload story, mirrored into /readyz: how deep the
            # queue is, what has been shed so far, how degraded we are
            "queue_depth": (0 if self.batcher is None
                            else self.batcher.queue_depth()),
            "shed": _overload.shed_counts(),
            "brownout_level": _overload.level(),
            # the connection plane: open/idle/peak sockets + the
            # --max-connections budget (0 = unlimited)
            "connections": self.connections.stats(),
        }
        if self.reqlog is not None:
            out["reqlog"] = self.reqlog.stats()
        if active is not None and active.canary is not None:
            out["canary"] = active.canary
        if active is not None and active.rank_engine is not None:
            # the ranked workload's counters: item-axis size, requests
            # served, and the serving.rank compile counter the
            # zero-recompile contract is asserted against
            out["rank"] = {
                "items": active.rank_engine.index.n_items,
                "max_k": active.rank_engine.max_k,
                "requests": self.n_ranked,
                "compiles": active.rank_engine.compile_count,
                # user-side RE coordinates constrain fleet rank fan-out
                # (their sharded stores would drop the user's margin on
                # foreign hosts); the router refuses to rank past them
                "user_re_coordinates": list(
                    active.rank_engine.user_re_coordinates),
            }
        return out

    def readyz(self) -> tuple[int, dict]:
        """Readiness, as distinct from liveness: a process can be alive
        (``/healthz`` answers) yet unable to serve — no active model, a
        dead batcher worker, or brownout at max level (shedding traffic).
        Returns ``(status, body)``: 200 ready / 503 not ready, with the
        reasons and the same overload telemetry ``/healthz`` carries, so
        a load balancer can both gate on the code and explain the gate."""
        reasons = []
        if self.registry.active_or_none() is None:
            reasons.append("no_active_model")
        if self.batcher is not None and self.batcher.dead is not None:
            reasons.append("batcher_worker_dead")
        if self.rank_batcher is not None \
                and self.rank_batcher.dead is not None:
            reasons.append("rank_batcher_worker_dead")
        lvl = _overload.level()
        if lvl >= _overload.MAX_LEVEL:
            reasons.append("brownout_max")
        if self.connections.exhausted():
            # at the socket ceiling a load balancer must route around
            # this host NOW — new connections are being refused
            reasons.append("connections_exhausted")
        body = {
            "ready": not reasons,
            "reasons": reasons,
            "version": self.registry.active_version,
            "queue_depth": (0 if self.batcher is None
                            else self.batcher.queue_depth()),
            "shed": _overload.shed_counts(),
            "brownout_level": lvl,
            "connections": self.connections.stats(),
        }
        return (200 if not reasons else 503), body

    def reload(self, payload: dict) -> dict:
        """One-shot (no ``phase``) or two-phase ``/reload``. The phases
        are the fleet router's coordination verbs (SERVING.md "Fleet
        serving") — usable by hand against a single host too:

        - ``phase=prepare`` — validate + canary + warm + REGISTER the
          candidate without activating; returns its ``version`` +
          ``lineage``. The incumbent keeps serving.
        - ``phase=activate`` + ``version`` — pin a prepared version.
        - ``phase=abort`` + ``version`` — retire a prepared version; the
          incumbent was never disturbed.
        """
        phase = payload.get("phase")
        if phase in ("activate", "abort"):
            version = payload.get("version")
            if not isinstance(version, int):
                raise ValueError(
                    f"phase={phase} needs the prepared 'version' (int)")
            if phase == "activate":
                previous = self.registry.active_version
                sm = self.registry.activate(version)
                return {"version": sm.version, "previous": previous,
                        "lineage": sm.lineage, "phase": "activated"}
            self.registry.retire(version)
            return {"version": self.registry.active_version,
                    "retired": version, "phase": "aborted"}
        if phase not in (None, "prepare"):
            raise ValueError(f"unknown reload phase {phase!r} (want "
                             f"prepare | activate | abort)")
        if phase == "prepare" and payload.get("shard_map") is not None:
            # LIVE RESHARD prepare: same two-phase verbs, but the
            # candidate is a bucket→shard map (repacked views of the
            # ACTIVE model), not a model dir. activate/abort above work
            # unchanged on the returned version.
            previous = self.registry.active_version
            sm, moved = self.registry.prepare_reshard(payload["shard_map"])
            return {"version": sm.version, "previous": previous,
                    "lineage": sm.lineage,
                    "shard_map": sm.shard_map.map_hash,
                    "moved": moved, "phase": "prepared"}
        model_dir = payload.get("model_dir") or self.default_model_dir
        if not model_dir:
            raise ValueError("payload needs 'model_dir' (no default "
                             "configured)")
        previous = self.registry.active_version
        if phase == "prepare":
            sm = self.registry.prepare(model_dir)
            out = {"version": sm.version, "previous": previous,
                   "lineage": sm.lineage, "model_dir": sm.model_dir,
                   "phase": "prepared"}
        else:
            sm = self.registry.reload(model_dir)
            out = {"version": sm.version, "previous": previous,
                   "model_dir": sm.model_dir}
        if sm.canary is not None:
            # canary annotation of this activation (divergence vs the
            # incumbent over the request reservoir, quality/canary.py)
            out["canary"] = sm.canary
        return out

    def close(self) -> None:
        if self.overload is not None:
            # stops the controller AND restores brownout level 0
            self.overload.stop()
        if self.batcher is not None:
            self.batcher.close()
        if self.rank_batcher is not None:
            self.rank_batcher.close()
        if self.reqlog is not None:
            self.reqlog.close()


def _make_handler(service: ServingService):
    class Handler(BaseHTTPRequestHandler):
        # HTTP/1.1 = persistent connections: the stdlib default (1.0)
        # closes the socket after every response, which taxes every
        # fleet-router leg (and any keep-alive client) with a fresh TCP
        # handshake. Every reply carries Content-Length, which is all
        # 1.1 keep-alive needs; ThreadingHTTPServer's daemon threads
        # make idle-connection handler threads shutdown-safe.
        protocol_version = "HTTP/1.1"

        # per-request log lines go nowhere useful under test/bench load
        def log_message(self, fmt, *args):  # noqa: D102
            pass

        # --- connection accounting (tel-conn-home: THE one home) ---------
        def setup(self):
            """One inbound socket: account it before the first request
            is read. Over the --max-connections budget ``connect()``
            refuses — the request loop still runs so the client gets ONE
            typed 503 (never a silent close, never a hang) before
            :meth:`finish` drops the socket."""
            super().setup()
            self._conn_t0 = time.monotonic()
            self._conn_requests = 0
            self._conn_admitted = service.connections.connect()

        def finish(self):
            try:
                super().finish()
            finally:
                service.connections.disconnect(
                    time.monotonic() - self._conn_t0,
                    self._conn_requests,
                    admitted=getattr(self, "_conn_admitted", True))

        def _request_id(self) -> str:
            """Honor the inbound header; mint otherwise. Echoed on every
            response by :meth:`_reply_raw`."""
            inbound = self.headers.get(REQUEST_ID_HEADER)
            self.request_id = inbound.strip() if inbound else new_request_id()
            return self.request_id

        def _reply(self, status: int, body: dict,
                   headers: Optional[dict] = None) -> None:
            self._reply_raw(status, json.dumps(body).encode(),
                            "application/json", headers=headers)

        def _reply_raw(self, status: int, data: bytes,
                       content_type: str,
                       headers: Optional[dict] = None) -> None:
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(data)))
            rid = getattr(self, "request_id", None)
            if rid is not None:
                self.send_header(REQUEST_ID_HEADER, rid)
            deadline = getattr(self, "deadline", None)
            if deadline is not None:
                # remaining budget at respond time, echoed like the id
                self.send_header(
                    DEADLINE_HEADER,
                    f"{service.remaining_ms(deadline):.1f}")
            for key, value in (headers or {}).items():
                self.send_header(key, value)
            self.end_headers()
            self.wfile.write(data)

        def _reply_with_summary(self, rid: str, status: int, out: dict,
                                headers: Optional[dict],
                                leg_stages: dict,
                                parse_s: float) -> None:
            """Reply, attaching the leg-summary header to 200 scored/
            ranked responses. ``respond`` in the summary is the JSON
            serialization share — the socket write lands after the
            header by construction, so it can never be inside it (the
            registry histogram still times the full respond stage)."""
            if status == 200 and leg_stages:
                leg_stages["parse"] = parse_s
                t_ser = time.monotonic()
                data = json.dumps(out).encode()
                leg_stages["respond"] = time.monotonic() - t_ser
                headers = dict(headers or {})
                headers[LEG_SUMMARY_HEADER] = format_leg_summary(leg_stages)
                with _maybe_span("serving.respond", request_id=rid), \
                        _STAGE_SECONDS.labels(stage="respond").time():
                    self._reply_raw(status, data, "application/json",
                                    headers=headers)
                return
            with _maybe_span("serving.respond", request_id=rid), \
                    _STAGE_SECONDS.labels(stage="respond").time():
                self._reply(status, out, headers=headers)

        def _payload(self) -> dict:
            length = int(self.headers.get("Content-Length") or 0)
            if not length:
                return {}
            return json.loads(self.rfile.read(length) or b"{}")

        def _refuse_if_stopping(self) -> bool:
            """A stopping host answers every request with a typed 503
            ``reason=stopping`` and CLOSES the connection. Without this
            a keep-alive handler thread that outlives
            ``GameServer.stop()`` keeps answering a pooled fleet-router
            connection from a closed batcher forever — the restarted
            host on the same port never gets the socket back."""
            if not getattr(self.server, "photon_stopping", False):
                return False
            self.close_connection = True
            self._reply(503, {"error": "host is stopping",
                              "reason": "stopping"},
                        headers={"Connection": "close"})
            return True

        def _refuse_if_exhausted(self) -> bool:
            """A connection refused by the --max-connections budget is
            answered with one typed 503 ``reason=connections`` +
            ``Connection: close`` — the same refusal shape as a
            stopping host, feeding the same shed counter family the
            brownout ladder watches. Never a hang: the client learns
            the budget is spent and goes elsewhere."""
            if getattr(self, "_conn_admitted", True):
                return False
            self.close_connection = True
            e = _overload.shed(
                "connections",
                message=f"connection budget exhausted "
                        f"(--max-connections "
                        f"{service.connections.max_connections})",
                retry_after_s=1.0)
            self._reply(shed_status(e),
                        {"error": str(e), "reason": e.reason},
                        headers={"Connection": "close",
                                 "Retry-After":
                                     str(max(1, round(e.retry_after_s)))})
            return True

        def do_GET(self):  # noqa: N802
            if self._refuse_if_stopping() or self._refuse_if_exhausted():
                return
            self._conn_requests += 1
            service.connections.request_begin()
            try:
                self._get_traced()
            finally:
                service.connections.request_end()

        def _get_traced(self) -> None:
            rid = self._request_id()
            parsed = urlsplit(self.path)
            if parsed.path == "/rank":
                # the recommender surface: ?user=<raw id>&k=<int> —
                # deadline, admission control and the request id work
                # exactly as on /score
                qs = parse_qs(parsed.query)
                payload = {key: values[0] for key, values in qs.items()
                           if values}
                self.deadline = None  # GET: stamped inside _handle_rank
                with _maybe_span("serving.request", request_id=rid,
                                 path="/rank"):
                    self._handle_rank(rid, payload)
                return
            if self.path == "/healthz":
                self._reply(200, service.healthz())
            elif self.path == "/readyz":
                status, body = service.readyz()
                self._reply(status, body)
            elif self.path == "/metrics":
                from photon_ml_tpu.telemetry.prometheus import (
                    CONTENT_TYPE,
                    render,
                )

                self._reply_raw(200, render().encode(), CONTENT_TYPE)
            elif parsed.path == "/history":
                self._handle_history(parsed.query)
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

        def _handle_history(self, query: str) -> None:
            """``GET /history?series=&window=[&raw=1]`` — the host's
            retained-telemetry ring (closed series vocabulary; unknown
            names and bad windows are a 400, an unarmed sampler a 404).
            ``raw=1`` includes each snapshot's watched-subset exposition
            text — what the fleet router's fold scrapes."""
            sampler = getattr(service, "history", None)
            if sampler is None:
                self._reply(404, {"error": "history sampler not armed"})
                return
            qs = parse_qs(query)
            try:
                window = int((qs.get("window") or ["0"])[0])
                series = tuple(
                    s for s in (qs.get("series") or [""])[0].split(",")
                    if s)
                raw = (qs.get("raw") or ["0"])[0] not in ("", "0")
                data = sampler.payload_json(window=window, series=series,
                                            include_prom=raw)
            except ValueError as e:
                self._reply(400, {"error": str(e)})
                return
            self._reply_raw(200, data, "application/json")

        def _handle_rank(self, rid: str, payload: dict,
                         parse_ms: float = 0.0,
                         resolve_deadline: bool = True) -> None:
            """Shared /rank tail for the GET (query params) and POST
            (JSON body) routes: resolve the deadline when the GET path
            has not already (POST stamps it in its parse stage), call
            the service, map Shed → 429 like /score."""
            headers = None
            leg_stages: dict = {}
            try:
                if resolve_deadline:
                    with _maybe_span("serving.parse", request_id=rid), \
                            _STAGE_SECONDS.labels(stage="parse").time() \
                            as parse_t:
                        fault_point("serving.parse", path="/rank")
                        self.deadline = service.resolve_deadline(
                            self.headers.get(DEADLINE_HEADER))
                    parse_ms = parse_t.seconds * 1e3
                service.check_shard_map(self.headers.get(SHARD_MAP_HEADER))
                out = service.rank(payload, request_id=rid,
                                   stage_ms={"parse": parse_ms},
                                   deadline=self.deadline,
                                   stage_sink=leg_stages)
                status = 200
            except ShardMapMismatch as e:
                out = {"error": str(e), "reason": "shard_map_mismatch",
                       "request_id": rid}
                status = 503
            except BatcherClosed as e:
                self.close_connection = True
                out = {"error": str(e), "reason": "stopping",
                       "request_id": rid}
                status = 503
            except _overload.Shed as e:
                out = {"error": str(e), "reason": e.reason,
                       "request_id": rid}
                status = shed_status(e)
                headers = {"Retry-After": str(max(1, round(e.retry_after_s)))}
            except ValueError as e:
                out, status = {"error": str(e)}, 400
            except Exception as e:
                out, status = {"error": repr(e)}, 500
            self._reply_with_summary(rid, status, out, headers,
                                     leg_stages, parse_ms / 1e3)

        def do_POST(self):  # noqa: N802
            if self._refuse_if_stopping() or self._refuse_if_exhausted():
                return
            self._conn_requests += 1
            service.connections.request_begin()
            try:
                rid = self._request_id()
                with _maybe_span("serving.request", request_id=rid,
                                 path=self.path):
                    self._post_traced(rid)
            finally:
                service.connections.request_end()

        def _post_traced(self, rid: str) -> None:
            payload = None
            with _maybe_span("serving.parse", request_id=rid), \
                    _STAGE_SECONDS.labels(stage="parse").time() as parse_t:
                try:
                    fault_point("serving.parse", path=self.path)
                    payload = self._payload()
                    # the deadline budget is stamped HERE, at parse: the
                    # queue wait and the scoring spend the same budget
                    # the caller started measuring at send
                    self.deadline = service.resolve_deadline(
                        self.headers.get(DEADLINE_HEADER))
                    parse_error = None
                except (ValueError, json.JSONDecodeError) as e:
                    parse_error = (400, f"bad request: {e}")
                except Exception as e:
                    # an injected serving.parse fault (or a genuine parse-
                    # path bug) is a server error, not the client's JSON
                    parse_error = (500, repr(e))
            if parse_error is not None:
                status, message = parse_error
                self._reply(status, {"error": message})
                return
            if self.path == "/score":
                headers = None
                leg_stages: dict = {}
                try:
                    service.check_shard_map(
                        self.headers.get(SHARD_MAP_HEADER))
                    out = service.score(
                        payload, request_id=rid,
                        stage_ms={"parse": parse_t.seconds * 1e3},
                        deadline=self.deadline,
                        stage_sink=leg_stages)
                    status = 200
                except ShardMapMismatch as e:
                    # refused like mixed lineage: the fan-out was routed
                    # under a different map generation than this host's
                    out = {"error": str(e), "reason": "shard_map_mismatch",
                           "request_id": rid}
                    status = 503
                except BatcherClosed as e:
                    # stop() raced this request past the front-door
                    # refusal: same typed drain answer, same close
                    self.close_connection = True
                    out = {"error": str(e), "reason": "stopping",
                           "request_id": rid}
                    status = 503
                except _overload.Shed as e:
                    # admission control refused the request: 429 with a
                    # Retry-After hint — never a hang, never a 500
                    # (upstream sheds — router-only — map to 503)
                    out = {"error": str(e), "reason": e.reason,
                           "request_id": rid}
                    status = shed_status(e)
                    headers = {
                        "Retry-After": str(max(1, round(e.retry_after_s)))}
                except ValueError as e:
                    out, status = {"error": str(e)}, 400
                except Exception as e:
                    out, status = {"error": repr(e)}, 500
                self._reply_with_summary(rid, status, out, headers,
                                         leg_stages, parse_t.seconds)
            elif self.path == "/rank":
                # POST variant for full records: {"record": ..., "k": N}
                self._handle_rank(rid, payload,
                                  parse_ms=parse_t.seconds * 1e3,
                                  resolve_deadline=False)
            elif self.path == "/reload":
                try:
                    self._reply(200, service.reload(payload))
                except Exception as e:
                    # swap REJECTED: the active version is untouched, so
                    # this is a conflict, not a server death
                    self._reply(409, {
                        "error": repr(e),
                        "version": service.registry.active_version})
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

    return Handler


class GameServer:
    """Threaded HTTP server wrapper with a test-friendly lifecycle."""

    def __init__(self, service: ServingService, *, host: str = "127.0.0.1",
                 port: int = 0):
        self.service = service
        self._httpd = ThreadingHTTPServer((host, port),
                                          _make_handler(service))
        #: start/stop are operator-lifecycle calls from one control thread
        self._thread: Optional[threading.Thread] = None  # guarded-by: caller

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "GameServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name="photon-serving-http")
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self._httpd.serve_forever()

    def stop(self) -> None:
        # flip the refuse flag BEFORE teardown: keep-alive handler
        # threads survive shutdown() (only the accept loop stops), so
        # they must answer 503 reason=stopping + Connection: close from
        # here on, not serve stale results from a closing batcher
        self._httpd.photon_stopping = True
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join()
        # retained-telemetry plane, when the driver armed one (attrs set
        # by cli/serve_game.build_server; all closes are idempotent, so
        # the driver's own finally-close is harmless)
        for attr in ("watchdog", "history", "flight"):
            obj = getattr(self, attr, None)
            if obj is not None:
                obj.close()
        self.service.close()
