"""Jitted online scoring engine: shape-bucketed, zero steady-state recompiles.

Requests arrive at arbitrary batch sizes; XLA compiles one executable per
input SHAPE. Left alone, that means a recompile (10s+ through a remote-
compile tunnel) the first time any new size shows up — a latency cliff in
the middle of serving traffic. The engine therefore pads every batch up to
a power-of-two bucket (1, 2, 4, … ``max_batch``): the executable set is
fixed and small (log₂ max_batch + 1 shapes), :meth:`ScoringEngine.warmup`
pre-traces all of them, and steady-state serving performs **zero**
recompiles no matter how request sizes vary. ``compile_count`` exposes the
trace counter the serving bench asserts on.

Numeric contract: per-coordinate margins are accumulated in float64 (when
``jax_enable_x64`` is on — the serve CLI enables it on CPU backends) and the
total runs :func:`photon_ml_tpu.game.model.sum_coordinate_margins` — the
same reduction, same coordinate order, as the batch scorer. Online scores
are bit-identical to ``score_game`` output (tests/test_serving.py locks
this). Without x64 (TPU serving) accumulation degrades to f32 and parity is
approximate. Quantized coefficient tables (``--table-dtype bfloat16/int8``)
trade that exactness for footprint: rows dequantize in-trace
(:func:`photon_ml_tpu.serving.store.gather_rows`) and scores hold the
documented relative tolerances instead (bf16 ≤ 1e-2, int8 ≤ 5e-2 — the
score-parity gates in tests/test_serving.py).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Mapping, Optional, Sequence

import numpy as np

from photon_ml_tpu.game.model import (
    FixedEffectModel,
    GameModel,
    sum_coordinate_margins,
)
from photon_ml_tpu.io.data_reader import FeatureShardConfig, _record_features
from photon_ml_tpu.io.index import IndexMap
from photon_ml_tpu.resilience.faults import fault_point
from photon_ml_tpu.types import INTERCEPT_KEY
from photon_ml_tpu.serving import overload as _overload
from photon_ml_tpu.serving import stages as _stages
from photon_ml_tpu.serving import store as _store
from photon_ml_tpu.serving.store import EntityCoefficientStore
from photon_ml_tpu.telemetry import metrics as _metrics
from photon_ml_tpu.telemetry import profiling as _profiling

#: engine-side scoring latency per padded bucket shape (dispatch + D2H)
_SCORE_LATENCY = _metrics.histogram(
    "photon_serving_score_latency_seconds",
    "Engine scoring time per padded batch bucket", labels=("bucket",))

#: per-stage request-path critical path (same family the HTTP front end
#: and the microbatcher feed) — the engine owns the batch_assemble stage
#: (record → host arrays packing) and the execute stage (pad + jit
#: dispatch + D2H across every chunk of a batch)
_STAGE_SECONDS = _metrics.histogram(
    "photon_serving_stage_seconds",
    "Serving request time per request-path stage "
    "(parse | queue_wait | batch_assemble | execute | respond)",
    labels=("stage",))

#: the fn label serving's traces count under — the SAME
#: ``photon_compiles_total{fn}`` family the training paths use
#: (telemetry/profiling.py), so one scrape expression covers every
#: recompile contract in the system. The engine keeps its own jit (the
#: power-of-two bucket machinery IS the zero-recompile design) and counts
#: traces from inside the traced body via ``profiling.record_compile``.
SCORING_FN_LABEL = "serving.score"


def next_bucket(n: int) -> int:
    """Smallest power of two ≥ max(n, 1)."""
    return 1 << max(int(n) - 1, 0).bit_length()


class _ScoreProgram:
    """One jitted scoring program + its trace counter, shareable across
    engine instances. A patch-derived model has the same coordinate
    structure as its parent — only the table CONTENTS differ, and those
    ride as jit arguments — so the derived engine reuses the parent's
    executables outright (``ScoringEngine(share_from=parent)``): a patch
    activation that appends no new table rows compiles NOTHING, on any
    host. The counter lives here (not on the engine) so ``compile_count``
    tells the truth for shared programs too."""

    __slots__ = ("jit", "compiles")

    def __init__(self):
        self.jit = None
        #: bumped from inside the traced body (trace time only — jit
        #: serializes traces), deliberately not lock-annotated
        self.compiles = 0


@dataclasses.dataclass(frozen=True)
class RequestBatch:
    """Host arrays for one batch of scoring requests: per-shard dense
    designs, per-random-effect-coordinate store rows, offsets."""

    n: int
    offsets: np.ndarray  # (n,) float32
    xs: tuple  # per shard config: (n, dim) float32
    rows: tuple  # per RE coordinate: (n,) int32 store rows


class ScoringEngine:
    """Scores request records against one loaded GAME model version.

    One engine per :class:`~photon_ml_tpu.serving.registry.ServingModel`
    version — hot-swapping installs a fresh engine, so an engine's jit
    cache always matches its coefficients. Thread-safe: concurrent
    :meth:`score` calls share the compiled executables.
    """

    def __init__(self, model: GameModel,
                 shard_configs: Sequence[FeatureShardConfig],
                 index_maps: Mapping[str, IndexMap],
                 stores: Mapping[str, EntityCoefficientStore],
                 *, max_batch: int = 1024,
                 share_from: "Optional[ScoringEngine]" = None):
        import jax
        import jax.numpy as jnp

        self.model = model
        self.shard_configs = tuple(shard_configs)
        self.index_maps = dict(index_maps)
        self.stores = dict(stores)
        self.max_batch = next_bucket(max_batch)
        self._shard_order = [c.shard_id for c in self.shard_configs]
        # coordinate walk order is the model's — the summation contract is
        # order-sensitive and the batch path iterates the same dict
        self._coords = list(model.coordinates.items())
        self._re_order = [cid for cid, cm in self._coords
                          if not isinstance(cm, FixedEffectModel)]
        for cid in self._re_order:
            if cid not in self.stores:
                raise ValueError(f"no EntityCoefficientStore for "
                                 f"random-effect coordinate {cid!r}")
        # model parameters ride as jit ARGUMENTS, not closure constants:
        # constants get baked into every bucket's executable (compile-time
        # and image bloat proportional to table size × bucket count).
        # Random-effect tables ride as (table, scales) pairs — possibly
        # quantized storage, dequantized in-trace by store.gather_rows
        self._params = {
            "fe": {cid: jnp.asarray(
                np.asarray(cm.model.coefficients.means, np.float32))
                for cid, cm in self._coords
                if isinstance(cm, FixedEffectModel)},
            "re": {cid: self.stores[cid].device_params
                   for cid in self._re_order},
        }
        self._lock = threading.Lock()
        self._n_calls = 0  # guarded-by: _lock
        self._n_scored = 0  # guarded-by: _lock
        #: optional photon_ml_tpu.quality.QualityMonitor, attached by the
        #: registry at load time. Accumulation is host-side numpy over
        #: arrays score_batch already holds — the jitted program, the f32
        #: bit-parity and the zero-recompile contract are untouched.
        self.monitor = None
        self._accum = jnp.float64 if jax.config.jax_enable_x64 \
            else jnp.float32
        #: the structural signature executable sharing keys on: same
        #: shard order, same coordinate walk (id, kind, feature shard),
        #: same accumulation dtype ⇒ byte-identical traced program
        self._signature = (
            tuple(self._shard_order),
            tuple((cid, isinstance(cm, FixedEffectModel),
                   cm.feature_shard_id) for cid, cm in self._coords),
            str(self._accum.__name__),
        )
        if share_from is not None \
                and share_from._signature == self._signature:
            self._program = share_from._program
        else:
            self._program = self._build_program()

    def _build_program(self) -> _ScoreProgram:
        """Build this engine's jitted program. The closure captures ONLY
        structural constants (coordinate walk, shard order) and the
        program's own trace counter — never a specific version's tables —
        so patch-derived engines can share it verbatim."""
        import jax
        import jax.numpy as jnp

        program = _ScoreProgram()
        accum = self._accum
        shard_order = tuple(self._shard_order)
        re_order = tuple(self._re_order)
        coords = tuple((cid, isinstance(cm, FixedEffectModel),
                        cm.feature_shard_id) for cid, cm in self._coords)

        def _score_padded(params, offsets, xs, rows):
            # body runs at TRACE time only — one increment per compiled
            # bucket shape, the recompile counter the serving bench asserts
            program.compiles += 1
            _profiling.record_compile(SCORING_FN_LABEL)
            margins = []
            i_x = {sid: i for i, sid in enumerate(shard_order)}
            i_r = {cid: i for i, cid in enumerate(re_order)}
            for cid, is_fixed, feature_shard_id in coords:
                x = xs[i_x[feature_shard_id]].astype(accum)
                if is_fixed:
                    m = x @ params["fe"][cid].astype(accum)
                else:
                    # quantized tables dequantize HERE, fused into the
                    # scoring trace (store.gather_rows is the sanctioned
                    # home of the table numeric format — hygiene rule 5)
                    tab = _store.gather_rows(params["re"][cid],
                                             rows[i_r[cid]], accum)
                    m = jnp.sum(x * tab, axis=1)
                margins.append(m.astype(jnp.float32))
            # the per-coordinate f32 margins are program outputs too: the
            # fleet router merges THESE (fleet/router.py) through the same
            # sum_coordinate_margins reduction — the single-host path
            # simply never fetches them (async dispatch, total-only D2H)
            total = sum_coordinate_margins(offsets, margins, xp=jnp)
            return total, tuple(margins)

        program.jit = jax.jit(_score_padded)
        return program

    # --- stats ------------------------------------------------------------
    @property
    def compile_count(self) -> int:
        """Distinct jitted traces of this engine's PROGRAM so far (== XLA
        compiles). Constant after :meth:`warmup` — the zero-recompile
        contract. A patch-derived engine shares its parent's program, so
        the count carries across activation: a delta of 0 over a swap IS
        the zero-recompile-activation proof. The process-wide scrape
        equivalent is ``photon_compiles_total{fn="serving.score"}``."""
        return self._program.compiles

    @property
    def n_scored(self) -> int:
        return self._n_scored

    # --- request packing --------------------------------------------------
    def pack(self, records: Sequence[dict]) -> RequestBatch:
        """Records (TrainingExampleAvro-shaped dicts: ``features`` list,
        ``metadataMap``, optional ``offset``) → host arrays.

        Feature handling mirrors the batch reader exactly — bag filtering,
        index-map lookup (unknown keys dropped), intercept column, duplicate
        (row, col) entries accumulating in f32 — so packing introduces no
        online/batch skew.
        """
        n = len(records)
        offsets = np.zeros(n, np.float32)
        for i, rec in enumerate(records):
            off = rec.get("offset")
            if off is not None:
                offsets[i] = off
        xs = []
        for cfg in self.shard_configs:
            imap = self.index_maps[cfg.shard_id]
            x = np.zeros((n, len(imap)), np.float32)
            get = imap.key_to_index.get
            for i, rec in enumerate(records):
                for key, value in _record_features(rec, cfg.feature_bags):
                    j = get(key)
                    if j is not None:
                        x[i, j] += np.float32(value)
                if cfg.has_intercept:
                    x[i, imap.key_to_index[INTERCEPT_KEY]] += np.float32(1.0)
            xs.append(x)
        rows = []
        for cid in self._re_order:
            store = self.stores[cid]
            raw = [
                (rec.get("metadataMap") or {}).get(store.random_effect_type)
                for rec in records]
            rows.append(store.rows_for(raw))
        return RequestBatch(n=n, offsets=offsets, xs=tuple(xs),
                            rows=tuple(rows))

    # --- scoring ----------------------------------------------------------
    def score(self, records: Sequence[dict]) -> np.ndarray:
        """Total GAME score per record (float32, batch-path parity)."""
        # the serving-side chaos site: one visit per scoring call, BEFORE
        # any stage work — an injected fault fails this batch (its Futures
        # get the error, the batcher worker survives) and a request shed by
        # admission control never even reaches this point
        fault_point("serving.execute", n=len(records))
        with _STAGE_SECONDS.labels(stage="batch_assemble").time() as t:
            batch = self.pack(records)
        _stages.record("batch_assemble", t.seconds)
        return self.score_batch(batch)

    def score_margins(self, records: Sequence[dict]):
        """Scores PLUS the per-coordinate f32 margins and offsets — the
        fleet router's merge inputs (f32 values widened to double in JSON
        are exact, so the router re-running ``sum_coordinate_margins``
        over them reproduces this host's totals bit-for-bit). Returns
        ``(scores (n,) f32, offsets (n,) f32, [(cid, (n,) f32), ...])``
        in the model's coordinate order."""
        fault_point("serving.execute", n=len(records))
        with _STAGE_SECONDS.labels(stage="batch_assemble").time() as t:
            batch = self.pack(records)
        _stages.record("batch_assemble", t.seconds)
        scores, margins = self.score_batch(batch, with_margins=True)
        return scores, batch.offsets, \
            [(cid, m) for (cid, _cm), m in zip(self._coords, margins)]

    def score_batch(self, batch: RequestBatch, with_margins: bool = False):
        out = np.empty(batch.n, np.float32)
        margins = [np.empty(batch.n, np.float32)
                   for _ in self._coords] if with_margins else None
        # batches past the largest bucket chunk — per-sample independence
        # makes the split score-invariant
        with _STAGE_SECONDS.labels(stage="execute").time() as exec_t:
            for lo in range(0, batch.n, self.max_batch):
                hi = min(lo + self.max_batch, batch.n)
                chunk, chunk_margins = self._score_chunk(
                    batch, lo, hi, with_margins=with_margins)
                out[lo:hi] = chunk
                if with_margins:
                    for j, m in enumerate(chunk_margins):
                        margins[j][lo:hi] = m
        _stages.record("execute", exec_t.seconds)
        with self._lock:
            self._n_calls += 1
            self._n_scored += batch.n
        monitor = self.monitor
        if monitor is not None and _overload.is_shed("quality"):
            # brownout level 2+: quality accumulation is optional work —
            # shed it before shedding traffic (SERVING.md overload ladder)
            monitor = None
        if monitor is not None:
            # live quality accumulation (quality/monitor.py): fallback-row
            # hits per coordinate + nonzero design cells per shard are
            # host facts this batch already materialized; the score
            # binning itself happens inside the monitor (hygiene rule 6)
            cold = {
                cid: int(np.count_nonzero(
                    np.asarray(r) == self.stores[cid].fallback_row))
                for cid, r in zip(self._re_order, batch.rows)}
            coverage = {
                cfg.shard_id: (int(np.count_nonzero(x)), int(x.size))
                for cfg, x in zip(self.shard_configs, batch.xs)}
            monitor.observe(out, cold=cold, coverage=coverage)
        return (out, margins) if with_margins else out

    def _score_chunk(self, batch: RequestBatch, lo: int, hi: int,
                     with_margins: bool = False):
        n = hi - lo
        b = next_bucket(n)
        offsets = np.zeros(b, np.float32)
        offsets[:n] = batch.offsets[lo:hi]
        xs = []
        for x in batch.xs:
            xp = np.zeros((b, x.shape[1]), np.float32)
            xp[:n] = x[lo:hi]
            xs.append(xp)
        rows = []
        for cid, r in zip(self._re_order, batch.rows):
            rp = np.full(b, self.stores[cid].fallback_row, np.int32)
            rp[:n] = r[lo:hi]
            rows.append(rp)
        # the np.asarray D2H pull belongs inside the timed region: jax
        # dispatch is async, so the jit call alone returns before the
        # device finishes. Margins are fetched only when asked (the fleet
        # margin-merge path); the single-host path pulls the total alone.
        with _SCORE_LATENCY.labels(bucket=str(b)).time():
            scores, margins = self._program.jit(
                self._params, offsets, tuple(xs), tuple(rows))
            out = np.asarray(scores)[:n]
            out_margins = ([np.asarray(m)[:n] for m in margins]
                           if with_margins else None)
        return out, out_margins

    def warmup(self, max_bucket: Optional[int] = None) -> int:
        """Pre-trace every bucket executable (1, 2, 4, … ``max_batch``) so
        live traffic never waits on a compile. Returns the number of
        compiles performed."""
        top = self.max_batch if max_bucket is None else next_bucket(max_bucket)
        before = self._program.compiles
        b = 1
        while b <= top:
            empty = RequestBatch(
                n=b, offsets=np.zeros(b, np.float32),
                xs=tuple(np.zeros((b, len(self.index_maps[c.shard_id])),
                                  np.float32) for c in self.shard_configs),
                rows=tuple(np.full(b, self.stores[cid].fallback_row,
                                   np.int32) for cid in self._re_order))
            self._score_chunk(empty, 0, b)
            b <<= 1
        return self._program.compiles - before
