"""Per-feature summary statistics (reference
``photon-api/.../stat/FeatureDataStatistics.scala`` a.k.a.
``BasicStatisticalSummary`` via Spark ``colStats``): mean, variance, min,
max, max magnitude, nnz per feature column — computed in one vectorized pass
over a CSR shard (zeros counted implicitly), feeding normalization contexts
and the summarization output file.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from photon_ml_tpu.game.data import FeatureShard


@dataclasses.dataclass(frozen=True)
class FeatureDataStatistics:
    mean: np.ndarray
    variance: np.ndarray
    min: np.ndarray
    max: np.ndarray
    max_magnitude: np.ndarray
    num_nonzeros: np.ndarray
    count: int

    @staticmethod
    def from_shard(shard: FeatureShard) -> "FeatureDataStatistics":
        d = shard.dim
        n = shard.n_samples
        cols = shard.cols.astype(np.int64)
        vals = shard.vals.astype(np.float64)
        nnz = np.bincount(cols, minlength=d).astype(np.int64)
        s1 = np.bincount(cols, weights=vals, minlength=d)
        s2 = np.bincount(cols, weights=vals * vals, minlength=d)
        mean = s1 / max(n, 1)
        # population variance incl. implicit zeros (matches colStats'
        # treatment of sparse columns up to the n/(n-1) factor; reference
        # uses the unbiased estimator)
        denom = max(n - 1, 1)
        variance = np.maximum((s2 - n * mean * mean) / denom, 0.0)

        vmin = np.zeros(d)
        vmax = np.zeros(d)
        np.minimum.at(vmin, cols, vals)
        np.maximum.at(vmax, cols, vals)
        # columns with no explicit zeros but full support: min/max from data only
        full = nnz >= n
        if full.any():
            explicit_min = np.full(d, np.inf)
            explicit_max = np.full(d, -np.inf)
            np.minimum.at(explicit_min, cols, vals)
            np.maximum.at(explicit_max, cols, vals)
            vmin[full] = explicit_min[full]
            vmax[full] = explicit_max[full]
        max_magnitude = np.maximum(np.abs(vmin), np.abs(vmax))
        return FeatureDataStatistics(
            mean=mean, variance=variance, min=vmin, max=vmax,
            max_magnitude=max_magnitude, num_nonzeros=nnz, count=n)

    def allreduce(self) -> "FeatureDataStatistics":
        """Combine per-process statistics into the global ones (identity on
        a single process) — multi-process drivers compute normalization
        contexts from these so every process transforms the objective
        identically. Means/variances recombine through the moment sums
        (s1, s2); min/max/nnz reduce directly. (At a per-process count of
        exactly 1 the unbiased-variance denominator makes the recovered s2
        approximate; a 1-row process shard is degenerate anyway.)"""
        import jax

        if jax.process_count() == 1:
            return self
        from photon_ml_tpu.parallel.multihost import (
            allreduce_max,
            allreduce_sum,
        )

        n = self.count
        s1 = self.mean * n
        s2 = self.variance * max(n - 1, 1) + n * np.square(self.mean)
        n_g = int(allreduce_sum(np.array([n], np.int64))[0])
        s1_g = allreduce_sum(s1)
        s2_g = allreduce_sum(s2)
        mean = s1_g / max(n_g, 1)
        variance = np.maximum(
            (s2_g - n_g * np.square(mean)) / max(n_g - 1, 1), 0.0)
        vmin = -allreduce_max(-self.min)
        vmax = allreduce_max(self.max)
        return FeatureDataStatistics(
            mean=mean, variance=variance, min=vmin, max=vmax,
            max_magnitude=np.maximum(np.abs(vmin), np.abs(vmax)),
            num_nonzeros=allreduce_sum(self.num_nonzeros), count=n_g)

    def to_records(self, names: list[str]):
        """FeatureSummarizationResultAvro-shaped records."""
        from photon_ml_tpu.io.model_io import _split_key

        for i, key in enumerate(names):
            name, term = _split_key(key)
            yield {
                "featureName": name,
                "featureTerm": term,
                "metrics": {
                    "mean": float(self.mean[i]),
                    "variance": float(self.variance[i]),
                    "min": float(self.min[i]),
                    "max": float(self.max[i]),
                    "maxMagnitude": float(self.max_magnitude[i]),
                    "numNonzeros": float(self.num_nonzeros[i]),
                    "count": float(self.count),
                },
            }
