"""Public test utilities (the reference's ``photon-test-utils`` module:
``SparkTestUtils.scala`` + ``CommonTestUtils.scala``, reshaped for JAX).

What the reference's ``sparkTest`` fixture provides — a local[*]
SparkContext exercising the real distributed code paths in one JVM — maps
here to a host-simulated device mesh: :func:`virtual_devices` forces a CPU
backend with N virtual devices so ``shard_map``/``psum`` paths run without
hardware. Data generators mirror ``CommonTestUtils``' random problem
builders so downstream users can write parity tests the same way this
repo's own suite does.

NOTE: like the reference's singleton-locked SparkContext, the virtual
device count must be set before JAX initializes a backend — call
:func:`virtual_devices` at import time (conftest), not inside a test.
"""

from __future__ import annotations

import os
import numpy as np


def virtual_devices(n: int = 8, *, force_cpu: bool = True) -> None:
    """Configure an ``n``-device virtual CPU mesh (call before jax init).

    The moral equivalent of ``SparkTestUtils.sparkTest``'s local[*] cluster:
    the same pjit/shard_map code that drives a TPU slice runs on ``n``
    simulated host devices.
    """
    import re

    flags = os.environ.get("XLA_FLAGS", "")
    match = re.search(r"xla_force_host_platform_device_count=(\d+)", flags)
    if match is None:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()
    elif int(match.group(1)) != n:
        # silently keeping the old count would hand the caller a
        # different-sized mesh than they asked for
        raise ValueError(
            f"XLA_FLAGS already forces "
            f"{match.group(1)} host devices; requested {n}. Set the flag "
            f"once, before any backend initialization")
    if force_cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")


def make_classification(n: int = 500, d: int = 10, seed: int = 0,
                        intercept: bool = False,
                        weights: bool = False):
    """Random logistic problem → (GLMData, x, labels) — the counterpart of
    ``CommonTestUtils``' gaussian data generators."""
    import jax.numpy as jnp

    from photon_ml_tpu.ops.design import DenseDesign
    from photon_ml_tpu.ops.objective import GLMData

    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d))
    w = rng.normal(size=d)
    margins = x @ w
    labels = (rng.uniform(size=n) < 1 / (1 + np.exp(-margins))).astype(
        np.float64)
    if intercept:
        x = np.concatenate([x, np.ones((n, 1))], axis=1)
    wts = (rng.uniform(0.5, 2.0, size=n) if weights
           else np.ones(n))
    data = GLMData(design=DenseDesign(x=jnp.asarray(x)),
                   labels=jnp.asarray(labels),
                   offsets=jnp.zeros(n), weights=jnp.asarray(wts))
    return data, x, labels


def dense_shard(x: np.ndarray):
    """Wrap a dense ``(n, d)`` matrix as a :class:`FeatureShard` — the
    boilerplate every GAME test needs."""
    from photon_ml_tpu.game.data import FeatureShard

    nn, dd = x.shape
    return FeatureShard.from_coo(
        np.repeat(np.arange(nn), dd),
        np.tile(np.arange(dd, dtype=np.int32), nn),
        # explicit copy: from_coo's sorted fast path would otherwise keep
        # a VIEW of the caller's matrix inside the frozen shard
        np.array(x, np.float32).ravel(), nn, dd)


def make_mixed_effect(n: int = 2000, d_fixed: int = 8, d_re: int = 4,
                      n_entities: int = 37, seed: int = 0,
                      param_seed: int = 12345,
                      entity_column: str = "entityId"):
    """Mixed-effect logistic GameData (global effect + per-entity slopes,
    power-law entity sizes) — the Yahoo!-Music-sample-shaped generator used
    by GAME integration tests."""
    from photon_ml_tpu.game.data import GameData

    prng = np.random.default_rng(param_seed)
    w_fixed = prng.normal(size=d_fixed).astype(np.float32)
    u = (1.5 * prng.normal(size=(n_entities, d_re))).astype(np.float32)
    rng = np.random.default_rng(seed)
    xf = rng.normal(size=(n, d_fixed)).astype(np.float32)
    xr = rng.normal(size=(n, d_re)).astype(np.float32)
    probs = 1.0 / np.arange(1, n_entities + 1)
    probs /= probs.sum()
    ent = rng.choice(n_entities, size=n, p=probs).astype(np.int64)
    margin = xf @ w_fixed + np.einsum("nd,nd->n", xr, u[ent])
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-margin))).astype(np.float32)

    data = GameData.build(
        labels=y, shards={"fixed": dense_shard(xf), "re": dense_shard(xr)},
        id_columns={entity_column: ent})
    return data, (xf, xr, ent, w_fixed, u)


def assert_allclose_coefficients(actual, desired, *, atol: float = 1e-6,
                                 rtol: float = 1e-5,
                                 err_msg: str = "") -> None:
    """Tolerance compare for coefficient vectors
    (``CommonTestUtils.assertIterableEqualsWithTolerance``)."""
    np.testing.assert_allclose(np.asarray(actual), np.asarray(desired),
                               atol=atol, rtol=rtol, err_msg=err_msg)


def finite_difference_gradient(fun, w: np.ndarray, eps: float = 1e-6,
                               ) -> np.ndarray:
    """Central-difference gradient — the reference unit tests' ground truth
    for objective gradients (``*LossFunctionTest`` pattern)."""
    w = np.asarray(w, np.float64)
    g = np.zeros_like(w)
    for i in range(w.size):
        dw = np.zeros_like(w)
        dw[i] = eps
        g[i] = (float(fun(w + dw)) - float(fun(w - dw))) / (2 * eps)
    return g
