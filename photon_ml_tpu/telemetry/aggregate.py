"""Fleet-wide metric aggregation: fold N registry snapshots into one.

PR 3 gave every process a live registry; a ``--multihost`` run therefore
exposes N independent ``/metrics``-shaped snapshots (the chief's plus one
per ``workers/proc-N/``). This module is the missing fold — the ROADMAP's
"multi-host metric aggregation" item — implemented on the
:mod:`~photon_ml_tpu.telemetry.prometheus` render/parse round-trip so every
transport shares ONE merge code path:

- :func:`merge_parsed` / :func:`aggregate_text` — the pure fold. Counters
  and histogram ``_bucket``/``_sum``/``_count`` series sum element-wise per
  label set; gauges resolve by OWNER semantics: the first snapshot holding
  a label set wins (snapshots are passed chief-first, so replicated gauges
  read as the chief's), while per-host gauges — tagged with a ``process``
  label at render time (``metrics.mark_host_owned``) — carry distinct label
  sets and fan out, one series per host.
- :class:`FleetMetricsAggregator` — the in-training collective transport:
  every process renders its registry and the texts ride
  :func:`~photon_ml_tpu.parallel.multihost.allgather_text` (one symmetric
  host collective); process 0 materializes the aggregate. Training calls
  :func:`sweep_boundary` at coordinate-descent sweep (and GLM lambda)
  boundaries; the fold hook is only installed under ``--metrics-port``, so
  bare runs pay nothing — not even a registry render.
- :class:`MetricsHTTPServer` — the chief's live scrape endpoint
  (``--metrics-port``): ``GET /metrics`` serves the latest fleet aggregate.
  Same stdlib ``ThreadingHTTPServer`` lifecycle as the serving front end
  (``serving/http.py::GameServer``) — telemetry cannot import serving
  (the dependency points the other way), so the thin handler is restated
  here rather than reused.
- :func:`merge_trace_files` — the span-trace sibling: fold per-process
  ``trace.jsonl`` files into one wall-clock-ordered timeline, each record
  tagged with its ``process``. Span ids stay per-process scoped; the
  unique key in a merged trace is ``(process, span_id)``.

The offline transport over the same fold is ``tools/metrics_fold.py``
(merge dumped ``metrics.prom`` files after a run); because both transports
feed identical snapshot texts in identical (process) order through
:func:`aggregate_text`, their outputs are byte-identical.
"""

from __future__ import annotations

import json
import logging
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Iterable, Optional, Sequence

from photon_ml_tpu.telemetry.metrics import MetricsRegistry, default_registry
from photon_ml_tpu.telemetry.prometheus import (
    CONTENT_TYPE,
    ParsedSnapshot,
    histogram_series_names,
    parse_text,
    render,
)

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# the pure fold
# ---------------------------------------------------------------------------


def _label_key(labels) -> tuple:
    return tuple(sorted(labels.items()))


def _merge_series(out: ParsedSnapshot, snapshots: Sequence[ParsedSnapshot],
                  series: str, sum_values: bool) -> None:
    index: dict[tuple, int] = {}
    samples: list = []
    for snap in snapshots:
        for labels, value in snap.get(series, ()):
            key = _label_key(labels)
            pos = index.get(key)
            if pos is None:
                index[key] = len(samples)
                samples.append((labels, value))
            elif sum_values:
                kept, total = samples[pos]
                samples[pos] = (kept, total + value)
            # else: owner semantics — the first (chief-most) snapshot
            # holding this label set keeps its value
    if samples:
        out[series] = samples


def merge_parsed(snapshots: Sequence[ParsedSnapshot]) -> ParsedSnapshot:
    """Fold parsed snapshots (chief first, then workers in process order).

    Family order and headers follow first appearance; a family declared
    with conflicting types across snapshots (a version-skewed fleet
    redefining a name) raises rather than summing apples into oranges.
    Merging a single snapshot is the identity — ``render`` of the result
    is byte-identical to the input text.
    """
    out = ParsedSnapshot()
    for snap in snapshots:
        for name, fam in snap.families.items():
            have = out.families.get(name)
            if have is None:
                out.families[name] = dict(fam)
            elif have["type"] != fam["type"]:
                raise ValueError(
                    f"metric family {name!r} has conflicting types across "
                    f"processes ({have['type']} vs {fam['type']}) — a "
                    f"mixed-version fleet is redefining the metric; check "
                    f"photon_build_info in the per-process snapshots")
            elif not have.get("help") and fam.get("help"):
                have["help"] = fam["help"]
    claimed: set[str] = set()
    for name, fam in out.families.items():
        if fam["type"] == "histogram":
            for series in histogram_series_names(name):
                claimed.add(series)
                _merge_series(out, snapshots, series, sum_values=True)
        else:
            claimed.add(name)
            _merge_series(out, snapshots, name,
                          sum_values=fam["type"] == "counter")
    for snap in snapshots:  # headerless series: first snapshot wins
        for series in snap:
            if series not in claimed and series not in out:
                out[series] = list(snap[series])
    return out


def aggregate_text(texts: Sequence[str]) -> str:
    """N exposition texts (chief first) → one aggregate exposition text."""
    return render(merge_parsed([parse_text(t) for t in texts]))


# ---------------------------------------------------------------------------
# process identity helpers (safe before/without jax.distributed)
# ---------------------------------------------------------------------------


def process_tag() -> Optional[str]:
    """This process's index as a label value when the job spans processes,
    else None (single-process renders stay untagged, so existing golden
    outputs — and single-host scrape dashboards — are unchanged)."""
    if "jax" not in sys.modules:
        return None
    import jax

    try:
        if jax.process_count() > 1:
            return str(jax.process_index())
    except Exception:
        return None
    return None


def is_chief() -> bool:
    if "jax" not in sys.modules:
        return True
    import jax

    try:
        return jax.process_index() == 0
    except Exception:
        return True


# ---------------------------------------------------------------------------
# in-training collective fold + sweep-boundary hooks
# ---------------------------------------------------------------------------


class FleetMetricsAggregator:
    """Collective registry fold with a thread-safe "latest aggregate" slot.

    :meth:`fold` is a COLLECTIVE: every process of the job must call it at
    the same point (the sweep-boundary hook guarantees this — the hook is
    installed by the same ``--metrics-port`` flag on every process).
    Single-process jobs degrade to the identity fold and :meth:`latest`
    renders live instead of serving the last fold's snapshot.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self.registry = registry if registry is not None \
            else default_registry()
        self._lock = threading.Lock()
        self._latest: Optional[str] = None  # guarded-by: _lock

    def local_text(self) -> str:
        """This process's registry rendered for the fold (host-owned gauges
        tagged with this process's index on multi-process jobs)."""
        tag = process_tag()
        return render(self.registry,
                      host_tag=None if tag is None else ("process", tag))

    def fold(self, local_text: Optional[str] = None) -> Optional[str]:
        """Gather every process's rendered registry and materialize the
        aggregate on process 0 (returned there; None on workers). Pass
        ``local_text`` to fold an already-rendered snapshot — the close
        path does, so the dumped ``metrics.prom`` and the folded text are
        the same bytes."""
        text = local_text if local_text is not None else self.local_text()
        from photon_ml_tpu.parallel.multihost import allgather_text

        texts = allgather_text(text)
        if not is_chief():
            return None
        agg = aggregate_text(texts)
        with self._lock:
            self._latest = agg
        return agg

    def latest(self) -> str:
        """The most recent aggregate (as fresh as the last sweep
        boundary); before the first fold — or on single-process jobs,
        where there is nothing to wait for — a live local render."""
        if process_tag() is not None:
            with self._lock:
                if self._latest is not None:
                    return self._latest
        return self.local_text()


#: sweep-boundary hooks; empty (the common case) costs one truthiness check
_SWEEP_HOOKS: list = []


def install_sweep_hook(fn: Callable) -> Callable[[], None]:
    """Register ``fn(**info)`` to run at every coordinate-descent sweep /
    GLM lambda boundary; returns the uninstaller. The telemetry session
    owns install/uninstall — a hook left behind after its run would turn
    the next single-process fit into a hung collective."""
    _SWEEP_HOOKS.append(fn)

    def uninstall() -> None:
        try:
            _SWEEP_HOOKS.remove(fn)
        except ValueError:
            pass

    return uninstall


def sweep_boundary(**info) -> None:
    """Training's fold point (called by ``game/coordinate_descent.py``,
    ``game/multiprocess.py`` and ``glm/training.py`` once per sweep, at a
    collective-symmetric position). No hooks installed — the default — is
    a no-op; hook failures are logged, never raised (telemetry must not
    kill a run)."""
    if not _SWEEP_HOOKS:
        return
    for fn in list(_SWEEP_HOOKS):
        try:
            fn(**info)
        except Exception:
            logger.warning("sweep-boundary telemetry hook failed",
                           exc_info=True)


# ---------------------------------------------------------------------------
# the chief's live scrape endpoint
# ---------------------------------------------------------------------------


def _make_handler(provider: Callable[[], str]):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # noqa: D102
            pass

        def _reply(self, status: int, data: bytes, content_type: str) -> None:
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):  # noqa: N802
            if self.path == "/metrics":
                try:
                    body = provider().encode("utf-8")
                except Exception as e:  # provider must not kill the server
                    self._reply(500, json.dumps(
                        {"error": repr(e)}).encode(), "application/json")
                    return
                self._reply(200, body, CONTENT_TYPE)
            elif self.path == "/healthz":
                self._reply(200, json.dumps({"status": "ok"}).encode(),
                            "application/json")
            else:
                self._reply(404, json.dumps(
                    {"error": f"unknown path {self.path}"}).encode(),
                    "application/json")

    return Handler


class MetricsHTTPServer:
    """Threaded ``GET /metrics`` listener serving ``provider()`` — the
    training-side sibling of ``serving/http.py::GameServer`` (same
    start/stop lifecycle, same exposition content type)."""

    def __init__(self, provider: Callable[[], str], *,
                 host: str = "127.0.0.1", port: int = 0):
        self._httpd = ThreadingHTTPServer((host, port),
                                          _make_handler(provider))
        #: start/stop are operator-lifecycle calls from one control thread
        self._thread: Optional[threading.Thread] = None  # guarded-by: caller

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "MetricsHTTPServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name="photon-metrics-http")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join()
            self._thread = None


# ---------------------------------------------------------------------------
# span-trace merge
# ---------------------------------------------------------------------------


def merge_trace_files(paths: Iterable[tuple[int, str]]) -> list[dict]:
    """Fold per-process ``trace.jsonl`` files into one timeline.

    ``paths`` yields ``(process_index, path)``. Every record gains a
    ``process`` attribute; the result is sorted by wall-clock ``ts``
    (stable, so same-timestamp records keep per-process file order) —
    cross-host sweep skew reads directly off adjacent ``cd.sweep`` spans.
    Span/parent ids keep their per-process scope: the unique span key in a
    merged trace is ``(process, span_id)``.
    """
    records: list[dict] = []
    for pid, path in paths:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                rec["process"] = pid
                records.append(rec)
    records.sort(key=lambda r: r.get("ts", 0.0))
    return records
