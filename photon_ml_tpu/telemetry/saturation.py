"""Resource-saturation telemetry: USE-method gauges over a CLOSED vocabulary.

The retained-telemetry ring (history.py) answers "what happened"; this
module answers the capacity question behind it — **which resource is the
binding constraint right now**. Following the USE method (utilization /
saturation / errors per resource — the same SRE playbook the SLO burn
tracker borrowed its budget math from), every serving-path resource gets
three gauges:

- ``photon_resource_utilization{resource=...}`` — busy fraction in
  [0, 1]: device duty cycle, queue depth over ``--max-queue``, pool
  active-workers over pool size, open connections over
  ``--max-connections``.
- ``photon_resource_saturation{resource=...}`` — waiting work (queue
  depth, pending pool tasks, buffered reqlog records): the "extra demand
  the resource could not absorb" axis.
- ``photon_resource_errors{resource=...}`` — errors attributed to the
  resource over the LAST sampling interval (sheds, refused connections,
  dropped log records). Probes report cumulative counts; the sampler
  deltas them, so the gauge reads as a per-interval rate numerator.

The resource vocabulary (:data:`RESOURCES`) is CLOSED and lint-enforced
(``tel-conn-home``): a resource name never derives from traffic, so the
plane's cardinality is bounded by construction, and a dashboard can
enumerate the axis. :class:`SaturationSampler` is **injectable-tick**
like :class:`~photon_ml_tpu.telemetry.history.HistorySampler` — it does
no threading of its own; the serving mains hang ``sample`` off the
history sampler's ``pre_sample`` hook so every retained ring snapshot
carries fresh saturation gauges, and the router's byte-identical fold
(``tools/metrics_fold.py``) ships them fleet-wide for free.

Probes are plain callables returning a small dict, CONSTRUCTED AT THE
WIRING SITE (``cli/serve_game.py`` / ``cli/serve_fleet.py`` /
``serving/http.py``) — telemetry never imports serving or fleet, the
same inversion ``fold_history`` uses. This module only supplies the
generic probe builders (:func:`queue_probe`, :func:`executor_probe`,
:func:`busy_probe`) and the device duty-cycle derivation.
"""

from __future__ import annotations

import threading
from typing import Callable, Mapping, Optional

from photon_ml_tpu.telemetry import metrics as _metrics

__all__ = [
    "RESOURCES",
    "SaturationSampler",
    "busy_probe",
    "device_busy_seconds",
    "executor_probe",
    "queue_probe",
]

#: the CLOSED resource vocabulary — every serving-path resource the
#: capacity plane accounts for. Additions are a reviewed decision (the
#: ``tel-conn-home`` lint requires probe registrations to name one of
#: these as a literal), mirroring the history-series and shed-reason
#: vocabularies.
RESOURCES = (
    "device",
    "batcher_queue",
    "rank_batcher_queue",
    "http_connections",
    "handler_threads",
    "saver_pool",
    "router_pool",
    "hedge_pool",
    "reqlog",
)

_UTILIZATION = _metrics.gauge(
    "photon_resource_utilization",
    "USE-method utilization per serving-path resource (busy fraction in "
    "[0, 1]: device duty cycle, queue depth / capacity, pool active / "
    "size, open connections / budget)",
    labels=("resource",))
_SATURATION = _metrics.gauge(
    "photon_resource_saturation",
    "USE-method saturation per serving-path resource (waiting work: "
    "queue depth, pending pool tasks, buffered log records)",
    labels=("resource",))
_ERRORS = _metrics.gauge(
    "photon_resource_errors",
    "USE-method errors attributed to each serving-path resource over "
    "the last sampling interval (sheds, refused connections, drops)",
    labels=("resource",))
# each host saturates on its own pressure: a fleet fold must fan these
# out per host, never let one host's duty cycle overwrite another's
for _fam in ("photon_resource_utilization", "photon_resource_saturation",
             "photon_resource_errors"):
    _metrics.mark_host_owned(_fam)


def _clamp01(value: float) -> float:
    return 0.0 if value < 0.0 else (1.0 if value > 1.0 else float(value))


def queue_probe(depth_fn: Callable[[], int],
                capacity_fn: Callable[[], Optional[int]],
                errors_fn: Optional[Callable[[], float]] = None,
                ) -> Callable[[], dict]:
    """Probe for a bounded queue: utilization = depth / capacity (0 when
    unbounded), saturation = depth, errors = the caller's cumulative
    refusal count (e.g. this queue's shed tally)."""
    def probe() -> dict:
        depth = float(depth_fn())
        cap = capacity_fn()
        out = {"utilization": _clamp01(depth / cap) if cap else 0.0,
               "saturation": depth}
        if errors_fn is not None:
            out["errors"] = float(errors_fn())
        return out
    return probe


def executor_probe(executor, size: Optional[int] = None,
                   ) -> Callable[[], dict]:
    """Probe for a stdlib ``ThreadPoolExecutor``: utilization = active
    workers / pool size, saturation = queued-but-unstarted tasks. Reads
    two private attributes (``_idle_semaphore``, ``_work_queue``) — the
    ONE sanctioned peek, confined here so a stdlib change breaks exactly
    one function (and degrades to zeros, never raises)."""
    def probe() -> dict:
        cap = size if size is not None \
            else getattr(executor, "_max_workers", 0)
        try:
            idle = executor._idle_semaphore._value
            spawned = len(executor._threads)
            pending = executor._work_queue.qsize()
        except AttributeError:  # pragma: no cover - stdlib drift
            return {"utilization": 0.0, "saturation": 0.0}
        active = max(0, spawned - idle)
        return {"utilization": _clamp01(active / cap) if cap else 0.0,
                "saturation": float(pending)}
    return probe


def busy_probe(busy_seconds_fn: Callable[[], float],
               errors_fn: Optional[Callable[[], float]] = None,
               ) -> Callable[[], dict]:
    """Probe for a duty-cycle resource: the callable returns CUMULATIVE
    busy-seconds; the sampler turns the interval delta over wall time
    into utilization (clamped to [0, 1] — overlapping busy intervals on
    a threaded host can nominally exceed the wall clock)."""
    def probe() -> dict:
        out: dict = {"busy_seconds": float(busy_seconds_fn())}
        if errors_fn is not None:
            out["errors"] = float(errors_fn())
        return out
    return probe


def device_busy_seconds(registry=None) -> float:
    """Cumulative device busy-seconds, from whichever layer timed the
    dispatch in this process: the summed ``_sum`` of the profiling
    layer's ``photon_execute_latency_seconds`` histogram (training and
    any ``profile_jit``-wrapped program) plus the request path's
    ``photon_serving_stage_seconds{stage="execute"}`` — serving engines
    count compiles via ``record_compile`` and time the device leg as the
    execute STAGE, so the profiled family never accumulates there (the
    two sources are disjoint per process, never double-counted). Feed
    through :func:`busy_probe`; the interval delta over wall time IS the
    device duty cycle."""
    reg = registry if registry is not None else _metrics.default_registry()
    total = 0.0
    fam = reg.get("photon_execute_latency_seconds")
    if fam is not None:
        total += sum(child.sum for _labels, child in fam.children())
    stages = reg.get("photon_serving_stage_seconds")
    if stages is not None:
        idx = (stages.label_names.index("stage")
               if "stage" in stages.label_names else None)
        total += sum(child.sum for values, child in stages.children()
                     if idx is not None and values[idx] == "execute")
    return float(total)


class SaturationSampler:
    """Derives the three USE gauges for every registered probe on each
    injectable tick.

    ``add_probe(resource, probe)`` registers a callable returning a dict
    with any of ``utilization`` / ``saturation`` / ``errors`` (cumulative
    — deltaed here) / ``busy_seconds`` (cumulative — converted to
    utilization over the interval). Unknown resource names raise: the
    vocabulary is closed at runtime exactly as ``tel-conn-home`` closes
    it at lint time. ``sample(now=)`` drives every probe and publishes
    the gauges; a failing probe zeroes its resource for the tick rather
    than taking down sampling (observation never takes down serving).
    """

    def __init__(self, *, registry=None):
        self._registry = registry if registry is not None \
            else _metrics.default_registry()
        self._utilization = self._registry.gauge(
            "photon_resource_utilization", _UTILIZATION.help,
            labels=("resource",))
        self._saturation = self._registry.gauge(
            "photon_resource_saturation", _SATURATION.help,
            labels=("resource",))
        self._errors = self._registry.gauge(
            "photon_resource_errors", _ERRORS.help, labels=("resource",))
        self._lock = threading.Lock()
        self._probes: dict[str, Callable[[], dict]] = {}  # guarded-by: _lock
        self._prev_errors: dict[str, float] = {}  # guarded-by: _lock
        self._prev_busy: dict[str, float] = {}  # guarded-by: _lock
        self._prev_ts: Optional[float] = None  # guarded-by: _lock

    def add_probe(self, resource: str,
                  probe: Callable[[], dict]) -> None:
        if resource not in RESOURCES:
            raise ValueError(
                f"unknown resource {resource!r}: the saturation "
                f"vocabulary is closed ({', '.join(RESOURCES)})")
        with self._lock:
            self._probes[resource] = probe

    def resources(self) -> tuple:
        """The currently probed resources (sorted, for /statusz)."""
        with self._lock:
            return tuple(sorted(self._probes))

    def sample(self, now: Optional[float] = None) -> dict:
        """One injectable tick: run every probe, publish the gauges,
        return ``{resource: {utilization, saturation, errors}}``. Wired
        as the history sampler's ``pre_sample`` so each retained ring
        snapshot carries this tick's values."""
        if now is None:
            import time as _time
            now = _time.monotonic()
        out: dict[str, dict] = {}
        with self._lock:
            probes = dict(self._probes)
            dt = (now - self._prev_ts) if self._prev_ts is not None else 0.0
            self._prev_ts = float(now)
        for resource, probe in probes.items():
            try:
                raw: Mapping = probe() or {}
            except Exception:
                raw = {}
            util = float(raw.get("utilization", 0.0))
            busy = raw.get("busy_seconds")
            with self._lock:
                if busy is not None:
                    prev = self._prev_busy.get(resource)
                    self._prev_busy[resource] = float(busy)
                    if prev is not None and dt > 0:
                        util = _clamp01((float(busy) - prev) / dt)
                    else:
                        util = 0.0
                errors_cum = float(raw.get("errors", 0.0))
                prev_err = self._prev_errors.get(resource, errors_cum)
                self._prev_errors[resource] = errors_cum
            values = {
                "utilization": _clamp01(util),
                "saturation": max(0.0, float(raw.get("saturation", 0.0))),
                "errors": max(0.0, errors_cum - prev_err),
            }
            self._utilization.labels(resource=resource).set(
                values["utilization"])
            self._saturation.labels(resource=resource).set(
                values["saturation"])
            self._errors.labels(resource=resource).set(values["errors"])
            out[resource] = values
        return out
