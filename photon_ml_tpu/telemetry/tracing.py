"""Span tracing: nested ``span(name)`` contexts → ``trace.jsonl``.

``util/Timed.scala`` gave the reference *flat* stage timings in a log file;
a run that interleaves coordinate descent, retries, checkpointing and
validation needs the *tree*: which stage contained which step, and where
the wall-clock actually went. A span is one timed region with an id, its
enclosing span's id (tracked per-thread via ``contextvars``, so concurrent
serving requests each get their own stack), and arbitrary JSON attributes.

- unconfigured (the default), spans cost two contextvar operations and a
  ``perf_counter`` pair — cheap enough to leave permanently in hot-ish
  paths like the coordinate-descent step loop;
- ``GLOBAL_TRACER.configure(path, bus=...)`` (done by the drivers'
  ``--telemetry-dir`` flag) appends one JSON line per completed span to
  ``<run_dir>/trace.jsonl`` and, when a bus is given, posts a
  ``span_finished`` event so the EventBus→metrics bridge folds span
  durations into the registry;
- ``timed()`` (:mod:`photon_ml_tpu.logging_util`) is now a thin wrapper
  over a span — stage sections appear in the trace tree for free.

Record layout (one JSON object per line)::

    {"name": ..., "span_id": 3, "parent_id": 2, "ts": <wall clock>,
     "t0": ..., "t1": ..., "seconds": ..., <attribute>: ...}

``t0``/``t1`` are ``perf_counter`` readings — monotonic and mutually
comparable within the process, so a child's interval provably nests inside
its parent's (the property the telemetry tests assert); ``ts`` is the wall
clock for humans correlating with ``photon.log``.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import json
import os
import threading
import time
from typing import Iterator, Optional

#: the enclosing span's id on THIS thread/context (None = root)
_CURRENT: contextvars.ContextVar[Optional[int]] = contextvars.ContextVar(
    "photon_current_span", default=None)

#: the full open-ancestor id stack on THIS thread/context — what lets a
#: span that outlives its lexical parent (async background work submitted
#: with a copied context) re-parent to the nearest ancestor still open
_STACK: contextvars.ContextVar[tuple] = contextvars.ContextVar(
    "photon_span_stack", default=())

#: reserved record keys — span attributes may not shadow them
_RESERVED = frozenset(
    {"name", "span_id", "parent_id", "ts", "t0", "t1", "seconds"})


class Span:
    """One live timed region; ``set(**attrs)`` attaches attributes any time
    before exit (e.g. a loss computed after the work the span times)."""

    __slots__ = ("name", "span_id", "parent_id", "attrs", "ts", "t0", "t1",
                 "seconds")

    def __init__(self, name: str, span_id: int, parent_id: Optional[int],
                 attrs: dict):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.attrs = attrs
        self.seconds = 0.0

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def record(self) -> dict:
        bad = _RESERVED & self.attrs.keys()
        if bad:
            raise ValueError(f"span attributes shadow reserved keys {bad}")
        return {"name": self.name, "span_id": self.span_id,
                "parent_id": self.parent_id, "ts": self.ts,
                "t0": self.t0, "t1": self.t1,
                "seconds": self.seconds, **self.attrs}


class Tracer:
    """Span factory + (optional) JSONL sink + (optional) EventBus bridge."""

    def __init__(self):
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._fh = None
        self._path: Optional[str] = None
        self._bus = None
        #: ids of spans currently open anywhere in the process — consulted
        #: at span exit so an async span re-parents instead of recording an
        #: interval that leaks outside its (already closed) parent
        self._open: set[int] = set()
        #: completed-record taps (the flight recorder's span lane) —
        #: replaced wholesale on mutation so readers iterate an immutable
        #: snapshot without taking the lock on the span hot path
        self._taps: tuple = ()

    @property
    def enabled(self) -> bool:
        """True when spans are being exported (a sink is configured)."""
        return self._fh is not None

    @property
    def path(self) -> Optional[str]:
        return self._path

    def configure(self, path: str, bus=None) -> "Tracer":
        """Start appending completed spans to ``path`` (parent dirs
        created). Reconfiguring closes the previous sink first."""
        with self._lock:
            if self._fh is not None:
                self._fh.close()
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            self._fh = open(path, "a", encoding="utf-8")
            self._path = path
            self._bus = bus
        return self

    def close(self) -> None:
        """Stop exporting; spans keep working (and keep their parentage)
        as no-ops."""
        with self._lock:
            if self._fh is not None:
                self._fh.close()
            self._fh = None
            self._path = None
            self._bus = None

    def add_tap(self, fn) -> "callable":
        """Call ``fn(record)`` for every completed span/annotation record
        — even when no file sink is configured (the flight recorder taps
        here so the black box fills on hosts that never write
        ``trace.jsonl``). Tap exceptions are swallowed; returns a
        removal callable."""
        with self._lock:
            self._taps = self._taps + (fn,)

        def _remove() -> None:
            with self._lock:
                self._taps = tuple(t for t in self._taps if t is not fn)
        return _remove

    @property
    def _sinking(self) -> bool:
        """True when a completed record goes anywhere (file or tap) —
        the guard that keeps unconfigured spans dict-build-free."""
        return self._fh is not None or bool(self._taps)

    def _write(self, record: dict) -> None:
        for tap in self._taps:
            try:
                tap(record)
            except Exception:
                pass
        line = json.dumps(record) + "\n"
        with self._lock:
            if self._fh is not None:
                self._fh.write(line)
                self._fh.flush()

    @contextlib.contextmanager
    def span(self, name: str, **attrs) -> Iterator[Span]:
        sp = Span(name, next(self._ids), _CURRENT.get(), attrs)
        token = _CURRENT.set(sp.span_id)
        ancestors = _STACK.get()
        stack_token = _STACK.set(ancestors + (sp.span_id,))
        with self._lock:
            self._open.add(sp.span_id)
        sp.ts = time.time()
        sp.t0 = time.perf_counter()
        try:
            yield sp
        finally:
            # leave the open set BEFORE stamping t1: a concurrent child
            # that still observes this span open is then guaranteed to
            # stamp its own t1 first, so the enclosure check below can
            # never race a parent mid-close
            with self._lock:
                self._open.discard(sp.span_id)
            sp.t1 = time.perf_counter()
            sp.seconds = sp.t1 - sp.t0
            _CURRENT.reset(token)
            _STACK.reset(stack_token)
            with self._lock:
                if (sp.parent_id is not None
                        and sp.parent_id not in self._open):
                    # async span outlived its lexical parent (background
                    # writers inherit the submitting stage's context but
                    # may finish after the stage closes): re-parent to the
                    # nearest ancestor still open, so every recorded
                    # interval provably nests inside its parent's — the
                    # trace.jsonl enclosure contract
                    sp.parent_id = next(
                        (a for a in reversed(ancestors) if a in self._open),
                        None)
            if self._sinking:
                self._write(sp.record())
            bus = self._bus
            if bus is not None:
                bus.post("span_finished", span=name, span_id=sp.span_id,
                         parent_id=sp.parent_id, seconds=sp.seconds)

    def annotate(self, name: str, **payload) -> None:
        """Write a non-span record (e.g. an optimizer iteration table) into
        the trace file, tagged with the current span as its parent. No-op
        when unconfigured."""
        if not self._sinking:
            return
        self._write({"name": name, "span_id": None,
                     "parent_id": _CURRENT.get(), "ts": time.time(),
                     **payload})

    @contextlib.contextmanager
    def span_under(self, parent_id: Optional[int], name: str,
                   **attrs) -> Iterator[Span]:
        """A span with an EXPLICIT parent — for work handed to a pool
        thread where the submitting request's contextvars do not follow
        (the fleet router's fan-out legs). Inside the context, nested
        ``span()`` calls parent to this span as usual; at exit, a parent
        that already closed re-parents this span to root rather than
        recording an interval that leaks outside it."""
        sp = Span(name, next(self._ids), parent_id, attrs)
        token = _CURRENT.set(sp.span_id)
        # the explicit parent is the only known-open ancestor here: the
        # submitting thread's deeper ancestry is not visible to this pool
        # thread, and claiming it would let re-parenting resurrect spans
        # this leg never nested inside
        ancestry = () if parent_id is None else (parent_id,)
        stack_token = _STACK.set(ancestry + (sp.span_id,))
        with self._lock:
            self._open.add(sp.span_id)
        sp.ts = time.time()
        sp.t0 = time.perf_counter()
        try:
            yield sp
        finally:
            with self._lock:
                self._open.discard(sp.span_id)
            sp.t1 = time.perf_counter()
            sp.seconds = sp.t1 - sp.t0
            _CURRENT.reset(token)
            _STACK.reset(stack_token)
            with self._lock:
                if (sp.parent_id is not None
                        and sp.parent_id not in self._open):
                    sp.parent_id = None
            if self._sinking:
                self._write(sp.record())
            bus = self._bus
            if bus is not None:
                bus.post("span_finished", span=name, span_id=sp.span_id,
                         parent_id=sp.parent_id, seconds=sp.seconds)

    def record_span(self, name: str, *, seconds: float,
                    parent_id: Optional[int] = None,
                    ts: Optional[float] = None, **attrs) -> int:
        """Materialize an EXTERNALLY timed region as a completed span —
        how the router turns a shard host's leg-summary stage seconds
        into children of its ``fleet.leg`` span. ``t0``/``t1`` are null
        (the remote perf_counter domain is not comparable to ours; the
        report tools only need ``seconds``/``parent_id``). Returns the
        new span id. No-op (id still minted) when unconfigured."""
        span_id = next(self._ids)
        if self._sinking:
            record = {"name": name, "span_id": span_id,
                      "parent_id": parent_id,
                      "ts": time.time() if ts is None else ts,
                      "t0": None, "t1": None,
                      "seconds": float(seconds), **attrs}
            bad = _RESERVED & attrs.keys()
            if bad:
                raise ValueError(
                    f"span attributes shadow reserved keys {bad}")
            self._write(record)
        return span_id

    def open_span_ids(self) -> tuple:
        """Ids of spans currently open anywhere in the process, sorted —
        what the flight recorder stamps into a dump header so a
        postmortem can name the work in flight at the moment of death."""
        with self._lock:
            return tuple(sorted(self._open))


#: process-global tracer the drivers configure; instrumented modules call
#: the module-level :func:`span` so embedders can swap sinks in one place
GLOBAL_TRACER = Tracer()


def span(name: str, **attrs):
    return GLOBAL_TRACER.span(name, **attrs)


def annotate(name: str, **payload) -> None:
    GLOBAL_TRACER.annotate(name, **payload)


def current_span_id() -> Optional[int]:
    """The enclosing span's id on this thread/context (None = root) —
    capture it BEFORE handing work to a pool so :func:`span_under` can
    stitch the pool thread's spans back under the request."""
    return _CURRENT.get()


def span_under(parent_id: Optional[int], name: str, **attrs):
    return GLOBAL_TRACER.span_under(parent_id, name, **attrs)


def record_span(name: str, *, seconds: float,
                parent_id: Optional[int] = None,
                ts: Optional[float] = None, **attrs) -> int:
    return GLOBAL_TRACER.record_span(
        name, seconds=seconds, parent_id=parent_id, ts=ts, **attrs)


def enabled() -> bool:
    return GLOBAL_TRACER.enabled


def configure(path: str, bus=None) -> Tracer:
    return GLOBAL_TRACER.configure(path, bus=bus)


def close() -> None:
    GLOBAL_TRACER.close()
