"""Unified telemetry: metrics registry, Prometheus exposition, span tracing.

The observability layer training, resilience, and serving all feed
(OBSERVABILITY.md is the operator-facing doc; SURVEY.md §5.1 maps the
reference's ``PhotonLogger``/``Timed``/``OptimizationStatesTracker`` story
this supersedes):

- :mod:`~photon_ml_tpu.telemetry.metrics` — thread-safe labeled
  Counter/Gauge/Histogram families in a process-global registry
  (stdlib-only, nanosecond-scale updates);
- :mod:`~photon_ml_tpu.telemetry.prometheus` — ``/metrics`` text
  exposition + the matching parser;
- :mod:`~photon_ml_tpu.telemetry.tracing` — nested spans →
  ``trace.jsonl`` (``timed()`` stages ride it automatically);
- :mod:`~photon_ml_tpu.telemetry.bridge` — the EventBus→registry
  translator (existing ``serving_request``/``retry_*``/``stage_finished``
  events become metrics with zero call-site changes);
- :mod:`~photon_ml_tpu.telemetry.device` — optional host-RSS/device-memory
  gauge sampler.

:class:`TelemetrySession` is the drivers' one-call lifecycle: configure the
global tracer into ``--telemetry-dir``, bind the bridge, start the sampler,
and on close dump a final ``metrics.prom`` snapshot next to the trace.
"""

from __future__ import annotations

import os
from typing import Optional

from photon_ml_tpu.telemetry import bridge, metrics, tracing  # noqa: F401
from photon_ml_tpu.telemetry.metrics import (  # noqa: F401
    DEFAULT_LATENCY_BUCKETS,
    MetricsRegistry,
    default_registry,
    quantile_from_buckets,
)
from photon_ml_tpu.telemetry.tracing import (  # noqa: F401
    GLOBAL_TRACER,
    Tracer,
    annotate,
    span,
)


def record_optimizer_trace(coordinate_id: str, result, *, sweep: int = 0,
                           ) -> None:
    """Fold one coordinate solve's optimizer trace into telemetry: the
    per-iteration (loss, |grad|) table goes into ``trace.jsonl`` as an
    ``optimizer_trace`` annotation under the current span, and the
    iteration/convergence summary lands in the registry — the reference's
    ``OptimizationStatesTracker`` dump, queryable instead of grepped.

    Call sites gate on :func:`tracing.enabled` — reading ``result`` arrays
    forces a device sync, which a non-telemetry run must not pay.
    """
    import numpy as np

    iterations = int(result.iterations)
    converged = bool(result.converged)
    metrics.counter(
        "photon_optimizer_iterations_total",
        "Optimizer iterations spent, per coordinate",
        labels=("coordinate",)).labels(coordinate=coordinate_id).inc(
            max(iterations, 0))
    metrics.gauge(
        "photon_optimizer_converged",
        "1 when the coordinate's last solve converged",
        labels=("coordinate",)).labels(coordinate=coordinate_id).set(
            1.0 if converged else 0.0)
    values = np.asarray(result.values, np.float64)
    gnorms = np.asarray(result.grad_norms, np.float64)
    if values.size == 0:
        return  # per-iteration tracking off (e.g. vmapped solves)
    n = min(iterations + 1, len(values))
    finite = np.isfinite(values[:n])
    if finite.any():
        last = int(np.nonzero(finite)[0][-1])
        metrics.gauge(
            "photon_optimizer_final_loss",
            "Objective value at the coordinate's last recorded iteration",
            labels=("coordinate",)).labels(coordinate=coordinate_id).set(
                float(values[last]))
        metrics.gauge(
            "photon_optimizer_final_grad_norm",
            "Gradient norm at the coordinate's last recorded iteration",
            labels=("coordinate",)).labels(coordinate=coordinate_id).set(
                float(gnorms[last]))
    tracing.annotate(
        "optimizer_trace", coordinate=coordinate_id, sweep=sweep,
        iterations=iterations, converged=converged,
        values=[float(v) for v in values[:n]],
        grad_norms=[float(g) for g in gnorms[:n]])


class _NullSession:
    """Telemetry disabled: every lifecycle call is a no-op."""

    enabled = False

    def close(self) -> None:
        pass


class TelemetrySession:
    """One run's telemetry lifecycle (built by the drivers from
    ``--telemetry-dir`` / ``--telemetry-poll-s``)."""

    enabled = True

    def __init__(self, telemetry_dir: Optional[str] = None,
                 poll_interval_s: float = 0.0, bus=None,
                 registry: Optional[MetricsRegistry] = None):
        if bus is None:
            from photon_ml_tpu.events import GLOBAL_BUS as bus
        self.telemetry_dir = telemetry_dir
        self.registry = registry if registry is not None \
            else default_registry()
        self._unbind = bridge.bind(bus=bus, registry=self.registry)
        self._sampler = None
        self._owns_tracer = False
        if telemetry_dir:
            os.makedirs(telemetry_dir, exist_ok=True)
            tracing.configure(os.path.join(telemetry_dir, "trace.jsonl"),
                              bus=bus)
            self._owns_tracer = True
        if poll_interval_s > 0:
            from photon_ml_tpu.telemetry.device import DeviceStatsSampler

            self._sampler = DeviceStatsSampler(
                poll_interval_s, registry=self.registry).start()

    def dump_metrics(self) -> Optional[str]:
        """Write the registry snapshot as ``<dir>/metrics.prom``; returns
        the path (None when no telemetry dir)."""
        if not self.telemetry_dir:
            return None
        from photon_ml_tpu.telemetry.prometheus import render

        path = os.path.join(self.telemetry_dir, "metrics.prom")
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(render(self.registry))
        os.replace(tmp, path)
        return path

    def close(self) -> None:
        if self._sampler is not None:
            self._sampler.close()
            self._sampler = None
        self.dump_metrics()
        if self._owns_tracer:
            tracing.close()
            self._owns_tracer = False
        self._unbind()
        self._unbind = lambda: None


def start_telemetry(telemetry_dir: Optional[str] = None,
                    poll_interval_s: float = 0.0, bus=None):
    """Driver entry: a live :class:`TelemetrySession` when anything is
    enabled, else an inert null session (so callers always hold something
    with ``close()``)."""
    if not telemetry_dir and poll_interval_s <= 0:
        return _NullSession()
    return TelemetrySession(telemetry_dir=telemetry_dir,
                            poll_interval_s=poll_interval_s, bus=bus)
