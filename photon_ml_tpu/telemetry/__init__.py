"""Unified telemetry: metrics registry, Prometheus exposition, span tracing.

The observability layer training, resilience, and serving all feed
(OBSERVABILITY.md is the operator-facing doc; SURVEY.md §5.1 maps the
reference's ``PhotonLogger``/``Timed``/``OptimizationStatesTracker`` story
this supersedes):

- :mod:`~photon_ml_tpu.telemetry.metrics` — thread-safe labeled
  Counter/Gauge/Histogram families in a process-global registry
  (stdlib-only, nanosecond-scale updates);
- :mod:`~photon_ml_tpu.telemetry.prometheus` — ``/metrics`` text
  exposition + the matching parser;
- :mod:`~photon_ml_tpu.telemetry.tracing` — nested spans →
  ``trace.jsonl`` (``timed()`` stages ride it automatically);
- :mod:`~photon_ml_tpu.telemetry.bridge` — the EventBus→registry
  translator (existing ``serving_request``/``retry_*``/``stage_finished``
  events become metrics with zero call-site changes);
- :mod:`~photon_ml_tpu.telemetry.device` — optional host-RSS/device-memory
  gauge sampler.

- :mod:`~photon_ml_tpu.telemetry.aggregate` — the fleet fold: merge N
  process registries into one scrapeable aggregate (collective at sweep
  boundaries, offline via ``tools/metrics_fold.py``), plus the chief's
  ``--metrics-port`` listener and the trace-merge helper.

:class:`TelemetrySession` is the drivers' one-call lifecycle: configure the
global tracer into ``--telemetry-dir``, bind the bridge, start the sampler
and (``--telemetry-poll-s``) the periodic ``metrics.prom`` snapshot writer,
stand up the fleet aggregator under ``--metrics-port``, and on close dump a
final ``metrics.prom`` snapshot next to the trace — with, on the chief of a
folding run, the matching ``metrics.aggregate.prom``.
"""

from __future__ import annotations

import logging
import os
import sys
import threading
from typing import Optional

from photon_ml_tpu.telemetry import bridge, metrics, tracing  # noqa: F401
from photon_ml_tpu.telemetry.metrics import (  # noqa: F401
    DEFAULT_LATENCY_BUCKETS,
    MetricsRegistry,
    default_registry,
    quantile_from_buckets,
)
from photon_ml_tpu.telemetry.tracing import (  # noqa: F401
    GLOBAL_TRACER,
    Tracer,
    annotate,
    span,
)

logger = logging.getLogger(__name__)


def emit_build_info(registry: Optional[MetricsRegistry] = None) -> None:
    """Register the ``photon_build_info{version, process, jax_version}``
    info-style gauge (constant 1; the payload rides the labels). Every
    driver emits it at startup, so one fleet scrape shows a mixed-version
    fleet — the failure mode the aggregator's type-conflict error points
    at — at a glance. Idempotent per (version, process, jax_version)."""
    import jax

    from photon_ml_tpu import __version__

    reg = registry if registry is not None else default_registry()
    try:
        process = str(jax.process_index())
    except Exception:
        process = "0"
    reg.gauge(
        "photon_build_info",
        "Constant 1; build/version info rides the labels (a fleet scrape "
        "shows mixed-version fleets at a glance)",
        labels=("version", "process", "jax_version")).labels(
            version=__version__, process=process,
            jax_version=jax.__version__).set(1.0)


def record_optimizer_trace(coordinate_id: str, result, *, sweep: int = 0,
                           ) -> None:
    """Fold one coordinate solve's optimizer trace into telemetry: the
    per-iteration (loss, |grad|) table goes into ``trace.jsonl`` as an
    ``optimizer_trace`` annotation under the current span, and the
    iteration/convergence summary lands in the registry — the reference's
    ``OptimizationStatesTracker`` dump, queryable instead of grepped.

    Call sites gate on :func:`tracing.enabled` — reading ``result`` arrays
    forces a device sync, which a non-telemetry run must not pay.
    """
    import numpy as np

    iterations = int(result.iterations)
    converged = bool(result.converged)
    metrics.counter(
        "photon_optimizer_iterations_total",
        "Optimizer iterations spent, per coordinate",
        labels=("coordinate",)).labels(coordinate=coordinate_id).inc(
            max(iterations, 0))
    metrics.gauge(
        "photon_optimizer_converged",
        "1 when the coordinate's last solve converged",
        labels=("coordinate",)).labels(coordinate=coordinate_id).set(
            1.0 if converged else 0.0)
    values = np.asarray(result.values, np.float64)
    gnorms = np.asarray(result.grad_norms, np.float64)
    if values.size == 0:
        return  # per-iteration tracking off (e.g. vmapped solves)
    n = min(iterations + 1, len(values))
    finite = np.isfinite(values[:n])
    if finite.any():
        last = int(np.nonzero(finite)[0][-1])
        metrics.gauge(
            "photon_optimizer_final_loss",
            "Objective value at the coordinate's last recorded iteration",
            labels=("coordinate",)).labels(coordinate=coordinate_id).set(
                float(values[last]))
        metrics.gauge(
            "photon_optimizer_final_grad_norm",
            "Gradient norm at the coordinate's last recorded iteration",
            labels=("coordinate",)).labels(coordinate=coordinate_id).set(
                float(gnorms[last]))
    tracing.annotate(
        "optimizer_trace", coordinate=coordinate_id, sweep=sweep,
        iterations=iterations, converged=converged,
        values=[float(v) for v in values[:n]],
        grad_norms=[float(g) for g in gnorms[:n]])


class _NullSession:
    """Telemetry disabled: every lifecycle call is a no-op."""

    enabled = False

    def close(self) -> None:
        pass


class TelemetrySession:
    """One run's telemetry lifecycle (built by the drivers from
    ``--telemetry-dir`` / ``--telemetry-poll-s`` / ``--metrics-port``).

    With ``metrics_port``, every process of the job installs the fleet
    fold hook (the fold is a collective, so the flag — shared by the whole
    job's command line — must act symmetrically) and the chief additionally
    serves ``GET /metrics`` with the latest aggregate. With a telemetry dir
    AND a positive poll interval, ``metrics.prom`` is re-snapshotted
    push-gateway-style every interval, so batch runs are observable
    mid-flight rather than only at exit.
    """

    enabled = True

    def __init__(self, telemetry_dir: Optional[str] = None,
                 poll_interval_s: float = 0.0, bus=None,
                 registry: Optional[MetricsRegistry] = None,
                 metrics_port: int = 0):
        if bus is None:
            from photon_ml_tpu.events import GLOBAL_BUS as bus
        self.telemetry_dir = telemetry_dir
        self.registry = registry if registry is not None \
            else default_registry()
        # session components: built here, torn down in close() — both
        # calls come from the one driver thread that owns the session
        self._unbind = bridge.bind(bus=bus, registry=self.registry)  # guarded-by: caller
        self._sampler = None  # guarded-by: caller
        self._owns_tracer = False  # guarded-by: caller
        self._aggregator = None  # guarded-by: caller
        self._server = None  # guarded-by: caller
        self._unhook = lambda: None  # guarded-by: caller
        self._snap_stop: Optional[threading.Event] = None  # guarded-by: caller
        self._snap_thread: Optional[threading.Thread] = None  # guarded-by: caller
        if telemetry_dir:
            os.makedirs(telemetry_dir, exist_ok=True)
            tracing.configure(os.path.join(telemetry_dir, "trace.jsonl"),
                              bus=bus)
            self._owns_tracer = True
        if poll_interval_s > 0:
            from photon_ml_tpu.telemetry.device import DeviceStatsSampler

            self._sampler = DeviceStatsSampler(
                poll_interval_s, registry=self.registry).start()
            if telemetry_dir:
                # push-gateway-style periodic snapshot on the same cadence
                # (Event.wait, not sleep — shutdown is immediate and the
                # resilience sleep-hygiene rule holds)
                self._snap_stop = threading.Event()
                self._snap_thread = threading.Thread(
                    target=self._snapshot_loop, args=(poll_interval_s,),
                    daemon=True, name="photon-telemetry-snapshot")
                self._snap_thread.start()
        if metrics_port:
            from photon_ml_tpu.telemetry.aggregate import (
                FleetMetricsAggregator,
                MetricsHTTPServer,
                install_sweep_hook,
                is_chief,
            )

            self._aggregator = FleetMetricsAggregator(registry=self.registry)
            self._unhook = install_sweep_hook(
                lambda **info: self._aggregator.fold())
            if is_chief():
                self._server = MetricsHTTPServer(
                    self._aggregator.latest, port=metrics_port).start()

    @property
    def metrics_url(self) -> Optional[str]:
        """The chief's live scrape URL (None off-chief / without
        ``--metrics-port``)."""
        return None if self._server is None else self._server.url

    def _snapshot_loop(self, interval_s: float) -> None:
        while not self._snap_stop.wait(interval_s):
            try:
                self.dump_metrics()
            except Exception:  # the writer must never kill the run
                logger.debug("periodic metrics snapshot failed",
                             exc_info=True)

    def _local_text(self) -> str:
        """This process's snapshot, host-tagged on multi-process jobs —
        the one renderer behind dumps, the periodic writer and the fold,
        so offline folds of the dumps reproduce the live fold exactly."""
        from photon_ml_tpu.telemetry.aggregate import process_tag
        from photon_ml_tpu.telemetry.prometheus import render

        tag = process_tag()
        return render(self.registry,
                      host_tag=None if tag is None else ("process", tag))

    def dump_metrics(self, text: Optional[str] = None) -> Optional[str]:
        """Write the registry snapshot as ``<dir>/metrics.prom`` (atomic
        tmp+rename — a scraper never reads a torn file); returns the path
        (None when no telemetry dir)."""
        if not self.telemetry_dir:
            return None
        return _write_atomic(
            os.path.join(self.telemetry_dir, "metrics.prom"),
            text if text is not None else self._local_text())

    def close(self) -> None:
        if self._snap_stop is not None:
            self._snap_stop.set()
            self._snap_thread.join()
            self._snap_stop = self._snap_thread = None
        if self._sampler is not None:
            self._sampler.close()
            self._sampler = None
        text = self._local_text()
        self.dump_metrics(text=text)
        if self._aggregator is not None:
            # final collective fold over the EXACT texts just dumped, so
            # tools/metrics_fold.py over the metrics.prom files reproduces
            # metrics.aggregate.prom byte-for-byte. Skipped when close()
            # runs on an exception path: the job is dying and a collective
            # here would hang against processes that never reach it.
            if sys.exc_info()[0] is None:
                try:
                    agg = self._aggregator.fold(local_text=text)
                except Exception:
                    logger.warning("final fleet metrics fold failed",
                                   exc_info=True)
                    agg = None
                if agg is not None and self.telemetry_dir:
                    _write_atomic(os.path.join(self.telemetry_dir,
                                               "metrics.aggregate.prom"),
                                  agg)
            if self._server is not None:
                self._server.stop()
                self._server = None
            self._unhook()
            self._unhook = lambda: None
            self._aggregator = None
        if self._owns_tracer:
            tracing.close()
            self._owns_tracer = False
        self._unbind()
        self._unbind = lambda: None


def _write_atomic(path: str, text: str) -> str:
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(text)
    os.replace(tmp, path)
    return path


def start_telemetry(telemetry_dir: Optional[str] = None,
                    poll_interval_s: float = 0.0, bus=None,
                    metrics_port: int = 0):
    """Driver entry: a live :class:`TelemetrySession` when anything is
    enabled, else an inert null session (so callers always hold something
    with ``close()``)."""
    if not telemetry_dir and poll_interval_s <= 0 and not metrics_port:
        return _NullSession()
    return TelemetrySession(telemetry_dir=telemetry_dir,
                            poll_interval_s=poll_interval_s, bus=bus,
                            metrics_port=metrics_port)
