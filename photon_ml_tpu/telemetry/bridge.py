"""EventBus → MetricsRegistry bridge.

The subsystems built before telemetry already *narrate* themselves on the
event bus — ``serving_request`` per scored request, ``retry_attempt`` /
``retry_exhausted`` around every transient-fault recovery,
``stage_started``/``stage_finished`` from ``timed()``, divergence-guard
verdicts, model registry lifecycle. This module turns that narration into
real metric families by subscribing ONE translating listener, so none of
those call sites needed touching to join the metrics story.

Cardinality discipline: event payloads carry unbounded detail (file paths,
error reprs); labels must not. The bridge keeps only bounded-vocabulary
labels — the retry ``op`` is truncated at its first ``:`` (``avro.read:
part-00007.avro`` → ``avro.read``), stage/span/coordinate names are the
small fixed sets the code declares.

``bind(bus, registry)`` is idempotent per (bus, registry) pair — the model
registry binds at construction and the drivers' ``--telemetry-dir`` path
binds again without double-counting.
"""

from __future__ import annotations

from typing import Callable, Optional

from photon_ml_tpu.telemetry.metrics import (
    MetricsRegistry,
    default_registry,
    mark_host_owned,
)

#: attribute stashed on the bus holding the registries already bridged to it
#: (strong refs on purpose: identity checks must not race id() reuse)
_BOUND_ATTR = "_telemetry_bridged_registries"


def _op_family(op: str) -> str:
    """``avro.read:part-00007.avro`` → ``avro.read`` (bounded label)."""
    return str(op).split(":", 1)[0]


def _make_listener(reg: MetricsRegistry) -> Callable:
    # families declared once, up front, so /metrics shows them at zero
    # before the first event arrives
    serving_requests = reg.counter(
        "photon_serving_requests_total",
        "Scored /score requests (one per request, any batch size)")
    serving_rows = reg.counter(
        "photon_serving_scored_rows_total",
        "Individual records scored across all requests")
    retry_attempts = reg.counter(
        "photon_retry_attempts_total",
        "Failed attempts that will be retried", labels=("op",))
    retry_exhausted = reg.counter(
        "photon_retry_exhausted_total",
        "Operations that failed past their retry budget", labels=("op",))
    retry_recovered = reg.counter(
        "photon_retry_recoveries_total",
        "Operations that succeeded after at least one failed attempt",
        labels=("op",))
    stage_seconds = reg.histogram(
        "photon_stage_seconds", "timed() stage durations",
        labels=("stage",),
        buckets=(0.01, 0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0, 1800.0))
    span_seconds = reg.histogram(
        "photon_span_seconds", "Completed span durations by span name",
        labels=("span",),
        buckets=(0.001, 0.01, 0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0))
    divergences = reg.counter(
        "photon_divergence_detected_total",
        "Non-finite coordinate steps detected by the guard",
        labels=("coordinate",))
    rollbacks = reg.counter(
        "photon_coordinate_rollbacks_total",
        "Guard rollback-retries", labels=("coordinate",))
    freezes = reg.counter(
        "photon_coordinate_freezes_total",
        "Coordinates frozen at their last good model",
        labels=("coordinate",))
    reloads = reg.counter(
        "photon_model_reloads_total",
        "Model versions loaded and registered")
    reload_rejects = reg.counter(
        "photon_model_reload_rejects_total",
        "Candidate model dirs rejected by validation")
    active_version = reg.gauge(
        "photon_model_active_version",
        "Currently active serving model version (0 = none)")
    # host-owned: a serving fleet mid-rollout legitimately has processes
    # on different versions — the aggregate must show every one, not
    # whichever host's gauge merged last
    mark_host_owned("photon_model_active_version")
    training_runs = reg.counter(
        "photon_training_runs_total",
        "Training driver invocations", labels=("driver",))
    supervisor_faults = reg.counter(
        "photon_supervisor_faults_total",
        "Fleet liveness faults detected by the supervisor",
        labels=("reason",))  # "exit" | "stall" — a closed vocabulary
    supervisor_restarts = reg.counter(
        "photon_supervisor_restarts_total",
        "Whole-fleet restarts performed by the supervisor")
    supervisor_exhausted = reg.counter(
        "photon_supervisor_exhausted_total",
        "Supervised runs abandoned past their restart budget or deadline")
    drift_events = reg.counter(
        "photon_quality_drift_events_total",
        "quality_drift_detected events: the live score distribution's "
        "PSI vs the active model's baseline crossed the drift threshold")
    canary_evals = reg.counter(
        "photon_quality_canary_evals_total",
        "Canary shadow-scoring evaluations at activation time, by "
        "verdict (pass | divergent | rejected — a closed vocabulary)",
        labels=("verdict",))
    brownout_changes = reg.counter(
        "photon_brownout_changes_total",
        "Serving brownout level transitions (up = degrading under "
        "pressure, down = recovering — a closed vocabulary)",
        labels=("direction",))
    slo_burns = reg.counter(
        "photon_slo_burn_total",
        "SLO burn-rate alerts fired by the fleet tracker, by burn "
        "window (the tracker's fixed window names — a closed vocabulary)",
        labels=("window",))

    def listener(event) -> None:
        name, p = event.name, event.payload
        if name == "serving_request":
            serving_requests.inc()
            serving_rows.inc(float(p.get("batch", 1)))
        elif name == "retry_attempt":
            retry_attempts.labels(op=_op_family(p.get("op", "op"))).inc()
        elif name == "retry_exhausted":
            retry_exhausted.labels(op=_op_family(p.get("op", "op"))).inc()
        elif name == "retry_succeeded":
            retry_recovered.labels(op=_op_family(p.get("op", "op"))).inc()
        elif name == "stage_finished":
            stage_seconds.labels(stage=str(p.get("stage", ""))).observe(
                float(p.get("seconds", 0.0)))
        elif name == "span_finished":
            span_seconds.labels(span=str(p.get("span", ""))).observe(
                float(p.get("seconds", 0.0)))
        elif name == "divergence_detected":
            divergences.labels(
                coordinate=str(p.get("coordinate", ""))).inc()
        elif name == "coordinate_rollback":
            rollbacks.labels(coordinate=str(p.get("coordinate", ""))).inc()
        elif name == "coordinate_frozen":
            freezes.labels(coordinate=str(p.get("coordinate", ""))).inc()
        elif name == "model_loaded":
            reloads.inc()
        elif name == "model_reload_rejected":
            reload_rejects.inc()
        elif name == "model_activated":
            active_version.set(float(p.get("version") or 0))
        elif name == "training_started":
            training_runs.labels(driver=str(p.get("driver", ""))).inc()
        elif name == "supervisor_fault_detected":
            supervisor_faults.labels(
                reason=str(p.get("reason", "unknown"))).inc()
        elif name == "supervisor_restart":
            supervisor_restarts.inc()
        elif name == "supervisor_exhausted":
            supervisor_exhausted.inc()
        elif name == "quality_drift_detected":
            drift_events.inc()
        elif name == "canary_evaluated":
            canary_evals.labels(
                verdict=str(p.get("verdict", "pass"))).inc()
        elif name == "brownout_changed":
            direction = ("up" if float(p.get("level", 0))
                         > float(p.get("previous", 0)) else "down")
            brownout_changes.labels(direction=direction).inc()
        elif name == "slo_burn_alert":
            slo_burns.labels(window=str(p.get("window", ""))).inc()

    return listener


def bind(bus=None, registry: Optional[MetricsRegistry] = None,
         ) -> Callable[[], None]:
    """Subscribe the translating listener; returns an unbind callable.

    Idempotent per (bus, registry): a second bind of the same pair is a
    no-op returning a no-op unbinder, so the serving registry, the CLI
    telemetry session, and tests can all bind defensively.
    """
    if bus is None:
        from photon_ml_tpu.events import GLOBAL_BUS as bus
    registry = registry if registry is not None else default_registry()
    bound: list = getattr(bus, _BOUND_ATTR, None)
    if bound is None:
        bound = []
        setattr(bus, _BOUND_ATTR, bound)
    if any(r is registry for r in bound):
        return lambda: None
    bound.append(registry)
    unsubscribe = bus.subscribe(_make_listener(registry))

    def unbind() -> None:
        unsubscribe()
        try:
            bound.remove(registry)
        except ValueError:
            pass

    return unbind
