"""Thread-safe labeled metrics registry: Counter / Gauge / Histogram.

The reference's observability surface is a durable per-run log
(``util/PhotonLogger.scala``) read after the fact; a system serving live
traffic (serving/) while training at hardware speed (game/) needs the
complementary live surface: process-local metric families any thread can
update in nanoseconds and any scraper can snapshot consistently. This module
is that surface — deliberately zero-dependency (stdlib only; no prometheus
client in the image) and small enough to audit:

- a **family** is (name, type, help, label names); ``labels(**kv)`` resolves
  a **child** (one time series). A family created twice with the same
  signature is the same object (idempotent get-or-create, so instrumented
  modules can declare their families at import time without coordination);
  a conflicting re-declaration raises.
- **Counter** only goes up; **Gauge** sets/adds; **Histogram** has fixed
  upper bounds (cumulative, Prometheus-style) plus ``sum``/``count`` and
  bucket-interpolated quantile estimation. ``Histogram.time()`` is the
  sanctioned latency timer — serving code is forbidden (by
  ``tools/check_telemetry_hygiene.py``) from calling ``time.perf_counter``
  itself, so every latency measurement flows through one accounting
  chokepoint, mirroring how every sleep flows through ``resilience/retry.py``.
- the **default registry** is process-global (``default_registry()``); the
  Prometheus exposition (:mod:`photon_ml_tpu.telemetry.prometheus`) and the
  ``/metrics`` endpoint render it. Tests build private ``MetricsRegistry``
  instances for exact-count assertions.

Every update takes one small lock (registry lock for get-or-create, child
lock for the value); no allocation on the hot path after the first
``labels()`` resolution — cache the child in a local when instrumenting a
tight loop.
"""

from __future__ import annotations

import bisect
import math
import threading
import time
from typing import Iterator, Mapping, Optional, Sequence

#: Prometheus-idiomatic latency buckets (seconds): sub-millisecond serving
#: hits through multi-second compiles all land in a resolved bucket.
DEFAULT_LATENCY_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


class _Timer:
    """``with histogram.time() as t: ...`` — observes the elapsed seconds on
    exit and leaves them on ``t.seconds`` for callers that also need the
    value (e.g. a response payload)."""

    __slots__ = ("_hist", "_t0", "seconds", "_discarded")

    def __init__(self, hist: "Histogram"):
        self._hist = hist
        self.seconds = 0.0
        self._discarded = False

    def __enter__(self) -> "_Timer":
        self._t0 = time.perf_counter()
        return self

    def elapsed(self) -> float:
        """Running read of the open timer (for a mid-region log line) —
        the observation itself still happens once, at exit."""
        return time.perf_counter() - self._t0

    def discard(self) -> None:
        """Suppress the exit-time observation: the timed region turned out
        not to represent the measured population (e.g. a request shed by
        admission control must not pollute the latency distribution).
        ``seconds`` is still filled in at exit for the caller."""
        self._discarded = True

    def __exit__(self, *exc) -> None:
        self.seconds = time.perf_counter() - self._t0
        if not self._discarded:
            self._hist.observe(self.seconds)


class Counter:
    """Monotonically increasing value (one labeled time series)."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter can only go up, got inc({amount})")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Settable value (one labeled time series)."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket histogram with cumulative counts (Prometheus layout:
    ``le``-bounded buckets + implicit ``+Inf``), total ``sum``/``count``,
    and bucket-interpolated quantiles."""

    __slots__ = ("uppers", "_lock", "_counts", "_sum", "_count")

    def __init__(self, buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS):
        uppers = tuple(sorted(float(b) for b in buckets))
        if not uppers:
            raise ValueError("histogram needs at least one bucket bound")
        if len(set(uppers)) != len(uppers):
            raise ValueError(f"duplicate bucket bounds in {uppers}")
        self.uppers = uppers
        self._lock = threading.Lock()
        self._counts = [0] * (len(uppers) + 1)  # +1 = the +Inf bucket
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        i = bisect.bisect_left(self.uppers, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1

    def time(self) -> _Timer:
        return _Timer(self)

    def snapshot(self) -> tuple[list[int], float, int]:
        """(cumulative counts per bound + +Inf, sum, count) — one consistent
        read."""
        with self._lock:
            counts = list(self._counts)
        cum = []
        running = 0
        for c in counts:
            running += c
            cum.append(running)
        return cum, self._sum, self._count

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def quantile(self, q: float) -> float:
        cum, _, total = self.snapshot()
        return quantile_from_buckets(self.uppers, cum, q)


def quantile_from_buckets(uppers: Sequence[float],
                          cumulative_counts: Sequence[int],
                          q: float) -> float:
    """Estimate the ``q``-quantile from cumulative bucket counts
    (``cumulative_counts`` has one entry per upper bound plus a final
    ``+Inf`` entry). Linear interpolation within the crossing bucket — the
    same estimate Prometheus's ``histogram_quantile`` computes — with the
    first bucket's lower bound taken as 0 (these are latency histograms).
    Shared by :meth:`Histogram.quantile` and by consumers of *parsed*
    exposition text (``tools/bench_serving.py``)."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile must be in [0, 1], got {q}")
    total = cumulative_counts[-1]
    if total == 0:
        return math.nan
    rank = q * total
    prev_upper, prev_cum = 0.0, 0
    for upper, cum in zip(list(uppers) + [math.inf], cumulative_counts):
        if cum >= rank:
            if math.isinf(upper):
                # rank falls past the last finite bound: the bound itself is
                # the best (under-)estimate, as in Prometheus
                return prev_upper if prev_cum else float(uppers[-1])
            in_bucket = cum - prev_cum
            frac = 1.0 if in_bucket == 0 else (rank - prev_cum) / in_bucket
            return prev_upper + (upper - prev_upper) * frac
        prev_upper, prev_cum = upper, cum
    return float(uppers[-1])  # pragma: no cover - loop always crosses


_TYPES = ("counter", "gauge", "histogram")
_CHILD_CLS = {"counter": Counter, "gauge": Gauge}


class MetricFamily:
    """(name, type, help, label names) + the children keyed by label
    values. Zero-label families proxy updates straight through
    (``family.inc()`` == ``family.labels().inc()``)."""

    def __init__(self, name: str, type_: str, help_: str,
                 label_names: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS):
        if type_ not in _TYPES:
            raise ValueError(f"metric type must be one of {_TYPES}, "
                             f"got {type_!r}")
        self.name = name
        self.type = type_
        self.help = help_
        self.label_names = tuple(label_names)
        self.buckets = tuple(sorted(float(b) for b in buckets)) \
            if buckets else ()
        self._lock = threading.Lock()
        self._children: dict[tuple[str, ...], object] = {}
        if not self.label_names:
            # a label-free family IS its one series: materialize it so the
            # exposition shows it at zero from declaration (scrapers need
            # the zero to compute rates across the first increment)
            self._children[()] = self._make_child()

    def _make_child(self):
        if self.type == "histogram":
            return Histogram(self.buckets)
        return _CHILD_CLS[self.type]()

    def labels(self, **labels: str):
        got = tuple(sorted(labels))
        want = tuple(sorted(self.label_names))
        if got != want:
            raise ValueError(
                f"{self.name}: labels {sorted(labels)} != declared "
                f"{sorted(self.label_names)}")
        key = tuple(str(labels[k]) for k in self.label_names)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._make_child()
            return child

    def children(self) -> Iterator[tuple[tuple[str, ...], object]]:
        """Snapshot of (label values, child) in insertion order."""
        with self._lock:
            return iter(list(self._children.items()))

    # --- zero-label conveniences -----------------------------------------
    def inc(self, amount: float = 1.0) -> None:
        self.labels().inc(amount)

    def set(self, value: float) -> None:
        self.labels().set(value)

    def dec(self, amount: float = 1.0) -> None:
        self.labels().dec(amount)

    def observe(self, value: float) -> None:
        self.labels().observe(value)

    def time(self) -> _Timer:
        return self.labels().time()

    def quantile(self, q: float) -> float:
        return self.labels().quantile(q)

    @property
    def value(self) -> float:
        return self.labels().value

    @property
    def count(self) -> int:
        return self.labels().count


class MetricsRegistry:
    """Thread-safe family store. Get-or-create is idempotent on an exact
    signature match and loud on a conflict — two modules disagreeing on what
    ``photon_x_total`` means should fail at declaration, not at scrape."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: dict[str, MetricFamily] = {}

    def _get_or_create(self, name: str, type_: str, help_: str,
                       labels: Sequence[str],
                       buckets: Sequence[float]) -> MetricFamily:
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = MetricFamily(name, type_, help_, labels, buckets)
                self._families[name] = fam
                return fam
        if fam.type != type_ or fam.label_names != tuple(labels) or (
                type_ == "histogram" and fam.buckets != tuple(
                    sorted(float(b) for b in buckets))):
            raise ValueError(
                f"metric {name!r} already registered as {fam.type} with "
                f"labels {fam.label_names}; conflicting re-declaration "
                f"({type_}, {tuple(labels)})")
        return fam

    def counter(self, name: str, help_: str = "",
                labels: Sequence[str] = ()) -> MetricFamily:
        return self._get_or_create(name, "counter", help_, labels, ())

    def gauge(self, name: str, help_: str = "",
              labels: Sequence[str] = ()) -> MetricFamily:
        return self._get_or_create(name, "gauge", help_, labels, ())

    def histogram(self, name: str, help_: str = "",
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS,
                  ) -> MetricFamily:
        return self._get_or_create(name, "histogram", help_, labels, buckets)

    def get(self, name: str) -> Optional[MetricFamily]:
        with self._lock:
            return self._families.get(name)

    def collect(self) -> list[MetricFamily]:
        """Families in registration order (the exposition walks this)."""
        with self._lock:
            return list(self._families.values())


#: gauge families whose value is meaningful PER HOST (queue depth, RSS,
#: device memory): a fleet aggregate must fan them out under a ``process``
#: label instead of letting one host's value overwrite another's. The set
#: holds NAMES (not family objects) so marking works at declaration time
#: and the exposition layer can consult it without import cycles.
_HOST_OWNED_GAUGES: set[str] = set()


def mark_host_owned(name: str) -> None:
    """Declare gauge family ``name`` per-host-owned: multi-process renders
    tag its series with a ``process`` label (see ``prometheus.render``) so
    the fleet aggregate keeps one series per host. Counters and histograms
    never need this — they sum."""
    _HOST_OWNED_GAUGES.add(name)


def host_owned_gauges() -> frozenset:
    return frozenset(_HOST_OWNED_GAUGES)


#: the process-global registry — instrumented modules and the ``/metrics``
#: exposition meet here
_DEFAULT_REGISTRY = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return _DEFAULT_REGISTRY


def counter(name: str, help_: str = "",
            labels: Sequence[str] = ()) -> MetricFamily:
    """Get-or-create on the default registry (module-level shorthand)."""
    return _DEFAULT_REGISTRY.counter(name, help_, labels)


def gauge(name: str, help_: str = "",
          labels: Sequence[str] = ()) -> MetricFamily:
    return _DEFAULT_REGISTRY.gauge(name, help_, labels)


def histogram(name: str, help_: str = "", labels: Sequence[str] = (),
              buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS,
              ) -> MetricFamily:
    return _DEFAULT_REGISTRY.histogram(name, help_, labels, buckets)
