"""On-host telemetry history: a bounded ring of periodic metric snapshots.

Everything the observability plane exposes today is *instantaneous* — a
``/metrics`` scrape is a point in time, spans stream to a file, and when
a host dies the minutes that led up to it are gone. This module retains
them: a :class:`HistorySampler` takes a periodic snapshot of a **closed
subset** of the process registry (:data:`WATCHED_FAMILIES`), derives the
operator-facing signals (:data:`HISTORY_SERIES` — shed rate, hedge rate,
per-shard p50/p99, compile count, ...) and keeps the last ``capacity``
snapshots in a lock-disciplined ring. ``GET /history?series=&window=``
serves the ring on both the serving host (``serving/http.py``) and the
fleet router (``fleet/router.py``); the router folds per-host rings into
one fleet timeline with :func:`fold_history`, which reuses the exact
counter/gauge/histogram merge semantics of
:mod:`photon_ml_tpu.telemetry.aggregate` (counters and histogram buckets
sum, gauges first-snapshot-wins with host-owned families fanned out) —
the same semantics ``tools/metrics_fold.py`` applies offline.

Sampling is **injectable-tick**: :meth:`HistorySampler.sample` takes an
optional monotonic ``now`` exactly like
:meth:`~photon_ml_tpu.fleet.observe.SloBurnTracker.tick`, so tests drive
the clock instead of sleeping. The series vocabulary is closed and
lint-enforced (``tel-retained-vocab``): a history series name never
derives from a request, so the ring's cardinality is bounded by
construction no matter what traffic does.
"""

from __future__ import annotations

import json
import re
import threading
import time
from typing import Callable, Iterable, Optional, Sequence

from photon_ml_tpu.telemetry.metrics import (
    default_registry,
    quantile_from_buckets,
)
from photon_ml_tpu.telemetry.prometheus import (
    ParsedSnapshot,
    parse_text,
    render,
)

__all__ = [
    "HISTORY_SERIES",
    "WATCHED_FAMILIES",
    "HistorySampler",
    "derive_series",
    "fold_history",
    "history_payload",
    "subset_text",
]

#: metric families the history ring retains — a CLOSED set. Everything
#: else on the registry stays scrape-only; retaining a family costs ring
#: bytes on every host forever, so additions are a reviewed decision
#: (mirrors the leg-summary stage vocabulary in ``serving/http.py``).
WATCHED_FAMILIES = (
    "photon_compiles_total",
    "photon_connections_open",
    "photon_fleet_hedges_total",
    "photon_fleet_requests_total",
    "photon_fleet_shard_load",
    "photon_fleet_shard_p50_seconds",
    "photon_fleet_shard_p99_seconds",
    "photon_fleet_upstream_errors_total",
    "photon_resource_saturation",
    "photon_resource_utilization",
    "photon_serving_queue_depth",
    "photon_serving_request_latency_seconds",
    "photon_serving_requests_total",
    "photon_shed_total",
    "photon_slo_burn_total",
)

#: derived series a snapshot carries — the CLOSED query vocabulary for
#: ``GET /history?series=``. Unknown names are a 400, never an empty
#: timeline, so a typo'd dashboard fails loudly.
HISTORY_SERIES = (
    "compiles",
    "duty_cycle",
    "hedge_rate",
    "latency_p50",
    "latency_p99",
    "open_connections",
    "queue_depth",
    "requests",
    "resource_util",
    "shard_binding",
    "shard_load",
    "shard_p50",
    "shard_p99",
    "shed_rate",
    "slo_burn",
    "upstream_errors",
)

#: series names (and flight-recorder field names) must look like this —
#: runtime mirror of the ``tel-retained-vocab`` lint rule
SERIES_NAME_RE = re.compile(r"\A[a-z][a-z0-9_]{0,59}\Z")

DEFAULT_CAPACITY = 240

_SUFFIXES = ("_bucket", "_sum", "_count")


def _family_of(series_name: str) -> str:
    for suffix in _SUFFIXES:
        if series_name.endswith(suffix):
            return series_name[: -len(suffix)]
    return series_name


def subset_text(text: str,
                families: Sequence[str] = WATCHED_FAMILIES) -> str:
    """Exposition ``text`` reduced to the watched families (HELP/TYPE
    headers kept). The result round-trips through
    :func:`~photon_ml_tpu.telemetry.prometheus.parse_text` like any
    scrape, which is what lets :func:`fold_history` reuse the aggregate
    merge path unchanged."""
    keep = frozenset(families)
    lines = []
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            name = parts[2] if len(parts) > 2 else ""
        else:
            name = _family_of(line.split("{", 1)[0].split(None, 1)[0])
        if name in keep:
            lines.append(line)
    return "\n".join(lines) + "\n" if lines else ""


def _counter_sum(parsed: ParsedSnapshot, name: str) -> float:
    return float(sum(v for _labels, v in parsed.get(name, ())))


def _labeled_gauge(parsed: ParsedSnapshot, name: str,
                   label: str) -> dict[str, float]:
    out: dict[str, float] = {}
    for labels, value in parsed.get(name, ()):
        if label in labels:
            out[labels[label]] = float(value)
    return out


def _labeled_max(parsed: ParsedSnapshot, name: str,
                 label: str) -> dict[str, float]:
    """Per-``label`` maxima of gauge ``name`` — on folded text a
    host-owned gauge fans out per host, and the capacity question is
    "how saturated is the WORST instance", never the average."""
    out: dict[str, float] = {}
    for labels, value in parsed.get(name, ()):
        key = labels.get(label)
        if key is not None and float(value) > out.get(key, float("-inf")):
            out[key] = float(value)
    return out


def _shard_binding(parsed: ParsedSnapshot) -> dict[str, str]:
    """Per-shard binding resource: the resource with the highest
    utilization among this shard's fanned-out
    ``photon_resource_utilization`` series (ties break to the
    lexicographically first resource — deterministic, like every fold).
    Host-tier snapshots carry no ``shard`` label, so the dict is empty
    there and populated exactly where it means something: the folded
    fleet timeline."""
    best: dict[str, tuple[float, str]] = {}
    for labels, value in parsed.get("photon_resource_utilization", ()):
        shard = labels.get("shard")
        resource = labels.get("resource")
        if shard is None or resource is None:
            continue
        cur = best.get(shard)
        value = float(value)
        if cur is None or value > cur[0] \
                or (value == cur[0] and resource < cur[1]):
            best[shard] = (value, resource)
    return {shard: resource for shard, (_v, resource) in best.items()}


def _hist_cumulative(parsed: ParsedSnapshot,
                     name: str) -> tuple[list[float], list[float]]:
    """Summed-over-labels cumulative bucket counts for histogram
    ``name`` as ``(finite_uppers, cumulative_counts_incl_inf)``."""
    by_upper: dict[float, float] = {}
    for labels, value in parsed.get(name + "_bucket", ()):
        le = labels.get("le", "+Inf")
        upper = float("inf") if le == "+Inf" else float(le)
        by_upper[upper] = by_upper.get(upper, 0.0) + float(value)
    uppers = sorted(u for u in by_upper if u != float("inf"))
    cum = [by_upper[u] for u in uppers]
    cum.append(by_upper.get(float("inf"), cum[-1] if cum else 0.0))
    return uppers, cum


def _window_quantile(prev: Optional[ParsedSnapshot], cur: ParsedSnapshot,
                     name: str, q: float) -> Optional[float]:
    """Quantile of the observations that arrived BETWEEN two snapshots
    (bucket-count deltas), so the timeline shows the latency of each
    interval rather than a since-boot average. ``None`` when the
    interval saw no observations."""
    uppers, cum = _hist_cumulative(cur, name)
    if prev is not None:
        p_uppers, p_cum = _hist_cumulative(prev, name)
        if p_uppers == uppers:
            cum = [max(0.0, c - p) for c, p in zip(cum, p_cum)]
    if not uppers or cum[-1] <= 0:
        return None
    return float(quantile_from_buckets(uppers, cum, q))


def _delta(prev: Optional[ParsedSnapshot], cur: ParsedSnapshot,
           name: str) -> float:
    base = _counter_sum(prev, name) if prev is not None else 0.0
    return max(0.0, _counter_sum(cur, name) - base)


def derive_series(prev: Optional[ParsedSnapshot], cur: ParsedSnapshot,
                  dt_s: float) -> dict:
    """The :data:`HISTORY_SERIES` values for one interval, computed from
    two parsed watched-subset snapshots. This is the ONE derivation path
    — the router's fleet timeline calls it on *folded* text, so a
    derived fleet signal is by construction the same function of the
    folded families that each host applies to its own."""
    dt = max(float(dt_s), 1e-9)
    requests = _delta(prev, cur, "photon_serving_requests_total")
    shed = _delta(prev, cur, "photon_shed_total")
    hedges = _delta(prev, cur, "photon_fleet_hedges_total")
    fleet_requests = _delta(prev, cur, "photon_fleet_requests_total")
    return {
        "compiles": _counter_sum(cur, "photon_compiles_total"),
        # device-seconds per wall second: on host text this is one duty
        # cycle in [0, 1]; on folded text the fanned-out per-host gauges
        # SUM, so the fleet reads in device-seconds/second (N hosts
        # flat-out = N.0) — capacity, not a percentage
        "duty_cycle": float(sum(
            v for labels, v in cur.get("photon_resource_utilization", ())
            if labels.get("resource") == "device")),
        "hedge_rate": hedges / max(fleet_requests, 1.0),
        "latency_p50": _window_quantile(
            prev, cur, "photon_serving_request_latency_seconds", 0.50),
        "latency_p99": _window_quantile(
            prev, cur, "photon_serving_request_latency_seconds", 0.99),
        "open_connections": float(sum(
            v for _l, v in cur.get("photon_connections_open", ()))),
        "queue_depth": float(sum(
            v for _l, v in cur.get("photon_serving_queue_depth", ()))),
        "requests": requests,
        # worst-instance utilization per resource — the binding axis of
        # the USE plane (max across hosts on folded text: the capacity
        # question is about the most constrained instance)
        "resource_util": _labeled_max(
            cur, "photon_resource_utilization", "resource"),
        # shard → its most-utilized resource, readable only on folded
        # text (host-owned gauges carry shard labels there); what the
        # hot-shard advisor stamps on detections
        "shard_binding": _shard_binding(cur),
        "shard_load": _labeled_gauge(
            cur, "photon_fleet_shard_load", "shard"),
        "shard_p50": _labeled_gauge(
            cur, "photon_fleet_shard_p50_seconds", "shard"),
        "shard_p99": _labeled_gauge(
            cur, "photon_fleet_shard_p99_seconds", "shard"),
        "shed_rate": shed / max(shed + requests, 1.0),
        "slo_burn": _delta(prev, cur, "photon_slo_burn_total"),
        "upstream_errors": _delta(
            prev, cur, "photon_fleet_upstream_errors_total"),
    }


def history_payload(snapshots: Sequence[dict], *, source: str,
                    capacity: int, window: int = 0,
                    series: Iterable[str] = (),
                    include_prom: bool = False) -> dict:
    """The ``GET /history`` response body: the last ``window`` snapshots
    (0 = all retained), each reduced to the requested ``series`` (empty
    = all). ``include_prom`` (the ``?raw=1`` form) ships each snapshot's
    watched-subset exposition text too — what the router's fold
    consumes. Raises :class:`ValueError` on a name outside the closed
    vocabulary — the handlers map that to a 400."""
    wanted = tuple(series)
    for name in wanted:
        if name not in HISTORY_SERIES:
            raise ValueError(
                f"unknown history series {name!r}: the vocabulary is "
                f"closed ({', '.join(HISTORY_SERIES)})")
    snaps = list(snapshots)
    if window:
        if window < 0:
            raise ValueError(f"window must be >= 0, got {window}")
        snaps = snaps[-window:]
    out = []
    for snap in snaps:
        values = snap["series"]
        if wanted:
            values = {k: values[k] for k in wanted}
        row = {"tick": snap["tick"], "ts": snap["ts"], "series": values}
        if include_prom:
            row["prom"] = snap["prom"]
        out.append(row)
    return {"source": source, "capacity": capacity,
            "series": list(wanted or HISTORY_SERIES), "snapshots": out}


class HistorySampler:
    """Bounded ring of watched-subset snapshots over one registry.

    ``sample(now=None)`` is the injectable tick: it renders the watched
    subset, derives the interval's :data:`HISTORY_SERIES`, appends one
    snapshot and notifies listeners — all under one lock discipline
    (ring mutation under ``_lock``; the registry read itself is
    internally consistent per family). ``start(period_s)`` runs the
    tick on a daemon thread for production; tests call ``sample``
    directly with a driven clock and never sleep.
    """

    def __init__(self, *, registry=None, capacity: int = DEFAULT_CAPACITY,
                 source: str = "host",
                 pre_sample: Optional[Callable[[], None]] = None):
        if capacity <= 0:
            raise ValueError(f"capacity must be > 0, got {capacity}")
        self._registry = registry if registry is not None \
            else default_registry()
        self._capacity = int(capacity)
        self._source = source
        self._pre_sample = pre_sample
        self._lock = threading.Lock()
        self._ring: list[dict] = []  # guarded-by: _lock
        self._listeners: list[Callable[[dict], None]] = []  # guarded-by: _lock
        self._prev_parsed: Optional[ParsedSnapshot] = None  # guarded-by: _lock
        self._prev_ts: Optional[float] = None  # guarded-by: _lock
        self._tick = 0  # guarded-by: _lock
        self._stop = threading.Event()  # guarded-by: caller
        self._thread: Optional[threading.Thread] = None  # guarded-by: caller

    @property
    def capacity(self) -> int:
        return self._capacity

    @property
    def source(self) -> str:
        return self._source

    def add_listener(self, fn: Callable[[dict], None]) -> Callable[[], None]:
        """Call ``fn(snapshot)`` after every sample (advisor ticks, the
        flight recorder's history lane, watchdog pets). Listener
        exceptions are swallowed like the event bus's — observation
        never takes down sampling."""
        with self._lock:
            self._listeners.append(fn)

        def _remove() -> None:
            with self._lock:
                if fn in self._listeners:
                    self._listeners.remove(fn)
        return _remove

    def sample(self, now: Optional[float] = None) -> dict:
        now = time.monotonic() if now is None else float(now)
        if self._pre_sample is not None:
            try:
                self._pre_sample()
            except Exception:
                pass  # heat refresh is best-effort; the snapshot still lands
        prom = subset_text(render(self._registry))
        parsed = parse_text(prom)
        with self._lock:
            dt = (now - self._prev_ts) if self._prev_ts is not None else 0.0
            self._tick += 1
            snap = {
                "tick": self._tick,
                "ts": now,
                "series": derive_series(self._prev_parsed, parsed, dt),
                "prom": prom,
            }
            self._prev_parsed = parsed
            self._prev_ts = now
            self._ring.append(snap)
            if len(self._ring) > self._capacity:
                del self._ring[: len(self._ring) - self._capacity]
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(snap)
            except Exception:
                pass
        return snap

    def snapshots(self, window: int = 0) -> list[dict]:
        with self._lock:
            snaps = list(self._ring)
        return snaps[-window:] if window else snaps

    def payload(self, *, window: int = 0, series: Iterable[str] = (),
                include_prom: bool = False) -> dict:
        return history_payload(self.snapshots(), source=self._source,
                               capacity=self._capacity, window=window,
                               series=series, include_prom=include_prom)

    def payload_json(self, *, window: int = 0,
                     series: Iterable[str] = (),
                     include_prom: bool = False) -> bytes:
        return json.dumps(
            self.payload(window=window, series=series,
                         include_prom=include_prom),
            sort_keys=True).encode("utf-8")

    def start(self, period_s: float) -> None:
        """Tick every ``period_s`` on a daemon thread (production mode —
        the serving mains arm this; tests drive :meth:`sample`)."""
        if period_s <= 0 or self._thread is not None:
            return
        self._stop.clear()

        def _loop() -> None:
            while not self._stop.wait(period_s):
                self.sample()
        self._thread = threading.Thread(
            target=_loop, name="photon-history-sampler", daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5.0)


def fold_history(fold_texts: Callable[[str, Sequence[tuple]], str],
                 router_snaps: Sequence[dict],
                 host_snaps: Sequence[tuple[int, int, Sequence[dict]]],
                 ) -> list[dict]:
    """Fold per-host history rings into one fleet timeline.

    ``fold_texts(router_text, [(shard, replica, text), ...])`` supplies
    the merge — the router passes
    :func:`photon_ml_tpu.fleet.observe.fold_fleet_snapshots`, i.e. the
    EXACT aggregate semantics ``tools/metrics_fold.py`` applies offline
    (injected as a callable so telemetry never imports fleet). Rings
    tick on independent clocks, so rows align by distance from the
    newest snapshot; the folded timeline is as long as the shortest
    ring, and each row re-derives :data:`HISTORY_SERIES` from the
    folded text with :func:`derive_series` — fleet counters sum, fleet
    quantiles come from summed buckets, never from averaged host
    quantiles."""
    rows = len(router_snaps)
    for _shard, _replica, snaps in host_snaps:
        rows = min(rows, len(snaps))
    folded: list[dict] = []
    prev_parsed: Optional[ParsedSnapshot] = None
    prev_ts: Optional[float] = None
    for offset in range(rows, 0, -1):
        router_snap = router_snaps[-offset]
        members = [(shard, replica, snaps[-offset]["prom"])
                   for shard, replica, snaps in host_snaps]
        text = fold_texts(router_snap["prom"], members)
        parsed = parse_text(text)
        ts = float(router_snap["ts"])
        dt = (ts - prev_ts) if prev_ts is not None else 0.0
        folded.append({
            "tick": router_snap["tick"],
            "ts": ts,
            "series": derive_series(prev_parsed, parsed, dt),
            "prom": text,
        })
        prev_parsed, prev_ts = parsed, ts
    return folded
