"""Black-box flight recorder: the last N telemetry records, dumped on death.

A serving host that crashes takes its trace file buffer, its metrics
registry and its event stream down with it — the scrape-based plane only
ever shows the minutes a process *survived*. The
:class:`FlightRecorder` is the aircraft answer: a **preallocated** ring
of the most recent span records (tapped off
:class:`~photon_ml_tpu.telemetry.tracing.Tracer` via ``add_tap``, so it
fills even on hosts that never configure ``trace.jsonl``), event-bus
events, log lines and history snapshots, written ATOMICALLY to
``flight-<ts>.jsonl`` (tmp + ``os.replace`` — a reader can never observe
a partial dump) on four trigger classes:

- **fault-site trip** — a ``fault_injected`` bus event
  (:mod:`photon_ml_tpu.resilience.faults`);
- **unhandled exception** — chained ``sys.excepthook`` /
  ``threading.excepthook``;
- **SIGTERM** — chained signal handler installed by the serving/fleet
  mains (what the supervisor's terminate-then-kill escalation sends
  first, so a supervised worker's black box survives its own eviction);
- **watchdog stall** — :class:`Watchdog` (in-process liveness, petted by
  the history sampler) and the fleet supervisor's heartbeat-stall fault
  (``supervisor_fault_detected`` with ``reason="stall"``).

``tools/postmortem.py`` renders a dump into a deterministic incident
report. Record *kinds* and manual ``note()`` field names come from a
closed vocabulary (lint rule ``tel-retained-vocab``): like span
attributes, the black box is indexed storage — request payloads don't
belong in it, request *ids* (the sanctioned join key) do.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import sys
import threading
import time
import traceback
from typing import Callable, Optional

from photon_ml_tpu.telemetry.history import SERIES_NAME_RE

__all__ = [
    "DUMP_REASONS",
    "RECORD_KINDS",
    "FlightRecorder",
    "Watchdog",
]

#: why a dump happened — closed; the postmortem keys its headline off it
DUMP_REASONS = ("fault_site", "unhandled_exception", "sigterm",
                "watchdog_stall", "manual")

#: what a ring slot can hold — closed; ``tools/postmortem.py`` renders
#: each kind into its own report section
RECORD_KINDS = ("span", "event", "log", "history", "note")

#: default ring capacity — at one span + one event per request this is
#: roughly the last ~250 requests plus the interleaved history ticks
DEFAULT_CAPACITY = 512

#: don't let a fault storm turn into a dump storm: repeat triggers of
#: the SAME reason inside this window coalesce into the first dump
DEFAULT_COOLDOWN_S = 5.0

_SCHEMA = 1


class _FlightLogHandler(logging.Handler):
    def __init__(self, recorder: "FlightRecorder"):
        super().__init__()
        self._recorder = recorder

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self._recorder.record_log(
                self.format(record), level=record.levelname,
                logger=record.name)
        except Exception:
            pass  # the black box never takes down the thing it records


class FlightRecorder:
    """Crash-safe ring of recent telemetry + atomic dump-on-trigger.

    The ring is a fixed-size preallocated list written modulo capacity
    under one lock — recording is O(1) with zero allocation growth, so
    it can sit on the request path's span tap indefinitely. ``dump()``
    snapshots the ring under the lock, then renders and publishes the
    file OUTSIDE it (tmp + ``os.replace``), so a dump mid-traffic never
    stalls recorders for the I/O.
    """

    def __init__(self, dump_dir: str, *, capacity: int = DEFAULT_CAPACITY,
                 source: str = "host",
                 context_fn: Optional[Callable[[], dict]] = None,
                 tracer=None,
                 cooldown_s: float = DEFAULT_COOLDOWN_S):
        if capacity <= 0:
            raise ValueError(f"capacity must be > 0, got {capacity}")
        self._dump_dir = dump_dir
        self._capacity = int(capacity)
        self._source = source
        self._context_fn = context_fn
        self._tracer = tracer
        self._cooldown_s = float(cooldown_s)
        self._ring: list = [None] * self._capacity
        self._seq = 0
        self._lock = threading.Lock()
        self._last_dump: dict[str, float] = {}
        self._uninstalls: list[Callable[[], None]] = []
        self._prev_excepthook = None
        self._prev_threading_hook = None
        self._prev_sigterm = None

    @property
    def capacity(self) -> int:
        return self._capacity

    @property
    def seq(self) -> int:
        with self._lock:
            return self._seq

    # ------------------------------------------------------------------
    # recording lanes
    # ------------------------------------------------------------------

    def _append(self, kind: str, payload: dict) -> None:
        if kind not in RECORD_KINDS:
            raise ValueError(
                f"unknown flight record kind {kind!r}: the vocabulary is "
                f"closed ({', '.join(RECORD_KINDS)})")
        with self._lock:
            self._seq += 1
            self._ring[(self._seq - 1) % self._capacity] = {
                "seq": self._seq, "kind": kind, **payload}

    def record_span(self, record: dict) -> None:
        """One completed span/annotation record (the tracer tap lane)."""
        self._append("span", {"record": dict(record)})

    def record_event(self, name: str, payload: dict,
                     ts: Optional[float] = None) -> None:
        """One event-bus event (the bus subscription lane)."""
        self._append("event", {"event": name, "payload": dict(payload),
                               "ts": ts})

    def record_log(self, line: str, *, level: str = "INFO",
                   logger: str = "") -> None:
        self._append("log", {"line": str(line), "level": level,
                             "logger": logger})

    def record_history(self, snapshot: dict) -> None:
        """One history-ring snapshot (exposition text dropped — the ring
        keeps the derived series, the live sampler keeps the text)."""
        self._append("history", {"tick": snapshot.get("tick"),
                                 "ts": snapshot.get("ts"),
                                 "series": snapshot.get("series", {})})

    def note(self, name: str, **fields) -> None:
        """Manual breadcrumb. ``name`` and field names must be literal
        members of the closed snake_case vocabulary (enforced here and
        by ``tel-retained-vocab``); values may carry the request id —
        the sanctioned join key — but never raw payload fields."""
        for key in (name, *fields):
            if not SERIES_NAME_RE.match(key):
                raise ValueError(
                    f"flight note name/field {key!r} outside the closed "
                    f"vocabulary (want snake_case, lint "
                    f"tel-retained-vocab)")
        self._append("note", {"note": name, "fields": fields})

    def records(self) -> list[dict]:
        """The retained records, oldest first (a copy)."""
        with self._lock:
            return self._snapshot_locked()

    def _snapshot_locked(self) -> list[dict]:
        if self._seq <= self._capacity:
            return [r for r in self._ring[: self._seq] if r is not None]
        head = self._seq % self._capacity
        return [r for r in self._ring[head:] + self._ring[:head]
                if r is not None]

    # ------------------------------------------------------------------
    # dump
    # ------------------------------------------------------------------

    def dump(self, reason: str, *, ts: Optional[float] = None,
             force: bool = False) -> Optional[str]:
        """Publish the ring as ``flight-<ts>.jsonl`` in ``dump_dir``.

        Atomic by construction: the full document is written to a
        ``.tmp`` sibling, flushed + fsynced, then ``os.replace``d into
        place — a concurrent reader sees the complete dump or no file,
        never a partial one. Returns the path, or ``None`` when a
        repeat trigger of the same reason lands inside the cooldown.
        """
        if reason not in DUMP_REASONS:
            raise ValueError(
                f"unknown dump reason {reason!r}: the vocabulary is "
                f"closed ({', '.join(DUMP_REASONS)})")
        mono = time.monotonic()
        with self._lock:
            last = self._last_dump.get(reason)
            if (not force and last is not None
                    and mono - last < self._cooldown_s):
                return None
            self._last_dump[reason] = mono
            records = self._snapshot_locked()
            seq = self._seq
        wall = time.time() if ts is None else float(ts)
        header = {
            "kind": "flight_header",
            "schema": _SCHEMA,
            "reason": reason,
            "source": self._source,
            "ts": wall,
            "seq": seq,
            "capacity": self._capacity,
            "retained": len(records),
            "active_span_ids": (list(self._tracer.open_span_ids())
                                if self._tracer is not None else []),
        }
        if self._context_fn is not None:
            try:
                header["context"] = self._context_fn()
            except Exception as e:
                header["context_error"] = repr(e)
        os.makedirs(self._dump_dir, exist_ok=True)
        path = os.path.join(self._dump_dir, f"flight-{int(wall * 1000)}.jsonl")
        k = 0
        while os.path.exists(path):
            k += 1
            path = os.path.join(
                self._dump_dir, f"flight-{int(wall * 1000)}-{k}.jsonl")
        lines = [json.dumps(header, sort_keys=True, default=str)]
        lines.extend(json.dumps(r, sort_keys=True, default=str)
                     for r in records)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write("\n".join(lines) + "\n")
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        return path

    # ------------------------------------------------------------------
    # trigger wiring
    # ------------------------------------------------------------------

    def _on_event(self, event) -> None:
        payload = dict(event.payload)
        self.record_event(event.name, payload,
                          ts=getattr(event, "timestamp", None))
        if event.name == "fault_injected":
            self.dump("fault_site")
        elif (event.name == "supervisor_fault_detected"
                and payload.get("reason") == "stall"):
            self.dump("watchdog_stall")

    def install(self, *, bus=None, tracer=None, sampler=None,
                logger: Optional[logging.Logger] = None
                ) -> Callable[[], None]:
        """Wire the recording lanes: tracer tap, bus subscription (which
        also arms the fault-site and supervisor-stall dump triggers),
        history-sampler listener, log handler. Returns an uninstall
        callable; :meth:`close` calls it too."""
        uninstalls: list[Callable[[], None]] = []
        if tracer is not None:
            self._tracer = tracer
            uninstalls.append(tracer.add_tap(self.record_span))
        if bus is not None:
            uninstalls.append(bus.subscribe(self._on_event))
        if sampler is not None:
            uninstalls.append(sampler.add_listener(self.record_history))
        if logger is not None:
            handler = _FlightLogHandler(self)
            logger.addHandler(handler)
            uninstalls.append(lambda: logger.removeHandler(handler))
        self._uninstalls.extend(uninstalls)

        def _uninstall() -> None:
            for fn in uninstalls:
                try:
                    fn()
                except Exception:
                    pass
        return _uninstall

    def install_excepthook(self) -> None:
        """Dump on any unhandled exception (main thread or worker), then
        chain to the previous hooks — the crash still crashes."""
        if self._prev_excepthook is not None:
            return
        self._prev_excepthook = sys.excepthook
        self._prev_threading_hook = threading.excepthook

        def _hook(exc_type, exc, tb):
            self._record_crash(exc_type, exc, tb)
            self.dump("unhandled_exception")
            self._prev_excepthook(exc_type, exc, tb)

        def _thread_hook(args):
            self._record_crash(args.exc_type, args.exc_value,
                               args.exc_traceback, thread=args.thread)
            self.dump("unhandled_exception")
            self._prev_threading_hook(args)

        sys.excepthook = _hook
        threading.excepthook = _thread_hook

    def _record_crash(self, exc_type, exc, tb, thread=None) -> None:
        try:
            frames = traceback.format_exception(exc_type, exc, tb)
            self._append("note", {
                "note": "unhandled_exception",
                "fields": {
                    "error": repr(exc),
                    "thread": getattr(thread, "name", "main"),
                    "trace": "".join(frames)[-4000:],
                }})
        except Exception:
            pass

    def install_sigterm(self) -> bool:
        """Dump on SIGTERM, then chain to the previous handler (or exit
        with the conventional 143 when the previous disposition was the
        default). Signal handlers only install from the main thread —
        returns False (recorder still works, trigger unarmed) elsewhere.
        """
        def _handler(signum, frame):
            self.dump("sigterm")
            prev = self._prev_sigterm
            if callable(prev):
                prev(signum, frame)
            elif prev == signal.SIG_DFL:
                raise SystemExit(128 + signum)

        try:
            self._prev_sigterm = signal.signal(signal.SIGTERM, _handler)
        except ValueError:
            return False
        return True

    def uninstall_hooks(self) -> None:
        if self._prev_excepthook is not None:
            sys.excepthook = self._prev_excepthook
            threading.excepthook = self._prev_threading_hook
            self._prev_excepthook = None
            self._prev_threading_hook = None
        if self._prev_sigterm is not None:
            try:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
            except ValueError:
                pass
            self._prev_sigterm = None

    def close(self) -> None:
        for fn in self._uninstalls:
            try:
                fn()
            except Exception:
                pass
        self._uninstalls.clear()
        self.uninstall_hooks()


class Watchdog:
    """In-process liveness: dump ``watchdog_stall`` when pets stop.

    ``pet(now=None)`` is called by whatever proves the process is making
    progress (the serving mains pet from the history sampler's
    listener); ``check(now=None)`` dumps — ONCE per stall episode,
    edge-triggered like the SLO burn latch — when the last pet is older
    than ``timeout_s``. Both take an injectable monotonic ``now`` so
    tests drive the clock; ``start(period_s)`` runs ``check`` on a
    daemon thread in production.
    """

    def __init__(self, recorder: FlightRecorder, *, timeout_s: float):
        if timeout_s <= 0:
            raise ValueError(f"timeout_s must be > 0, got {timeout_s}")
        self._recorder = recorder
        self._timeout_s = float(timeout_s)
        self._lock = threading.Lock()
        self._last_pet = time.monotonic()  # guarded-by: _lock
        self._stalled = False  # guarded-by: _lock
        self._stop = threading.Event()  # guarded-by: caller
        self._thread: Optional[threading.Thread] = None  # guarded-by: caller

    def pet(self, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else float(now)
        with self._lock:
            self._last_pet = now
            self._stalled = False

    def check(self, now: Optional[float] = None) -> Optional[str]:
        now = time.monotonic() if now is None else float(now)
        with self._lock:
            stale = now - self._last_pet >= self._timeout_s
            if not stale or self._stalled:
                return None
            self._stalled = True  # latch: one dump per episode
            age = now - self._last_pet
        self._recorder.note("watchdog_stall", pet_age_s=round(age, 3))
        return self._recorder.dump("watchdog_stall")

    def start(self, period_s: float) -> None:
        if period_s <= 0 or self._thread is not None:
            return
        self._stop.clear()

        def _loop() -> None:
            while not self._stop.wait(period_s):
                self.check()
        self._thread = threading.Thread(
            target=_loop, name="photon-flight-watchdog", daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5.0)
