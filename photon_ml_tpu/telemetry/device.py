"""Periodic host/device resource sampler → gauges.

"What is this run doing right now" includes "what is it holding": host RSS
(the Avro read + host-mirror footprint) and per-device accelerator memory
(the HBM the design tensors and score decomposition pin — the memory cliff
``CoordinateDescent`` guards against). The sampler polls both on a
background thread at a configurable interval and publishes gauges; it is
OFF by default and gated behind the drivers' ``--telemetry-poll-s`` flag
(0 disables) because ``device.memory_stats()`` can synchronize with the
backend — never put it on a request path.

The wait uses ``threading.Event.wait`` (not ``time.sleep``) so shutdown is
immediate and the resilience hygiene rule (all sleeps live in
``resilience/retry.py``) holds. A failed sample logs once at debug level
and keeps polling — a flaky backend stat must never kill telemetry, let
alone the run (same contract as event listeners).
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Optional

from photon_ml_tpu.telemetry.metrics import (
    MetricsRegistry,
    default_registry,
    mark_host_owned,
)

logger = logging.getLogger(__name__)

# per-host-owned gauges: a fleet aggregate must keep one series per
# process (tagged at render time), not let the chief's RSS overwrite a
# worker's
mark_host_owned("photon_host_rss_bytes")
mark_host_owned("photon_device_bytes_in_use")
mark_host_owned("photon_device_bytes_limit")


def host_rss_bytes() -> Optional[int]:
    """Resident set size of this process, or None when unreadable."""
    try:
        with open("/proc/self/status", encoding="ascii") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    try:
        import resource

        # ru_maxrss is KiB on Linux, bytes on macOS; Linux is the target
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:
        return None


class DeviceStatsSampler:
    """Background gauge poller; ``start()``/``close()`` lifecycle."""

    def __init__(self, interval_s: float,
                 registry: Optional[MetricsRegistry] = None):
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        self.interval_s = float(interval_s)
        reg = registry if registry is not None else default_registry()
        self._rss = reg.gauge("photon_host_rss_bytes",
                              "Process resident set size")
        self._in_use = reg.gauge("photon_device_bytes_in_use",
                                 "Accelerator memory in use, per device",
                                 labels=("device",))
        self._limit = reg.gauge("photon_device_bytes_limit",
                                "Accelerator memory limit, per device",
                                labels=("device",))
        self._samples = reg.counter("photon_device_samples_total",
                                    "Completed sampler polls")
        self._stop = threading.Event()
        #: start/close are operator-lifecycle calls from one control thread
        self._thread: Optional[threading.Thread] = None  # guarded-by: caller

    def sample_once(self) -> None:
        """One poll (also callable synchronously from tests)."""
        rss = host_rss_bytes()
        if rss is not None:
            self._rss.set(rss)
        try:
            import jax

            for d in jax.devices():
                stats = d.memory_stats()
                if not stats:
                    continue  # backend doesn't report (e.g. plain CPU)
                if "bytes_in_use" in stats:
                    self._in_use.labels(device=str(d.id)).set(
                        stats["bytes_in_use"])
                if "bytes_limit" in stats:
                    self._limit.labels(device=str(d.id)).set(
                        stats["bytes_limit"])
        except Exception:
            logger.debug("device memory stats unavailable", exc_info=True)
        self._samples.inc()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:  # sampler must never die mid-run
                logger.debug("telemetry sample failed", exc_info=True)

    def start(self) -> "DeviceStatsSampler":
        self.sample_once()  # one immediate sample: gauges exist right away
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name="photon-telemetry-sampler")
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
