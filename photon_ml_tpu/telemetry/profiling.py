"""Per-function jit profiling: compile/execute accounting + XLA cost analysis.

The span tree (tracing.py) answers "which STAGE took the wall-clock"; this
module answers the layer below it — for each hot jitted program, how much of
the wall went to *compilation* versus *execution*, what the compiled program
costs per run (FLOPs and bytes accessed, from XLA's own cost model), and
whether the program keeps recompiling (the training analog of serving's
zero-recompile contract: the compile counter must go FLAT after the first
coordinate-descent sweep).

:func:`profile_jit` is the one wrapper. It replaces a ``jax.jit`` call site::

    train = profiling.profile_jit(train_fn, "game.fixed_effect")
    result = train(data, w0, lam)       # same call surface as jit

and drives the jit through JAX's AOT API instead of the opaque dispatch
cache: each distinct abstract signature (pytree structure + leaf
shape/dtype/sharding + static values) is lowered and compiled ONCE, timed,
cost-analyzed, and held in the wrapper's own executable cache. Every later
call with that signature dispatches the cached executable directly. The
accounting lands in the process-global metrics registry, so ``metrics.prom``
and ``GET /metrics`` expose it with zero extra plumbing:

- ``photon_compiles_total{fn}`` / ``photon_compile_seconds_total{fn}`` —
  lower+compile events and their wall seconds, per wrapped function;
- ``photon_execute_latency_seconds{fn}`` — per-call latency histogram.
  NOTE async dispatch: jax returns before the device finishes, so by
  default this measures DISPATCH latency (the honest hot-path number —
  blocking here would serialize the coordinate-descent pipeline);
  ``block=True`` makes the timer wait for the result, for call sites that
  want device wall time;
- ``photon_flops_total{fn}`` / ``photon_bytes_accessed_total{fn}`` — XLA
  ``Compiled.cost_analysis()`` per-execution estimates, accumulated per
  call, so ``rate(photon_flops_total)`` is an achieved-FLOPs/s estimate;
- ``photon_peak_memory_bytes{fn}`` — ``Compiled.memory_analysis()``
  (arguments + outputs + temporaries) of the heaviest program compiled
  under the name.

Functions called UNDER A TRACE (a profiled function invoked inside another
jit, vmap or grad — e.g. the per-bucket solve inside the fused sweep
program) transparently fall back to the wrapped jit and inline: no separate
compile happens, so none is counted.

Two registry hooks complement the wrapper:

- :func:`record_compile` — for call sites that own their jit machinery
  (the serving engine counts traces from inside the traced body, where no
  wall-clock is measurable) but must share the ``photon_compiles_total``
  name family;
- :func:`install_xla_hooks` — a ``jax.monitoring`` listener folding EVERY
  XLA compile in the process (wrapped or not) into
  ``photon_xla_compiles_total{phase}`` /
  ``photon_xla_compile_seconds_total{phase}`` (phase: ``trace`` /
  ``lower`` / ``backend``), so the compile-vs-execute split in
  ``tools/perf_report.py`` never under-reports un-wrapped jits.
  Installed automatically with the first wrapper.
"""

from __future__ import annotations

import inspect
import threading
import time
from typing import Callable, Optional, Sequence

import jax

from photon_ml_tpu.telemetry import metrics as _metrics
from photon_ml_tpu.telemetry.metrics import MetricsRegistry

__all__ = [
    "ProfiledFunction",
    "profile_jit",
    "record_compile",
    "total_compiles",
    "install_xla_hooks",
]


def _families(registry: Optional[MetricsRegistry] = None):
    """The profiling metric families on ``registry`` (default registry when
    None) — get-or-create is idempotent, so every wrapper shares them."""
    reg = registry if registry is not None else _metrics.default_registry()
    return {
        "compiles": reg.counter(
            "photon_compiles_total",
            "XLA lower+compile events per profiled jit function (flat "
            "after warmup/first sweep = the zero-recompile contract)",
            labels=("fn",)),
        "compile_seconds": reg.counter(
            "photon_compile_seconds_total",
            "Wall seconds spent lowering+compiling, per profiled jit "
            "function", labels=("fn",)),
        "execute": reg.histogram(
            "photon_execute_latency_seconds",
            "Per-call latency of the compiled executable (dispatch-side "
            "unless the wrapper blocks; jax dispatch is async)",
            labels=("fn",)),
        "flops": reg.counter(
            "photon_flops_total",
            "Estimated FLOPs executed (XLA cost analysis per-execution "
            "estimate, accumulated per call)", labels=("fn",)),
        "bytes": reg.counter(
            "photon_bytes_accessed_total",
            "Estimated bytes accessed (XLA cost analysis per-execution "
            "estimate, accumulated per call)", labels=("fn",)),
        "peak_memory": reg.gauge(
            "photon_peak_memory_bytes",
            "Peak program memory (arguments+outputs+temporaries) of the "
            "heaviest executable compiled under the fn label",
            labels=("fn",)),
    }


# --- global XLA compile accounting (jax.monitoring) ------------------------

#: jax.monitoring duration events → the phase label we expose
_XLA_EVENT_PHASES = {
    "/jax/core/compile/jaxpr_trace_duration": "trace",
    "/jax/core/compile/jaxpr_to_mlir_module_duration": "lower",
    "/jax/core/compile/backend_compile_duration": "backend",
}

_hooks_lock = threading.Lock()
_hooks_installed = False


def install_xla_hooks() -> None:
    """Register the process-wide ``jax.monitoring`` listener that folds
    every XLA compile (profiled or not) into
    ``photon_xla_compiles_total{phase}`` and
    ``photon_xla_compile_seconds_total{phase}``. Idempotent; installed
    automatically by the first :class:`ProfiledFunction`."""
    global _hooks_installed
    with _hooks_lock:
        if _hooks_installed:
            return
        _hooks_installed = True
    compiles = _metrics.counter(
        "photon_xla_compiles_total",
        "XLA compile-pipeline events across the whole process (any jit, "
        "wrapped or not)", labels=("phase",))
    seconds = _metrics.counter(
        "photon_xla_compile_seconds_total",
        "Wall seconds in the XLA compile pipeline across the whole "
        "process (any jit, wrapped or not)", labels=("phase",))

    def _listener(event: str, duration: float, **_kw) -> None:
        phase = _XLA_EVENT_PHASES.get(event)
        if phase is None:
            return
        try:
            compiles.labels(phase=phase).inc()
            seconds.labels(phase=phase).inc(max(float(duration), 0.0))
        except Exception:
            pass  # a telemetry hook must never break a compile

    jax.monitoring.register_event_duration_secs_listener(_listener)


def record_compile(name: str, seconds: float = 0.0,
                   registry: Optional[MetricsRegistry] = None) -> None:
    """Count one compile under ``fn=name`` for call sites that own their jit
    machinery (the serving engine increments from inside the traced body,
    where the compile wall-clock is not observable — ``seconds`` defaults
    to 0 there; the global :func:`install_xla_hooks` listener still
    captures the real backend seconds)."""
    fams = _families(registry)
    fams["compiles"].labels(fn=name).inc()
    if seconds > 0:
        fams["compile_seconds"].labels(fn=name).inc(seconds)


def total_compiles(registry: Optional[MetricsRegistry] = None) -> float:
    """Sum of ``photon_compiles_total`` across every ``fn`` label — the
    number coordinate descent stamps on each ``cd.sweep`` span so the
    flat-after-sweep-1 contract is visible in the trace."""
    reg = registry if registry is not None else _metrics.default_registry()
    fam = reg.get("photon_compiles_total")
    if fam is None:
        return 0.0
    return sum(child.value for _labels, child in fam.children())


# --- the wrapper -----------------------------------------------------------


def _leaf_key(leaf):
    """Hashable abstract key for one pytree leaf: arrays by
    (shape, dtype, sharding) — the same equivalence jit's dispatch cache
    uses — and Python scalars by type (they trace weakly typed, so the
    value does not change the program)."""
    shape = getattr(leaf, "shape", None)
    if shape is not None:
        sharding = getattr(leaf, "sharding", None)
        return (tuple(shape), str(getattr(leaf, "dtype", "?")), sharding)
    if isinstance(leaf, (bool, int, float, complex)):
        return type(leaf)
    return (type(leaf), repr(leaf))


class _Pending:
    """Placeholder cache entry while one thread compiles a signature —
    parallel warm-compiles of DIFFERENT signatures proceed concurrently,
    but two threads racing the SAME signature share one compile."""

    __slots__ = ("event", "result", "error")

    def __init__(self):
        self.event = threading.Event()
        self.result = None
        self.error = None


class ProfiledFunction:
    """A jitted function driven through the AOT API with per-signature
    compile/execute accounting (see the module docstring).

    Call surface matches the wrapped function. ``static_argnames`` mirrors
    ``jax.jit``'s (resolved positionally through the function signature,
    like jit does); static values key the executable cache by VALUE, traced
    leaves by abstract signature. Tracer arguments (calls inside another
    trace) fall back to the plain jit and inline.
    """

    def __init__(self, fn: Callable, name: str, *,
                 static_argnames: Sequence[str] = (),
                 block: bool = False,
                 registry: Optional[MetricsRegistry] = None):
        install_xla_hooks()
        self.name = name
        self._static = tuple(static_argnames)
        self._block = block
        self._jitted = jax.jit(fn, static_argnames=self._static) \
            if self._static else jax.jit(fn)
        try:
            self._signature = inspect.signature(fn)
        except (TypeError, ValueError):
            if self._static:
                raise
            self._signature = None
        fams = _families(registry)
        self._compiles = fams["compiles"].labels(fn=name)
        self._compile_seconds = fams["compile_seconds"].labels(fn=name)
        self._execute = fams["execute"].labels(fn=name)
        self._flops = fams["flops"].labels(fn=name)
        self._bytes = fams["bytes"].labels(fn=name)
        self._peak_memory = fams["peak_memory"].labels(fn=name)
        self._lock = threading.Lock()
        self._cache: dict = {}

    # --- introspection ----------------------------------------------------
    @property
    def compiles(self) -> int:
        """Executables compiled by THIS wrapper so far."""
        with self._lock:
            return sum(1 for v in self._cache.values()
                       if not isinstance(v, _Pending))

    def cache_size(self) -> int:
        with self._lock:
            return len(self._cache)

    # --- internals --------------------------------------------------------
    def _split(self, args, kwargs):
        """Normalize a call to positional order and split static from
        dynamic arguments (jit's static_argnames semantics)."""
        if self._signature is None:
            return (), args, kwargs
        bound = self._signature.bind(*args, **kwargs)
        bound.apply_defaults()
        statics, dynamics = [], []
        for pname in self._signature.parameters:
            if pname not in bound.arguments:
                continue
            value = bound.arguments[pname]
            if pname in self._static:
                statics.append((pname, value))
            else:
                dynamics.append(value)
        return tuple(statics), tuple(dynamics), {}

    def _analyze(self, compiled):
        """(flops, bytes) per execution + peak memory from XLA's own cost
        model; 0.0 where a backend declines to say (the counters then
        simply stay flat for this fn)."""
        flops = bytes_ = 0.0
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            flops = max(float(ca.get("flops", 0.0)), 0.0)
            bytes_ = max(float(ca.get("bytes accessed", 0.0)), 0.0)
        except Exception:
            pass
        try:
            ma = compiled.memory_analysis()
            peak = (ma.argument_size_in_bytes + ma.output_size_in_bytes
                    + ma.temp_size_in_bytes)
            if peak > self._peak_memory.value:
                self._peak_memory.set(peak)
        except Exception:
            pass
        return flops, bytes_

    def _compile(self, key, lower_args, lower_kwargs):
        """Lower+compile ``key``'s executable, once per signature across
        threads (losers of the race wait on the winner's event)."""
        with self._lock:
            entry = self._cache.get(key)
            if entry is None:
                entry = self._cache[key] = _Pending()
                owner = True
            else:
                owner = False
        if not owner:
            if isinstance(entry, _Pending):
                entry.event.wait()
                if entry.error is not None:
                    raise entry.error
                return entry.result
            return entry
        pending = entry
        try:
            t0 = time.perf_counter()
            lowered = self._jitted.lower(*lower_args, **lower_kwargs)
            compiled = lowered.compile()
            self._compile_seconds.inc(time.perf_counter() - t0)
            self._compiles.inc()
            flops, bytes_ = self._analyze(compiled)
            result = (compiled, flops, bytes_)
            with self._lock:
                self._cache[key] = result
            pending.result = result
            return result
        except BaseException as e:
            pending.error = e
            with self._lock:
                self._cache.pop(key, None)  # retryable: do not poison
            raise
        finally:
            pending.event.set()

    # --- the call ---------------------------------------------------------
    def __call__(self, *args, **kwargs):
        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
        if any(isinstance(leaf, jax.core.Tracer) for leaf in leaves):
            # called inside another trace (fused programs, vmap, grad):
            # inline through the plain jit — no separate compile exists
            return self._jitted(*args, **kwargs)
        statics, dyn_args, dyn_kwargs = self._split(args, kwargs)
        if self._signature is None:
            key = (treedef, tuple(_leaf_key(l) for l in leaves))
            lower_args, lower_kwargs = args, kwargs
        else:
            dyn_leaves, dyn_treedef = jax.tree_util.tree_flatten(
                (dyn_args, dyn_kwargs))
            key = (statics, dyn_treedef,
                   tuple(_leaf_key(l) for l in dyn_leaves))
            # jit resolves static_argnames positionally; pass the
            # normalized positional form so lowering sees what we keyed
            lower_args, lower_kwargs = self._ordered(statics, dyn_args), {}
        compiled, flops, bytes_ = self._compile(key, lower_args,
                                                lower_kwargs)
        if flops:
            self._flops.inc(flops)
        if bytes_:
            self._bytes.inc(bytes_)
        with self._execute.time():
            out = compiled(*dyn_args, **dyn_kwargs)
            if self._block:
                out = jax.block_until_ready(out)
        return out

    def _ordered(self, statics, dynamics):
        """Re-interleave statics and dynamics back into signature order for
        lowering (the compiled executable is then CALLED with the dynamics
        only — JAX's AOT contract)."""
        static_by_name = dict(statics)
        out = []
        dyn_iter = iter(dynamics)
        for pname in self._signature.parameters:
            if pname in static_by_name:
                out.append(static_by_name[pname])
            else:
                try:
                    out.append(next(dyn_iter))
                except StopIteration:
                    break
        return tuple(out)


def profile_jit(fn: Callable, name: str, *,
                static_argnames: Sequence[str] = (),
                block: bool = False,
                registry: Optional[MetricsRegistry] = None,
                ) -> ProfiledFunction:
    """Wrap ``fn`` as a jitted function with compile/execute accounting
    under the ``fn=name`` label family — the drop-in replacement for
    ``jax.jit(fn)`` at the hot call sites (see the module docstring)."""
    return ProfiledFunction(fn, name, static_argnames=static_argnames,
                            block=block, registry=registry)
