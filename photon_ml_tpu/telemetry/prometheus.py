"""Prometheus text exposition (format 0.0.4) for a MetricsRegistry.

One pure function, :func:`render`, turns a registry snapshot into the text
format every Prometheus-compatible scraper ingests — served by
``GET /metrics`` on the serving front end and dumped as ``metrics.prom``
into a training run's ``--telemetry-dir``. :func:`parse_text` is the
inverse (subset: the families we emit), shared by
``tools/bench_serving.py``'s end-of-run scrape and the round-trip tests so
the writer and the one in-repo reader can never drift apart.

Layout per family::

    # HELP name help text
    # TYPE name counter|gauge|histogram
    name{label="value"} 1
    ...

Histograms expand to cumulative ``name_bucket{le="..."}`` series (including
``le="+Inf"``) plus ``name_sum`` and ``name_count``, exactly the layout
``histogram_quantile()`` expects.
"""

from __future__ import annotations

import math
from typing import Mapping, Optional

from photon_ml_tpu.telemetry.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
)

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s: str) -> str:
    return (s.replace("\\", "\\\\").replace("\"", "\\\"")
            .replace("\n", "\\n"))


def format_value(v: float) -> str:
    """Prometheus float formatting: integers without a trailing ``.0``,
    infinities as ``+Inf``/``-Inf``."""
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    if float(v) == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _labels_text(names, values, extra: Optional[tuple[str, str]] = None) -> str:
    pairs = [f'{n}="{_escape_label(v)}"' for n, v in zip(names, values)]
    if extra is not None:
        pairs.append(f'{extra[0]}="{extra[1]}"')
    return "{" + ",".join(pairs) + "}" if pairs else ""


def render(registry: Optional[MetricsRegistry] = None) -> str:
    """The registry's current state as exposition text (ends with ``\\n``)."""
    registry = registry if registry is not None else default_registry()
    lines: list[str] = []
    for fam in registry.collect():
        if fam.help:
            lines.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
        lines.append(f"# TYPE {fam.name} {fam.type}")
        for values, child in fam.children():
            if isinstance(child, (Counter, Gauge)):
                lines.append(
                    f"{fam.name}{_labels_text(fam.label_names, values)} "
                    f"{format_value(child.value)}")
            elif isinstance(child, Histogram):
                cum, total, count = child.snapshot()
                bounds = [format_value(b) for b in child.uppers] + ["+Inf"]
                for bound, c in zip(bounds, cum):
                    lines.append(
                        f"{fam.name}_bucket"
                        f"{_labels_text(fam.label_names, values, ('le', bound))}"
                        f" {c}")
                lines.append(
                    f"{fam.name}_sum{_labels_text(fam.label_names, values)} "
                    f"{format_value(total)}")
                lines.append(
                    f"{fam.name}_count{_labels_text(fam.label_names, values)} "
                    f"{count}")
    return "\n".join(lines) + "\n" if lines else ""


def _parse_label_block(block: str) -> dict[str, str]:
    out: dict[str, str] = {}
    i = 0
    while i < len(block):
        eq = block.index("=", i)
        name = block[i:eq].strip().lstrip(",").strip()
        assert block[eq + 1] == '"', f"unquoted label value in {block!r}"
        j = eq + 2
        val = []
        while block[j] != '"':
            if block[j] == "\\":
                nxt = block[j + 1]
                val.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt, nxt))
                j += 2
            else:
                val.append(block[j])
                j += 1
        out[name] = "".join(val)
        i = j + 1
    return out


def parse_value(s: str) -> float:
    if s == "+Inf":
        return math.inf
    if s == "-Inf":
        return -math.inf
    return float(s)


def parse_text(text: str) -> dict[str, list[tuple[dict[str, str], float]]]:
    """Exposition text → ``{series_name: [(labels, value), ...]}``.

    Histogram series come back under their expanded names
    (``x_bucket``/``x_sum``/``x_count``) — the shape scrapers see. Helper
    for the bench and tests, not a general-purpose Prometheus parser (no
    exemplars, no timestamps — we emit neither).
    """
    out: dict[str, list[tuple[dict[str, str], float]]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        if "{" in line:
            name, rest = line.split("{", 1)
            block, value_s = rest.rsplit("}", 1)
            labels = _parse_label_block(block)
        else:
            name, value_s = line.rsplit(" ", 1)
            labels = {}
        out.setdefault(name.strip(), []).append(
            (labels, parse_value(value_s.strip())))
    return out


def series_value(parsed: Mapping, name: str,
                 labels: Optional[Mapping[str, str]] = None,
                 default: float = 0.0) -> float:
    """First series under ``name`` whose labels contain ``labels`` (subset
    match); ``default`` when absent — scrape-delta helpers shouldn't crash
    on a counter that hasn't been created yet."""
    for got, value in parsed.get(name, ()):
        if labels is None or all(got.get(k) == v for k, v in labels.items()):
            return value
    return default
