"""Prometheus text exposition (format 0.0.4) for a MetricsRegistry.

One pure function, :func:`render`, turns a registry snapshot into the text
format every Prometheus-compatible scraper ingests — served by
``GET /metrics`` on the serving front end and dumped as ``metrics.prom``
into a training run's ``--telemetry-dir``. :func:`parse_text` is the
inverse (subset: the families we emit), shared by
``tools/bench_serving.py``'s end-of-run scrape and the round-trip tests so
the writer and the one in-repo reader can never drift apart.

The parse→render round-trip is BYTE-IDENTICAL: :func:`parse_text` returns a
:class:`ParsedSnapshot` that keeps the ``# HELP``/``# TYPE`` headers and
document order alongside the samples, and :func:`render` accepts either a
registry or a parsed snapshot. The fleet aggregator
(:mod:`photon_ml_tpu.telemetry.aggregate`) leans on this invariant so the
in-training collective fold and the offline ``tools/metrics_fold.py`` fold
of the same snapshots produce the same bytes.

Layout per family::

    # HELP name help text
    # TYPE name counter|gauge|histogram
    name{label="value"} 1
    ...

Histograms expand to cumulative ``name_bucket{le="..."}`` series (including
``le="+Inf"``) plus ``name_sum`` and ``name_count``, exactly the layout
``histogram_quantile()`` expects.
"""

from __future__ import annotations

import math
from typing import Mapping, Optional

from photon_ml_tpu.telemetry.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
    host_owned_gauges,
)

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _unescape(s: str) -> str:
    out, i = [], 0
    while i < len(s):
        c = s[i]
        if c == "\\" and i + 1 < len(s):
            out.append({"n": "\n", "\\": "\\"}.get(s[i + 1], s[i + 1]))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _escape_label(s: str) -> str:
    return (s.replace("\\", "\\\\").replace("\"", "\\\"")
            .replace("\n", "\\n"))


def format_value(v: float) -> str:
    """Prometheus float formatting: integers without a trailing ``.0``,
    infinities as ``+Inf``/``-Inf``."""
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    if float(v) == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _labels_text(names, values, extra: Optional[tuple[str, str]] = None) -> str:
    pairs = [f'{n}="{_escape_label(v)}"' for n, v in zip(names, values)]
    if extra is not None:
        pairs.append(f'{extra[0]}="{extra[1]}"')
    return "{" + ",".join(pairs) + "}" if pairs else ""


def render(registry=None,
           host_tag: Optional[tuple[str, str]] = None) -> str:
    """The registry's current state as exposition text (ends with ``\\n``).

    Also accepts a :class:`ParsedSnapshot` (what :func:`parse_text`
    returns), re-emitting it byte-identically — the aggregator's merge
    path. ``host_tag`` (e.g. ``("process", "1")``) is appended to every
    series of a host-owned gauge family (see
    :func:`~photon_ml_tpu.telemetry.metrics.mark_host_owned`) so a
    multi-process fold never collapses one host's gauge into another's.
    """
    if isinstance(registry, ParsedSnapshot):
        return render_parsed(registry)
    registry = registry if registry is not None else default_registry()
    host_owned = host_owned_gauges() if host_tag is not None else ()
    lines: list[str] = []
    for fam in registry.collect():
        if fam.help:
            lines.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
        lines.append(f"# TYPE {fam.name} {fam.type}")
        tag = (host_tag if fam.type == "gauge" and fam.name in host_owned
               else None)
        for values, child in fam.children():
            if isinstance(child, (Counter, Gauge)):
                lines.append(
                    f"{fam.name}{_labels_text(fam.label_names, values, tag)} "
                    f"{format_value(child.value)}")
            elif isinstance(child, Histogram):
                cum, total, count = child.snapshot()
                bounds = [format_value(b) for b in child.uppers] + ["+Inf"]
                for bound, c in zip(bounds, cum):
                    lines.append(
                        f"{fam.name}_bucket"
                        f"{_labels_text(fam.label_names, values, ('le', bound))}"
                        f" {c}")
                lines.append(
                    f"{fam.name}_sum{_labels_text(fam.label_names, values)} "
                    f"{format_value(total)}")
                lines.append(
                    f"{fam.name}_count{_labels_text(fam.label_names, values)} "
                    f"{count}")
    return "\n".join(lines) + "\n" if lines else ""


def _parse_label_block(block: str) -> dict[str, str]:
    out: dict[str, str] = {}
    i = 0
    while i < len(block):
        eq = block.index("=", i)
        name = block[i:eq].strip().lstrip(",").strip()
        assert block[eq + 1] == '"', f"unquoted label value in {block!r}"
        j = eq + 2
        val = []
        while block[j] != '"':
            if block[j] == "\\":
                nxt = block[j + 1]
                val.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt, nxt))
                j += 2
            else:
                val.append(block[j])
                j += 1
        out[name] = "".join(val)
        i = j + 1
    return out


def parse_value(s: str) -> float:
    if s == "+Inf":
        return math.inf
    if s == "-Inf":
        return -math.inf
    return float(s)


class ParsedSnapshot(dict):
    """:func:`parse_text` result: ``{series_name: [(labels, value), ...]}``
    (a plain dict, so pre-existing consumers keep working) plus
    ``families`` — ``{family_name: {"type": ..., "help": ...}}`` in
    document order, carrying the ``# HELP``/``# TYPE`` headers needed to
    re-render the text byte-identically and to merge snapshots
    type-correctly."""

    def __init__(self):
        super().__init__()
        self.families: dict[str, dict] = {}


def parse_text(text: str) -> ParsedSnapshot:
    """Exposition text → :class:`ParsedSnapshot`.

    Histogram series come back under their expanded names
    (``x_bucket``/``x_sum``/``x_count``) — the shape scrapers see. Not a
    general-purpose Prometheus parser (no exemplars, no timestamps — we
    emit neither), but ``render(parse_text(render(reg)))`` is
    byte-identical to ``render(reg)`` — the invariant the fleet
    aggregator depends on.
    """
    out = ParsedSnapshot()
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(" ", 3)
            if len(parts) >= 3 and parts[1] in ("HELP", "TYPE"):
                fam = out.families.setdefault(
                    parts[2], {"type": "untyped", "help": None})
                body = parts[3] if len(parts) > 3 else ""
                if parts[1] == "HELP":
                    fam["help"] = _unescape(body)
                else:
                    fam["type"] = body.strip() or "untyped"
            continue
        if "{" in line:
            name, rest = line.split("{", 1)
            block, value_s = rest.rsplit("}", 1)
            labels = _parse_label_block(block)
        else:
            name, value_s = line.rsplit(" ", 1)
            labels = {}
        out.setdefault(name.strip(), []).append(
            (labels, parse_value(value_s.strip())))
    return out


def _sample_line(name: str, labels: Mapping[str, str], value: float) -> str:
    if labels:
        block = ",".join(f'{k}="{_escape_label(v)}"'
                         for k, v in labels.items())
        return f"{name}{{{block}}} {format_value(value)}"
    return f"{name} {format_value(value)}"


def _label_key(labels: Mapping[str, str]) -> tuple:
    return tuple(sorted(labels.items()))


def histogram_series_names(family: str) -> tuple[str, str, str]:
    """The expanded series names a histogram family ``family`` emits."""
    return family + "_bucket", family + "_sum", family + "_count"


def _emit_histogram(lines: list, parsed: ParsedSnapshot, name: str) -> None:
    """Re-emit a histogram family per-child (all of one label set's buckets,
    then its ``_sum`` and ``_count``) — the layout :func:`render` writes, so
    the round-trip stays byte-identical."""
    bucket_name, sum_name, count_name = histogram_series_names(name)
    sums = list(parsed.get(sum_name, ()))
    counts = list(parsed.get(count_name, ()))
    groups: dict[tuple, list] = {}
    for labels, value in parsed.get(bucket_name, ()):
        base = {k: v for k, v in labels.items() if k != "le"}
        groups.setdefault(_label_key(base), []).append((labels, value))

    def pop_matching(samples: list, key: tuple):
        for i, (labels, value) in enumerate(samples):
            if _label_key(labels) == key:
                return samples.pop(i)
        return None

    for key, buckets in groups.items():
        for labels, value in buckets:
            lines.append(_sample_line(bucket_name, labels, value))
        for series, samples in ((sum_name, sums), (count_name, counts)):
            got = pop_matching(samples, key)
            if got is not None:
                lines.append(_sample_line(series, got[0], got[1]))
    # stray _sum/_count with no bucket series (not produced by our
    # renderer, but tolerated rather than dropped)
    for series, samples in ((sum_name, sums), (count_name, counts)):
        for labels, value in samples:
            lines.append(_sample_line(series, labels, value))


def render_parsed(parsed: ParsedSnapshot) -> str:
    """A :class:`ParsedSnapshot` back as exposition text — the exact bytes
    :func:`render` would have produced for the snapshot it was parsed from
    (headers, family order and sample order preserved)."""
    lines: list[str] = []
    claimed: set[str] = set()
    for name, fam in parsed.families.items():
        if fam.get("help"):
            lines.append(f"# HELP {name} {_escape_help(fam['help'])}")
        lines.append(f"# TYPE {name} {fam['type']}")
        if fam["type"] == "histogram":
            claimed.update(histogram_series_names(name))
            _emit_histogram(lines, parsed, name)
        else:
            claimed.add(name)
            for labels, value in parsed.get(name, ()):
                lines.append(_sample_line(name, labels, value))
    for name, samples in parsed.items():  # headerless series, document order
        if name in claimed:
            continue
        for labels, value in samples:
            lines.append(_sample_line(name, labels, value))
    return "\n".join(lines) + "\n" if lines else ""


def series_value(parsed: Mapping, name: str,
                 labels: Optional[Mapping[str, str]] = None,
                 default: float = 0.0) -> float:
    """First series under ``name`` whose labels contain ``labels`` (subset
    match); ``default`` when absent — scrape-delta helpers shouldn't crash
    on a counter that hasn't been created yet."""
    for got, value in parsed.get(name, ()):
        if labels is None or all(got.get(k) == v for k, v in labels.items()):
            return value
    return default
