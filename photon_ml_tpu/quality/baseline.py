"""Train-time model-quality baselines + the drift arithmetic (PSI/KS).

The telemetry stack observes the SYSTEM — latency, compiles, FLOPs,
restarts — while the model's predictions serve blind: with the continuous
refresh loop auto-publishing versions into a watched directory
(CONTINUOUS.md) and quantized tables introducing documented score
tolerances (SERVING.md), the highest-risk failure mode is a silently
degraded model activating into production with no metric moving. The
quality layer closes that gap, and this module is its reference side:

- :func:`compute_baseline` distills a validation (or training) score set
  into a compact :class:`QualityBaseline` — equal-mass score-histogram
  bins with their baseline proportions, mean/std/positive-rate, AUC
  (:mod:`photon_ml_tpu.evaluation.metrics`), per-coordinate
  margin-contribution stats, per-coordinate cold-start rates,
  per-shard feature coverage, and Hosmer–Lemeshow calibration bins
  (:mod:`photon_ml_tpu.diagnostics.hl` — the same binning the offline
  diagnostics report);
- the drivers publish it as ``quality-baseline.json`` at the run root
  (next to ``best/`` and ``data-manifest.json``) on the background writer
  pool, and the serving registry rediscovers it at load time
  (:func:`find_baseline`) to seed the online monitors;
- :func:`population_stability_index` / :func:`ks_statistic` are the ONE
  home of the drift arithmetic, and :func:`bin_scores` /
  :func:`quantile_edges` the one home of score-histogram binning
  (telemetry hygiene rule 6, ``tools/check_telemetry_hygiene.py``): a
  second PSI implementation that floors proportions differently would
  silently disagree about what "drift" means.

Everything here is host numpy over arrays the callers already hold — no
device work, no hot-path cost; the drivers submit the whole computation
to the :class:`~photon_ml_tpu.io.pipeline.BackgroundSaver`.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import tempfile
from typing import Mapping, Optional, Sequence

import numpy as np

from photon_ml_tpu.fleet.sharding import stable_hash_u32

#: artifact name, published at the RUN root (``best/`` and
#: ``all/config-i`` are siblings under it, like ``data-manifest.json``)
BASELINE_NAME = "quality-baseline.json"

#: default number of equal-mass score-histogram bins (the standard PSI
#: decile binning)
DEFAULT_SCORE_BINS = 10

#: proportion floor for the PSI log ratio — an empty bin must contribute
#: a large, finite penalty, not an infinity
_EPS = 1e-6


# ---------------------------------------------------------------------------
# binning + drift arithmetic (the hygiene-rule-6 home)
# ---------------------------------------------------------------------------


def quantile_edges(scores: np.ndarray,
                   n_bins: int = DEFAULT_SCORE_BINS) -> np.ndarray:
    """Interior edges of ``n_bins`` equal-mass bins over ``scores``
    (deduplicated — discrete score sets may yield fewer bins). The outer
    bins are implicitly open (``-inf`` / ``+inf``), so every live score
    lands somewhere."""
    scores = np.asarray(scores, np.float64)
    if scores.size == 0 or n_bins < 2:
        return np.zeros(0, np.float64)
    qs = np.linspace(0.0, 1.0, n_bins + 1)[1:-1]
    return np.unique(np.quantile(scores, qs))


def bin_scores(scores: np.ndarray, edges: Sequence[float]) -> np.ndarray:
    """Per-bin counts of ``scores`` against interior ``edges``
    (``len(edges) + 1`` bins). The single binning used on BOTH sides of
    every PSI/KS comparison — baseline proportions and the live monitor
    accumulate through this exact function."""
    edges = np.asarray(edges, np.float64)
    bins = np.searchsorted(edges, np.asarray(scores, np.float64),
                           side="right")
    return np.bincount(bins, minlength=len(edges) + 1).astype(np.float64)


def _proportions(counts_or_props: Sequence[float]) -> np.ndarray:
    p = np.asarray(counts_or_props, np.float64)
    total = p.sum()
    p = p / total if total > 0 else np.full(p.shape, 1.0 / max(len(p), 1))
    return np.clip(p, _EPS, None)


def population_stability_index(expected, actual) -> float:
    """PSI of ``actual`` vs ``expected`` over matched bins (counts or
    proportions — both are normalized). Rule of thumb: < 0.1 stable,
    0.1–0.25 moderate shift, > 0.25 significant drift."""
    e = _proportions(expected)
    a = _proportions(actual)
    if e.shape != a.shape:
        raise ValueError(f"PSI needs matched bins, got {e.shape} vs {a.shape}")
    return float(np.sum((a - e) * np.log(a / e)))


def ks_statistic(expected, actual) -> float:
    """Kolmogorov–Smirnov distance between two binned distributions:
    max |ΔCDF| over the shared bin edges, in [0, 1]."""
    e = _proportions(expected)
    a = _proportions(actual)
    if e.shape != a.shape:
        raise ValueError(f"KS needs matched bins, got {e.shape} vs {a.shape}")
    return float(np.max(np.abs(np.cumsum(a) - np.cumsum(e))))


# ---------------------------------------------------------------------------
# rank-drift reference + arithmetic (the ranked-serving half of rule 6)
# ---------------------------------------------------------------------------


def rank_probe_sample(user_ids: Sequence[str], n: int = 16) -> tuple:
    """Deterministic probe-user sample for the rank-drift reference:
    the ``n`` ids that sort first by ``crc32(id)`` — stable across
    processes, loads and vocabulary dict order, and uniform-ish over the
    id universe (the same fleet-joinable hashing discipline the request
    log samples by)."""
    ids = sorted({str(u) for u in user_ids},
                 key=lambda u: (stable_hash_u32(u), u))
    return tuple(ids[:max(int(n), 1)])


def rank_probe_records(user_ids: Sequence[str],
                       entity_types: Sequence[str]) -> list:
    """The probe users' rank request records — featureless, id-only (the
    intercept columns and the entity coefficient rows drive the
    ranking), exactly what ``GET /rank?user=...`` synthesizes, so the
    reference and the live surface rank the same inputs."""
    return [{"features": [],
             "metadataMap": {t: str(u) for t in entity_types},
             "offset": None} for u in user_ids]


def topk_overlap(reference: Sequence[str], live: Sequence[str]) -> float:
    """``|reference ∩ live| / |reference|`` in [0, 1] — the rank-drift
    statistic: 1.0 = the live top-k retrieves exactly the reference set
    (order-insensitive; a reordering within the same k items is not
    drift, a swapped-in item is). Empty reference compares as 1.0."""
    ref = {str(i) for i in reference}
    if not ref:
        return 1.0
    return len(ref & {str(i) for i in live}) / len(ref)


# ---------------------------------------------------------------------------
# the baseline artifact
# ---------------------------------------------------------------------------


def _none_or_float(v) -> Optional[float]:
    if v is None:
        return None
    v = float(v)
    return None if math.isnan(v) else v


@dataclasses.dataclass(frozen=True)
class QualityBaseline:
    """Compact quality profile of a model's reference score distribution
    — what the online monitors and the canary report compare live traffic
    against. All fields are plain JSON-serializable host values."""

    task: Optional[str]
    n_samples: int
    mean_score: float
    std_score: float
    #: weighted positive-label rate (None when labels were unavailable)
    positive_rate: Optional[float]
    #: weighted AUC on the reference set (logistic tasks with labels)
    auc: Optional[float]
    #: interior equal-mass score-bin edges (len n_bins - 1)
    edges: tuple
    #: per-bin reference mass (len n_bins, sums to 1)
    proportions: tuple
    #: per-coordinate margin-contribution stats {cid: {mean, std, abs_mean}}
    coordinates: Mapping[str, Mapping[str, float]]
    #: per-random-effect-coordinate fraction of reference rows with no
    #: entity id (the cold-start rate the live monitor compares against)
    cold_rates: Mapping[str, float]
    #: per-feature-shard mean fraction of nonzero design cells
    coverage: Mapping[str, float]
    #: Hosmer–Lemeshow calibration bins (logistic tasks with labels)
    calibration: Optional[Mapping] = None
    #: lineage passthrough (parentModel / trainedAt / dataManifest)
    lineage: Optional[Mapping] = None
    #: rank-drift reference: probe user id → that user's top-k item ids
    #: as the FULL model ranked them at load time (the serving registry
    #: pins this; patches inherit it, so patched-table ranking shifts
    #: surface as ``rank_overlap`` drift). None = no ranked workload.
    rank_probes: Optional[Mapping] = None
    #: the k the reference lists were computed at
    rank_k: int = 0

    @property
    def n_bins(self) -> int:
        return len(self.proportions)

    def to_dict(self) -> dict:
        return {
            "task": self.task,
            "nSamples": self.n_samples,
            "meanScore": self.mean_score,
            "stdScore": self.std_score,
            "positiveRate": self.positive_rate,
            "auc": self.auc,
            "scoreBins": {"edges": list(self.edges),
                          "proportions": list(self.proportions)},
            "coordinates": {cid: dict(st)
                            for cid, st in self.coordinates.items()},
            "coldRates": dict(self.cold_rates),
            "coverage": dict(self.coverage),
            "calibration": (None if self.calibration is None
                            else dict(self.calibration)),
            "lineage": (None if self.lineage is None
                        else dict(self.lineage)),
            "rankProbes": (None if self.rank_probes is None else {
                "k": self.rank_k,
                "users": {str(u): list(ids)
                          for u, ids in self.rank_probes.items()}}),
        }

    @classmethod
    def from_dict(cls, d: Mapping) -> "QualityBaseline":
        bins = d.get("scoreBins") or {}
        return cls(
            task=d.get("task"),
            n_samples=int(d.get("nSamples", 0)),
            mean_score=float(d.get("meanScore", 0.0)),
            std_score=float(d.get("stdScore", 0.0)),
            positive_rate=_none_or_float(d.get("positiveRate")),
            auc=_none_or_float(d.get("auc")),
            edges=tuple(float(e) for e in bins.get("edges", ())),
            proportions=tuple(float(p)
                              for p in bins.get("proportions", ())),
            coordinates={str(c): {k: float(v) for k, v in st.items()}
                         for c, st in (d.get("coordinates") or {}).items()},
            cold_rates={str(c): float(v)
                        for c, v in (d.get("coldRates") or {}).items()},
            coverage={str(s): float(v)
                      for s, v in (d.get("coverage") or {}).items()},
            calibration=d.get("calibration"),
            lineage=d.get("lineage"),
            rank_probes=(None if d.get("rankProbes") is None else {
                str(u): tuple(str(i) for i in ids)
                for u, ids in (d["rankProbes"].get("users") or {}).items()}),
            rank_k=int((d.get("rankProbes") or {}).get("k", 0)),
        )


def compute_baseline(scores, labels=None, weights=None, *,
                     task=None,
                     margins: Optional[Mapping[str, np.ndarray]] = None,
                     cold_rates: Optional[Mapping[str, float]] = None,
                     coverage: Optional[Mapping[str, float]] = None,
                     n_bins: int = DEFAULT_SCORE_BINS,
                     lineage: Optional[Mapping] = None) -> QualityBaseline:
    """Distill a reference score set into a :class:`QualityBaseline`.

    ``scores`` are TOTAL model scores (raw margins — the same quantity the
    serving engine emits, so live traffic bins comparably); ``margins``
    maps coordinate id → that coordinate's margin contribution. AUC and
    the Hosmer–Lemeshow calibration table are computed only for logistic
    tasks with labels (reusing ``evaluation/metrics.py`` and
    ``diagnostics/hl.py`` — the offline diagnostics' own arithmetic).
    """
    scores = np.asarray(scores, np.float64)
    n = int(scores.size)
    w = (np.ones(n, np.float64) if weights is None
         else np.asarray(weights, np.float64))
    edges = quantile_edges(scores, n_bins)
    counts = bin_scores(scores, edges) if n else np.zeros(1, np.float64)
    proportions = counts / max(counts.sum(), 1.0)

    positive_rate = auc = calibration = None
    task_value = getattr(task, "value", task)
    if labels is not None and n:
        labels = np.asarray(labels, np.float64)
        positive_rate = float(np.sum(w * labels) / max(np.sum(w), _EPS))
        if task_value == "LOGISTIC_REGRESSION":
            probs = 1.0 / (1.0 + np.exp(-np.clip(scores, -60.0, 60.0)))
            from photon_ml_tpu.diagnostics.hl import hosmer_lemeshow
            from photon_ml_tpu.evaluation.metrics import (
                area_under_roc_curve,
            )

            auc = _none_or_float(area_under_roc_curve(
                np.asarray(scores, np.float32),
                np.asarray(labels, np.float32),
                np.asarray(w, np.float32)))
            hl = hosmer_lemeshow(np.asarray(probs, np.float32),
                                 np.asarray(labels, np.float32),
                                 np.asarray(w, np.float32))
            calibration = {
                "binCounts": [float(c) for c in hl.bin_counts],
                "observedPositives": [float(c)
                                      for c in hl.observed_positives],
                "expectedPositives": [float(c)
                                      for c in hl.expected_positives],
                "meanPredicted": [float(c) for c in hl.mean_predicted],
                "chiSquare": float(hl.chi_square),
                "pValue": float(hl.p_value),
            }

    coordinate_stats = {}
    for cid, m in (margins or {}).items():
        m = np.asarray(m, np.float64)
        coordinate_stats[cid] = {
            "mean": float(m.mean()) if m.size else 0.0,
            "std": float(m.std()) if m.size else 0.0,
            "abs_mean": float(np.abs(m).mean()) if m.size else 0.0,
        }

    return QualityBaseline(
        task=task_value,
        n_samples=n,
        mean_score=float(scores.mean()) if n else 0.0,
        std_score=float(scores.std()) if n else 0.0,
        positive_rate=positive_rate,
        auc=auc,
        edges=tuple(float(e) for e in edges),
        proportions=tuple(float(p) for p in proportions),
        coordinates=coordinate_stats,
        cold_rates=dict(cold_rates or {}),
        coverage=dict(coverage or {}),
        calibration=calibration,
        lineage=None if lineage is None else dict(lineage),
    )


def baseline_from_game(model, data, *, task=None,
                       n_bins: int = DEFAULT_SCORE_BINS,
                       lineage: Optional[Mapping] = None) -> QualityBaseline:
    """The drivers' one-call path: profile a trained
    :class:`~photon_ml_tpu.game.model.GameModel` against a scored
    :class:`~photon_ml_tpu.game.data.GameData` (validation when the run
    has it, training data otherwise — either is a reference distribution
    for drift). Host-side only; the drivers run it on the background
    writer pool so it never touches the training wall."""
    from photon_ml_tpu.game.model import FixedEffectModel

    margins = model.score_by_coordinate(data)
    scores = model.score(data)
    cold_rates = {}
    for cid, cm in model.coordinates.items():
        if isinstance(cm, FixedEffectModel):
            continue
        ids = data.id_columns.get(cm.random_effect_type)
        cold_rates[cid] = (float(np.mean(np.asarray(ids) < 0))
                          if ids is not None and len(ids) else 1.0)
    coverage = {
        sid: (shard.nnz / float(data.n_samples * shard.dim)
              if data.n_samples and shard.dim else 0.0)
        for sid, shard in data.shards.items()}
    return compute_baseline(
        scores, data.labels, data.weights, task=task, margins=margins,
        cold_rates=cold_rates, coverage=coverage, n_bins=n_bins,
        lineage=lineage)


# ---------------------------------------------------------------------------
# persistence + discovery
# ---------------------------------------------------------------------------


def save_baseline(path: str, baseline: QualityBaseline) -> None:
    """Write the baseline JSON atomically (tmp + rename — a scraper or a
    loading registry can never observe a torn file). The drivers submit
    this through the BackgroundSaver, whose span/bytes accounting rides
    the existing ``io.save.*`` story."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    fd, tmp = tempfile.mkstemp(prefix=f".{os.path.basename(path)}-",
                               suffix=".tmp",
                               dir=os.path.dirname(path) or ".")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(baseline.to_dict(), f, indent=2, sort_keys=True)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def load_baseline(path: Optional[str]) -> Optional[QualityBaseline]:
    """Baseline at ``path``, or None when absent/unreadable — serving a
    model without a baseline is degraded observability, never an error."""
    if not path or not os.path.exists(path):
        return None
    try:
        with open(path, encoding="utf-8") as f:
            return QualityBaseline.from_dict(json.load(f))
    except (OSError, ValueError, KeyError):
        return None


def baseline_path_for(model_dir: str) -> str:
    """The baseline location for a resolved model dir: the RUN root
    (mirrors ``continuous/delta.py::manifest_path_for``)."""
    model_dir = os.path.normpath(model_dir)
    root = (os.path.dirname(model_dir)
            if os.path.basename(model_dir) == "best" else model_dir)
    return os.path.join(root, BASELINE_NAME)


def find_baseline(model_dir: str, *, max_up: int = 3) -> Optional[str]:
    """Locate ``quality-baseline.json`` for a model dir: it lives at the
    run root while the model may sit at ``<run>/best`` or
    ``<run>/all/config-N`` or ``<run>/patch`` — walk up like
    ``find_feature_index_dir``. None when no baseline was published."""
    probe = os.path.normpath(model_dir)
    for _ in range(max_up):
        candidate = os.path.join(probe, BASELINE_NAME)
        if os.path.exists(candidate):
            return candidate
        parent = os.path.dirname(probe)
        if parent == probe:
            break
        probe = parent
    return None
