"""Model-quality observability: train-time baselines, online drift
monitors, and canary-gated activation.

The telemetry stack (OBSERVABILITY.md) observes the SYSTEM; this package
observes the PREDICTIONS, spanning train → publish → serve:

- :mod:`~photon_ml_tpu.quality.baseline` — the training/refresh drivers
  distill validation scores into ``quality-baseline.json`` (score bins,
  calibration, per-coordinate stats) published next to the model; also
  the ONE home of the PSI/KS/binning arithmetic (telemetry hygiene
  rule 6);
- :mod:`~photon_ml_tpu.quality.monitor` — the serving engine accumulates
  live scores / cold-start hits / feature coverage into
  ``photon_quality_*`` metrics; a background :class:`DriftEvaluator`
  renders live-vs-baseline drift into
  ``photon_quality_drift_score{coordinate,kind}`` and posts
  ``quality_drift_detected`` past the threshold;
- :mod:`~photon_ml_tpu.quality.canary` — candidates shadow-score a
  reservoir of recent live requests against the incumbent at activation
  time; ``serve_game --canary-gate`` refuses divergent candidates like
  validation failures.

``tools/quality_report.py`` renders the whole story from a telemetry
dir; OBSERVABILITY.md "Model quality" documents the metric families.
"""

from photon_ml_tpu.quality.baseline import (  # noqa: F401
    BASELINE_NAME,
    DEFAULT_SCORE_BINS,
    QualityBaseline,
    baseline_from_game,
    baseline_path_for,
    bin_scores,
    compute_baseline,
    find_baseline,
    ks_statistic,
    load_baseline,
    population_stability_index,
    quantile_edges,
    rank_probe_records,
    rank_probe_sample,
    save_baseline,
    topk_overlap,
)
from photon_ml_tpu.quality.canary import (  # noqa: F401
    DEFAULT_BOUNDS,
    CanaryConfig,
    CanaryRejected,
    RequestReservoir,
    run_canary,
    score_divergence,
)
from photon_ml_tpu.quality.monitor import (  # noqa: F401
    DEFAULT_DRIFT_THRESHOLD,
    TOTAL_COORDINATE,
    DriftEvaluator,
    QualityMonitor,
)
