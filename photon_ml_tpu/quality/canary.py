"""Canary-gated activation: shadow-score a candidate before it serves.

Validation at load time proves a candidate is STRUCTURALLY sound
(metadata, part files, store packing — ``serving/registry.py``); it says
nothing about what the candidate *predicts*. With the continuous loop
auto-publishing versions into a watched directory, a refresh gone wrong —
a corrupted coefficient table, a solver fed garbage data — passes every
structural check and then serves garbage scores. The canary closes that
hole:

- the registry keeps a :class:`RequestReservoir` of recent live request
  records (uniform reservoir sampling, so the sample tracks real traffic
  without unbounded memory);
- at activation time (``/reload`` or a watch-dir pickup) the validated
  candidate **shadow-scores the reservoir against the incumbent**
  (:func:`run_canary`); the relative score divergence is annotated onto
  the activation (event + ``photon_quality_canary_divergence`` gauge +
  a ``quality.canary`` span for the report's history), and — under
  ``serve_game --canary-gate`` — a divergence past the bound REFUSES the
  activation exactly like a validation failure: :class:`CanaryRejected`
  propagates through the registry's reject path, the incumbent keeps
  serving bit-identically, and ``photon_model_reload_rejects_total``
  moves.

Default bounds are the quantized-table score tolerances SERVING.md
already documents as acceptable score error (bf16 ≤ 1e-2 relative, int8
≤ 5e-2); float32 stores default to the loosest of those (5e-2) — a
genuine model refresh may legitimately move scores more, in which case
the operator widens ``--canary-bound`` (the gate is for catching
corruption, not for freezing the model).
"""

from __future__ import annotations

import dataclasses
import random
import threading
from typing import Callable, Optional, Sequence

import numpy as np

from photon_ml_tpu.telemetry import metrics as _metrics
from photon_ml_tpu.telemetry import tracing

#: default divergence bound per serving table dtype — the documented
#: quantized-table score-parity tolerances (SERVING.md); float32 takes
#: the loosest documented tolerance
DEFAULT_BOUNDS = {"float32": 5e-2, "bfloat16": 1e-2, "int8": 5e-2}

_CANARY_SECONDS = _metrics.histogram(
    "photon_quality_canary_seconds",
    "Wall seconds of one canary shadow-scoring evaluation (incumbent + "
    "candidate over the request reservoir, at activation time — never "
    "on the score hot path)")
_CANARY_DIVERGENCE = _metrics.gauge(
    "photon_quality_canary_divergence",
    "Max relative score divergence of the last canary-evaluated "
    "candidate vs the incumbent over the request reservoir")
_metrics.mark_host_owned("photon_quality_canary_divergence")
_CANARY_REJECTS = _metrics.counter(
    "photon_quality_canary_rejects_total",
    "Candidate activations refused by the canary gate (divergence past "
    "the bound; the incumbent kept serving)")


class CanaryRejected(RuntimeError):
    """A candidate's shadow scores diverged past the gate's bound — the
    activation is refused like any validation failure."""


@dataclasses.dataclass(frozen=True)
class CanaryConfig:
    """Registry-level canary policy.

    ``gate=False`` (the default) only ANNOTATES activations with the
    measured divergence; ``gate=True`` (``serve_game --canary-gate``)
    refuses past the bound. ``bound=None`` resolves per table dtype from
    :data:`DEFAULT_BOUNDS`. Evaluations below ``min_records`` reservoir
    entries are skipped — a divergence measured on two requests says
    nothing."""

    gate: bool = False
    bound: Optional[float] = None
    min_records: int = 8

    def bound_for(self, table_dtype: str) -> float:
        if self.bound is not None:
            return float(self.bound)
        return DEFAULT_BOUNDS.get(table_dtype, DEFAULT_BOUNDS["float32"])


class RequestReservoir:
    """Bounded uniform sample of recent request records (Algorithm R).

    Thread-safe; ``add`` is O(records) dict-free bookkeeping per call —
    cheap enough to sit on the request path unconditionally."""

    def __init__(self, capacity: int = 256, seed: int = 0):
        self.capacity = int(capacity)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._records: list = []
        self._seen = 0

    def add(self, records: Sequence[dict]) -> None:
        with self._lock:
            for rec in records:
                self._seen += 1
                if len(self._records) < self.capacity:
                    self._records.append(rec)
                else:
                    j = self._rng.randrange(self._seen)
                    if j < self.capacity:
                        self._records[j] = rec

    def sample(self) -> list:
        with self._lock:
            return list(self._records)

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)


def score_divergence(incumbent_scores, candidate_scores) -> float:
    """Max relative divergence, ``max |cand - inc| / max(|inc|, 1)`` —
    the same normalization the quantized-table score-parity gates use,
    so the default bounds mean the same thing they mean there."""
    a = np.asarray(incumbent_scores, np.float64)
    b = np.asarray(candidate_scores, np.float64)
    if a.shape != b.shape:
        raise ValueError(f"score shapes differ: {a.shape} vs {b.shape}")
    if a.size == 0:
        return 0.0
    return float(np.max(np.abs(b - a) / np.maximum(np.abs(a), 1.0)))


def run_canary(incumbent_score: Callable, candidate_score: Callable,
               records: Sequence[dict], *, bound: float, gate: bool,
               candidate_dir: str, bus=None) -> dict:
    """Shadow-score ``records`` through both engines and judge the
    candidate. Returns the annotation dict (divergence, bound, verdict,
    wall seconds); raises :class:`CanaryRejected` past the bound under
    ``gate``. The evaluation is timed into
    ``photon_quality_canary_seconds`` and spanned as ``quality.canary``
    (the quality report renders the span history)."""
    records = list(records)
    with _CANARY_SECONDS.time() as timer, \
            tracing.span("quality.canary", candidate=candidate_dir) as sp:
        base = incumbent_score(records)
        cand = candidate_score(records)
        divergence = score_divergence(base, cand)
        verdict = ("pass" if divergence <= bound
                   else ("rejected" if gate else "divergent"))
        sp.set(divergence=round(divergence, 6), bound=bound,
               n=len(records), verdict=verdict)
    _CANARY_DIVERGENCE.set(divergence)
    result = {"divergence": divergence, "bound": bound,
              "n": len(records), "verdict": verdict,
              "seconds": timer.seconds}
    if bus is not None:
        bus.post("canary_evaluated", candidate=candidate_dir, **result)
    if verdict == "rejected":
        _CANARY_REJECTS.inc()
        raise CanaryRejected(
            f"canary: candidate {candidate_dir!r} diverges "
            f"{divergence:.4g} (> bound {bound:.4g}) from the incumbent "
            f"over {len(records)} reservoir records — activation refused; "
            f"widen --canary-bound if this is an intended model change")
    return result
