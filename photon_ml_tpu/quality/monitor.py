"""Online model-quality monitors: live score distribution vs baseline.

The serving engine hands every scored batch's host-side facts — total
scores, per-coordinate cold-start (fallback-row) hits, per-shard feature
coverage — to a :class:`QualityMonitor` (one per model version, attached
by the registry at load time). The monitor accumulates them into
``photon_quality_*`` metric families AND into its own host accumulators;
the metric updates are a handful of numpy reductions and counter
increments per batch, off the jitted path entirely, so the f32 bit-parity
and zero-recompile contracts are untouched (tests/test_quality.py locks
both).

A :class:`DriftEvaluator` — a background ``Event.wait`` thread, started
by ``serve_game --quality-poll-s`` — periodically folds the ACTIVE
version's accumulators against its train-time baseline
(:mod:`photon_ml_tpu.quality.baseline`, the one home of the PSI/KS
arithmetic — hygiene rule 6) into
``photon_quality_drift_score{coordinate, kind}`` gauges and posts a
``quality_drift_detected`` event on the registry's bus when the
total-score PSI crosses the threshold; the telemetry bridge counts those
into ``photon_quality_drift_events_total``. Gauges are host-owned, so a
fleet fold fans each serving host's drift out under a ``process`` label
instead of overwriting it (``telemetry/aggregate.py``).
"""

from __future__ import annotations

import threading
from typing import Mapping, Optional, Tuple

import numpy as np

from photon_ml_tpu.quality.baseline import (
    QualityBaseline,
    bin_scores,
    ks_statistic,
    population_stability_index,
)
from photon_ml_tpu.telemetry import metrics as _metrics

#: kinds rendered into the drift gauge; ``coordinate`` is the coordinate
#: id for cold_start, the feature-shard id for coverage, and the
#: ``__total__`` sentinel for whole-score-distribution kinds
TOTAL_COORDINATE = "__total__"

#: PSI rule-of-thumb default: > 0.25 is conventionally "significant
#: population shift"; serve_game exposes it as --drift-threshold
DEFAULT_DRIFT_THRESHOLD = 0.25

_SCORED_ROWS = _metrics.counter(
    "photon_quality_scored_rows_total",
    "Rows whose scores the online quality monitor accumulated (engine "
    "side — warmup padding excluded)")
_SCORE_BINS = _metrics.counter(
    "photon_quality_scores_total",
    "Live total-score histogram over the active baseline's equal-mass "
    "bins (bin = index into quality-baseline.json scoreBins)",
    labels=("bin",))
_COLD_START = _metrics.counter(
    "photon_quality_cold_start_total",
    "Scored rows that landed on a coordinate's zero fallback row "
    "(unknown or missing entity id — the GLMix cold-start path)",
    labels=("coordinate",))
_COVERAGE = _metrics.gauge(
    "photon_quality_feature_coverage_ratio",
    "Running mean fraction of nonzero design cells in live requests, "
    "per feature shard (compare with the baseline's coverage)",
    labels=("shard",))
_metrics.mark_host_owned("photon_quality_feature_coverage_ratio")
_DRIFT = _metrics.gauge(
    "photon_quality_drift_score",
    "Live-vs-baseline drift of the active model's predictions: "
    "PSI/KS/mean_shift of the total-score distribution "
    "(coordinate=__total__), per-coordinate cold-start rate deltas, "
    "per-shard coverage deltas", labels=("coordinate", "kind"))
_metrics.mark_host_owned("photon_quality_drift_score")


class QualityMonitor:
    """Per-model-version accumulator of live prediction-quality signals.

    Thread-safe (serving scores from HTTP worker threads); all updates
    are host numpy over arrays the engine already holds. Without a
    baseline the score histogram has no bins, but cold-start, coverage
    and row counting still accumulate — partial observability beats
    none."""

    def __init__(self, baseline: Optional[QualityBaseline] = None):
        self.baseline = baseline
        self._lock = threading.Lock()
        self._edges = (np.asarray(baseline.edges, np.float64)
                       if baseline is not None and baseline.edges else None)
        self._counts = (np.zeros(len(baseline.proportions), np.float64)
                        if baseline is not None and baseline.proportions
                        else None)  # guarded-by: _lock
        self._rows = 0  # guarded-by: _lock
        self._score_sum = 0.0  # guarded-by: _lock
        self._cold: dict[str, int] = {}  # guarded-by: _lock
        self._cov_nnz: dict[str, int] = {}  # guarded-by: _lock
        self._cov_cells: dict[str, int] = {}  # guarded-by: _lock

    # --- accumulation (engine side) ---------------------------------------
    def observe(self, scores: np.ndarray,
                cold: Mapping[str, int] = (),
                coverage: Mapping[str, Tuple[int, int]] = ()) -> None:
        """Fold one scored batch in: ``scores`` are the engine's final
        per-row totals, ``cold`` per-coordinate fallback-row hit counts,
        ``coverage`` per-shard ``(nonzero cells, total cells)``."""
        scores = np.asarray(scores, np.float64)
        n = int(scores.size)
        if n == 0:
            return
        binned = (bin_scores(scores, self._edges)
                  if self._edges is not None else None)
        with self._lock:
            self._rows += n
            self._score_sum += float(scores.sum())
            if binned is not None and self._counts is not None:
                self._counts += binned
            for cid, c in dict(cold).items():
                self._cold[cid] = self._cold.get(cid, 0) + int(c)
            for sid, (nnz, cells) in dict(coverage).items():
                self._cov_nnz[sid] = self._cov_nnz.get(sid, 0) + int(nnz)
                self._cov_cells[sid] = (self._cov_cells.get(sid, 0)
                                        + int(cells))
            cov_view = {sid: (self._cov_nnz[sid], self._cov_cells[sid])
                        for sid in self._cov_cells}
        # metric exports outside the monitor lock (registry children take
        # their own locks; ordering across families is not load-bearing)
        _SCORED_ROWS.inc(n)
        if binned is not None:
            for i, c in enumerate(binned):
                if c:
                    _SCORE_BINS.labels(bin=str(i)).inc(float(c))
        for cid, c in dict(cold).items():
            if c:
                _COLD_START.labels(coordinate=cid).inc(int(c))
        for sid, (nnz, cells) in cov_view.items():
            if cells:
                _COVERAGE.labels(shard=sid).set(nnz / cells)

    # --- evaluation (background side) -------------------------------------
    @property
    def n_rows(self) -> int:
        with self._lock:
            return self._rows

    def drift_scores(self, min_rows: int = 1) -> dict:
        """``{(coordinate, kind): score}`` of the live accumulation vs
        the baseline; empty without a baseline or below ``min_rows``
        (drift over a handful of requests is noise, not signal)."""
        b = self.baseline
        if b is None:
            return {}
        with self._lock:
            rows = self._rows
            counts = None if self._counts is None else self._counts.copy()
            score_sum = self._score_sum
            cold = dict(self._cold)
            cov = {sid: (self._cov_nnz[sid], self._cov_cells[sid])
                   for sid in self._cov_cells}
        if rows < max(min_rows, 1):
            return {}
        out: dict = {}
        if counts is not None and counts.sum() > 0:
            out[(TOTAL_COORDINATE, "psi")] = population_stability_index(
                b.proportions, counts)
            out[(TOTAL_COORDINATE, "ks")] = ks_statistic(
                b.proportions, counts)
        out[(TOTAL_COORDINATE, "mean_shift")] = (
            abs(score_sum / rows - b.mean_score)
            / max(b.std_score, 1e-9))
        for cid, base_rate in (b.cold_rates or {}).items():
            out[(cid, "cold_start")] = abs(cold.get(cid, 0) / rows
                                           - base_rate)
        for sid, base_cov in (b.coverage or {}).items():
            nnz, cells = cov.get(sid, (0, 0))
            if cells:
                out[(sid, "coverage")] = abs(nnz / cells - base_cov)
        return out


class DriftEvaluator:
    """Background evaluator: periodically renders the active version's
    drift into gauges and raises the alarm past the threshold.

    Waiting uses ``threading.Event.wait`` (serving code never sleeps —
    hygiene) and evaluation reads only host accumulators — zero device
    work, zero effect on the score path."""

    def __init__(self, registry, *,
                 threshold: float = DEFAULT_DRIFT_THRESHOLD,
                 min_rows: int = 50, poll_s: float = 30.0):
        self.registry = registry
        self.threshold = float(threshold)
        self.min_rows = int(min_rows)
        self.poll_s = float(poll_s)
        #: the evaluator thread and synchronous callers (tests, a manual
        #: evaluate_once) both touch these — the lock-discipline pass
        #: flagged the bare writes, so they now share a lock
        self._lock = threading.Lock()
        self.n_detections = 0  # guarded-by: _lock
        self._stop = threading.Event()
        #: start/stop are operator-lifecycle calls from one control thread
        self._thread: Optional[threading.Thread] = None  # guarded-by: caller
        self.last: dict = {}  # guarded-by: _lock

    def evaluate_once(self) -> dict:
        """One evaluation pass: compute drift scores for the active
        version, set the gauges, post ``quality_drift_detected`` when the
        total-score PSI crosses the threshold. Directly callable — the
        thread loop is just this on a timer, and tests drive it
        synchronously."""
        sm = self.registry.active_or_none()
        monitor = None if sm is None else getattr(sm.engine, "monitor",
                                                  None)
        if monitor is None:
            return {}
        scores = monitor.drift_scores(min_rows=self.min_rows)
        rank_drift = self._rank_drift(sm, monitor.baseline)
        if rank_drift is not None:
            scores.update(rank_drift)
        for (coordinate, kind), value in scores.items():
            _DRIFT.labels(coordinate=coordinate, kind=kind).set(value)
        psi = scores.get((TOTAL_COORDINATE, "psi"))
        if psi is not None and psi > self.threshold:
            with self._lock:
                self.n_detections += 1
            # the payload names WHAT drifted (coordinate/kind/drift) so a
            # bus subscriber — the feedback autopilot above all — can act
            # without re-scraping /metrics; psi/ks stay for back-compat
            self.registry.bus.post(
                "quality_drift_detected", version=sm.version,
                kind="psi", coordinate=TOTAL_COORDINATE,
                drift=round(psi, 6),
                psi=round(psi, 6),
                ks=round(scores.get((TOTAL_COORDINATE, "ks"), 0.0), 6),
                threshold=self.threshold, rows=monitor.n_rows)
        if rank_drift is not None:
            # the ranked workload's alarm rides the SAME event path — one
            # subscriber (and the bridge counter) covers both kinds
            for (coordinate, kind), value in rank_drift.items():
                if kind == "rank_overlap" and value > self.threshold:
                    with self._lock:
                        self.n_detections += 1
                    self.registry.bus.post(
                        "quality_drift_detected", version=sm.version,
                        kind="rank_overlap", coordinate=coordinate,
                        drift=round(value, 6), threshold=self.threshold)
        with self._lock:
            self.last = {f"{c}/{k}": v for (c, k), v in scores.items()}
        return scores

    def _rank_drift(self, sm, baseline) -> "Optional[dict]":
        """``{(item coordinate, "rank_overlap"): 1 - mean overlap}`` of
        the probe users' LIVE top-k against the reference lists the full
        model load pinned (quality/baseline.py) — None when the version
        has no rank engine or no reference. Re-ranks the probes through
        the active engine: a patched item table that reshuffles retrieval
        shows up here even when the score distribution stays flat."""
        rank_engine = getattr(sm, "rank_engine", None)
        if rank_engine is None or baseline is None \
                or not baseline.rank_probes or baseline.rank_k < 1:
            return None
        from photon_ml_tpu.quality.baseline import (
            rank_probe_records,
            topk_overlap,
        )

        users = list(baseline.rank_probes)
        k = min(baseline.rank_k, rank_engine.max_k)
        try:
            results = rank_engine.rank(
                rank_probe_records(users, rank_engine.user_entity_types),
                [k] * len(users))
        except Exception:
            import logging

            logging.getLogger(__name__).exception(
                "rank-drift probe ranking failed; skipping this pass")
            return None
        overlap = float(np.mean([
            topk_overlap(baseline.rank_probes[u], ids)
            for u, (ids, _) in zip(users, results)])) if users else 1.0
        return {(rank_engine.index.coordinate_id, "rank_overlap"):
                1.0 - overlap}

    # --- lifecycle --------------------------------------------------------
    def start(self) -> "DriftEvaluator":
        def loop() -> None:
            while not self._stop.wait(self.poll_s):
                try:
                    self.evaluate_once()
                except Exception:
                    import logging

                    logging.getLogger(__name__).exception(
                        "drift evaluation failed; will retry")

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="photon-quality-drift")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
