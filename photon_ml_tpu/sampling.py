"""Down-sampling for fixed-effect training data.

Re-design of the reference's samplers
(``photon-api/.../sampling/{DownSampler, BinaryClassificationDownSampler,
DefaultDownSampler}.scala``): the reference materializes a down-sampled RDD
per CD iteration; here sampling is a fresh per-sweep weight vector — rows
dropped get weight 0 (exactly absent from the objective), kept rows are
re-weighted by ``1/rate`` so the objective stays an unbiased estimate.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class DownSampler:
    """Uniform down-sampler (reference ``DefaultDownSampler``)."""

    rate: float
    seed: int = 20260729

    def __post_init__(self):
        if not 0.0 < self.rate < 1.0:
            raise ValueError(f"down-sampling rate must be in (0, 1): {self.rate}")

    def downsample(self, labels: np.ndarray, weights: np.ndarray,
                   sweep: int = 0) -> np.ndarray:
        """``sweep`` must vary per CD iteration so each sweep draws a fresh
        sample (the reference creates a new sampled RDD per iteration)."""
        rng = np.random.default_rng((self.seed, sweep))
        # size=shape (not shape[0]): the sharded fixed-effect path hands in
        # the stacked (n_shards, per) layout
        keep = rng.uniform(size=labels.shape) < self.rate
        out = np.where(keep, weights / self.rate, 0.0).astype(np.float32)
        return out


@dataclasses.dataclass(frozen=True)
class BinaryClassificationDownSampler(DownSampler):
    """Negative-class down-sampler for dominant-negative binary data
    (reference ``BinaryClassificationDownSampler``): positives always kept;
    negatives kept with probability ``rate`` and re-weighted ``1/rate``."""

    def downsample(self, labels: np.ndarray, weights: np.ndarray,
                   sweep: int = 0) -> np.ndarray:
        rng = np.random.default_rng((self.seed, sweep))
        pos = labels > 0.5
        keep_neg = rng.uniform(size=labels.shape) < self.rate
        out = np.where(pos, weights,
                       np.where(keep_neg, weights / self.rate, 0.0))
        return out.astype(np.float32)
