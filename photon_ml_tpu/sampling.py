"""Down-sampling for fixed-effect training data.

Re-design of the reference's samplers
(``photon-api/.../sampling/{DownSampler, BinaryClassificationDownSampler,
DefaultDownSampler}.scala``): the reference materializes a down-sampled RDD
per CD iteration; here sampling is a fresh per-sweep weight vector — rows
dropped get weight 0 (exactly absent from the objective), kept rows are
re-weighted by ``1/rate`` so the objective stays an unbiased estimate.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from photon_ml_tpu.util import hash_uniform


def _sweep_uniform(uids: np.ndarray, seed: int, sweep: int) -> np.ndarray:
    """Per-row uniform draw keyed by (seed, sweep, global row id) — a pure
    per-row function, so the kept set is identical under any row partition
    (the property multi-process training's sp==mp equality rests on)."""
    return hash_uniform(
        np.maximum(np.asarray(uids, np.int64), 0),
        seed ^ ((sweep + 1) * 0x5851F42D4C957F2D) & 0x7FFFFFFFFFFFFFFF)


@dataclasses.dataclass(frozen=True)
class DownSampler:
    """Uniform down-sampler (reference ``DefaultDownSampler``)."""

    rate: float
    seed: int = 20260729

    def __post_init__(self):
        if not 0.0 < self.rate < 1.0:
            raise ValueError(f"down-sampling rate must be in (0, 1): {self.rate}")

    def _keep(self, labels: np.ndarray, sweep: int,
              uids: Optional[np.ndarray]) -> np.ndarray:
        """``sweep`` must vary per CD iteration so each sweep draws a fresh
        sample (the reference creates a new sampled RDD per iteration).
        With ``uids`` (global row ids, same shape as ``labels``; negatives
        = padding) the draw is the counter-based per-row hash — identical
        under any row partition; without, a sequential rng stream over the
        batch shape (direct API use)."""
        if uids is not None:
            return _sweep_uniform(uids, self.seed, sweep) < self.rate
        rng = np.random.default_rng((self.seed, sweep))
        # size=shape (not shape[0]): the sharded fixed-effect path hands in
        # the stacked (n_shards, per) layout
        return rng.uniform(size=labels.shape) < self.rate

    def downsample(self, labels: np.ndarray, weights: np.ndarray,
                   sweep: int = 0,
                   uids: Optional[np.ndarray] = None) -> np.ndarray:
        keep = self._keep(labels, sweep, uids)
        return np.where(keep, weights / self.rate, 0.0).astype(np.float32)


@dataclasses.dataclass(frozen=True)
class BinaryClassificationDownSampler(DownSampler):
    """Negative-class down-sampler for dominant-negative binary data
    (reference ``BinaryClassificationDownSampler``): positives always kept;
    negatives kept with probability ``rate`` and re-weighted ``1/rate``."""

    def downsample(self, labels: np.ndarray, weights: np.ndarray,
                   sweep: int = 0,
                   uids: Optional[np.ndarray] = None) -> np.ndarray:
        pos = labels > 0.5
        keep_neg = self._keep(labels, sweep, uids)
        out = np.where(pos, weights,
                       np.where(keep_neg, weights / self.rate, 0.0))
        return out.astype(np.float32)
