"""Version-compat shims over the moving jax API surface.

The framework targets current jax, but the resilience story includes not
falling over on the trailing versions real clusters run. Everything
version-dependent is funneled through here so call sites stay on ONE
spelling:

- ``shard_map``: top-level export (jax >= 0.6) vs
  ``jax.experimental.shard_map`` (older), and the replication-check kwarg
  rename ``check_rep`` -> ``check_vma``.
- ``jax.sharding.AxisType`` is handled in :mod:`photon_ml_tpu.parallel.mesh`
  (mesh construction is the only consumer).
"""

from __future__ import annotations

import inspect

try:  # jax >= 0.6 exports shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # older jax: the experimental home
    from jax.experimental.shard_map import shard_map as _shard_map

_REP_KWARG = ("check_vma"
              if "check_vma" in inspect.signature(_shard_map).parameters
              else "check_rep")


def shard_map(f, *, check_vma=None, **kwargs):
    """``jax.shard_map`` with the modern ``check_vma`` spelling on every
    jax version this package supports."""
    if check_vma is not None:
        kwargs[_REP_KWARG] = check_vma
    return _shard_map(f, **kwargs)
