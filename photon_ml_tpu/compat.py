"""Version-compat shims over the moving jax API surface.

The framework targets current jax, but the resilience story includes not
falling over on the trailing versions real clusters run. Everything
version-dependent is funneled through here so call sites stay on ONE
spelling:

- ``shard_map``: top-level export (jax >= 0.6) vs
  ``jax.experimental.shard_map`` (older), and the replication-check kwarg
  rename ``check_rep`` -> ``check_vma``.
- ``typeof``: ``jax.typeof`` (jax >= 0.6's public aval accessor, whose
  result carries the ``vma`` varying-manual-axes set inside ``shard_map``
  bodies) vs ``jax.core.get_aval`` (older jax: same aval, no ``vma`` —
  callers must treat the attribute as optional).
- ``jax.sharding.AxisType`` is handled in :mod:`photon_ml_tpu.parallel.mesh`
  (mesh construction is the only consumer).
"""

from __future__ import annotations

import inspect

try:  # jax >= 0.6 exports shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # older jax: the experimental home
    from jax.experimental.shard_map import shard_map as _shard_map

_REP_KWARG = ("check_vma"
              if "check_vma" in inspect.signature(_shard_map).parameters
              else "check_rep")


def shard_map(f, *, check_vma=None, **kwargs):
    """``jax.shard_map`` with the modern ``check_vma`` spelling on every
    jax version this package supports."""
    if check_vma is not None:
        kwargs[_REP_KWARG] = check_vma
    return _shard_map(f, **kwargs)


def typeof(x):
    """``jax.typeof`` on every supported jax version.

    Returns the abstract value of ``x``. On jax versions that predate the
    top-level export the result comes from ``jax.core.get_aval`` and does
    NOT carry a ``vma`` attribute — read it with
    ``getattr(typeof(x), "vma", ...)`` (exactly how ``ops/pallas_glm.py``
    threads varying manual axes into its kernel out-structs)."""
    import jax

    fn = getattr(jax, "typeof", None)
    if fn is not None:
        return fn(x)
    from jax.core import get_aval

    return get_aval(x)
