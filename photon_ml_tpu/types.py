"""Core enums and type aliases shared across the framework.

Mirrors the vocabulary of the reference's top-level enums
(``photon-api/src/main/scala/com/linkedin/photon/ml/TaskType.scala``,
``photon-lib/.../optimization/OptimizerType.scala``,
``photon-lib/.../optimization/RegularizationType.scala``,
``photon-api/.../normalization/NormalizationType.scala``,
``photon-api/.../optimization/VarianceComputationType.scala``).
"""

from __future__ import annotations

import enum


class TaskType(enum.Enum):
    """Supported training task (loss family + link function)."""

    LOGISTIC_REGRESSION = "LOGISTIC_REGRESSION"
    LINEAR_REGRESSION = "LINEAR_REGRESSION"
    POISSON_REGRESSION = "POISSON_REGRESSION"
    SMOOTHED_HINGE_LOSS_LINEAR_SVM = "SMOOTHED_HINGE_LOSS_LINEAR_SVM"


class OptimizerType(enum.Enum):
    LBFGS = "LBFGS"
    OWLQN = "OWLQN"  # selected implicitly by L1/elastic-net in the reference
    TRON = "TRON"


class RegularizationType(enum.Enum):
    NONE = "NONE"
    L1 = "L1"
    L2 = "L2"
    ELASTIC_NET = "ELASTIC_NET"


class NormalizationType(enum.Enum):
    NONE = "NONE"
    SCALE_WITH_STANDARD_DEVIATION = "SCALE_WITH_STANDARD_DEVIATION"
    SCALE_WITH_MAX_MAGNITUDE = "SCALE_WITH_MAX_MAGNITUDE"
    STANDARDIZATION = "STANDARDIZATION"


class VarianceComputationType(enum.Enum):
    NONE = "NONE"
    SIMPLE = "SIMPLE"  # diagonal-Hessian inverse approximation
    FULL = "FULL"  # full-Hessian inverse (small feature dims only)


class DataValidationType(enum.Enum):
    """Row-level input validation policy.

    Reference: ``photon-client/.../DataValidators.scala``.
    """

    VALIDATE_FULL = "VALIDATE_FULL"
    VALIDATE_SAMPLE = "VALIDATE_SAMPLE"
    VALIDATE_DISABLED = "VALIDATE_DISABLED"


# Reference constants (photon-client/.../Constants.scala).
INTERCEPT_NAME = "(INTERCEPT)"
INTERCEPT_TERM = ""
#: Delimiter joining (name, term) into a single feature key, as in the
#: reference's ``Constants.scala`` (the \x01 control char keeps keys injective over (name, term)
#: pairs; glyph pending mount verification).
NAME_TERM_DELIMITER = "\x01"


def feature_key(name: str, term: str = "") -> str:
    """Canonical string key for a ``(name, term)`` feature pair."""
    return f"{name}{NAME_TERM_DELIMITER}{term}"


INTERCEPT_KEY = feature_key(INTERCEPT_NAME, INTERCEPT_TERM)
