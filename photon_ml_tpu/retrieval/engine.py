"""Jitted top-k ranking engine: user margins against every item, on device.

One ranking call scores a user record against EVERY row of the
:class:`~photon_ml_tpu.retrieval.index.ItemIndex` and returns the k best
— one device program: per-coordinate margins exactly as the scoring
engine computes them (the user-side coordinates broadcast over the item
axis; the item coordinate is a dequantizing matmul against the padded
item matrix), summed through the one score-summation home
:func:`~photon_ml_tpu.game.model.sum_coordinate_margins`, padding masked
to ``-inf``, then ``jax.lax.top_k``.

**Parity contract** (SERVING.md "Ranked retrieval"): at f32 tables the
returned ids and scores are bit-identical to brute-force scoring every
(user record, item id) pair through the serving score path (itself
bit-identical to ``GameModel.score`` / ``score_game``) and stable-sorting
descending in item-axis order — ``lax.top_k`` breaks ties toward the
lower item position, ``np.argsort(-scores, kind="stable")`` is the
reference. Quantized tables hold the documented store tolerances
(bf16 ≤ 1e-2, int8 ≤ 5e-2 relative).

**Zero-recompile contract.** Trace signatures vary over exactly three
bucketed axes: power-of-two user-batch buckets (≤ ``max_batch``),
power-of-two k buckets (≤ ``max_k``), and the index's padded item axis.
:meth:`warmup` pre-traces the whole grid; the live item count rides as a
*traced* scalar, so an ``apply_patch`` that grows the vocabulary inside
the padding changes no shape. Better still, patch-derived versions SHARE
the parent's jit (``share_from`` — model parameters are jit arguments,
so the executables are version-agnostic): activating a patch performs
zero compiles, not merely zero steady-state ones. Traces count under
``photon_compiles_total{fn="serving.rank"}`` (the scoring engine's
``record_compile`` idiom — the serving bench and tier-1 assert on it).
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence

import numpy as np

from photon_ml_tpu.game.model import FixedEffectModel, sum_coordinate_margins
from photon_ml_tpu.resilience.faults import fault_point
from photon_ml_tpu.retrieval.index import ItemIndex
from photon_ml_tpu.serving import store as _store
from photon_ml_tpu.serving.engine import ScoringEngine, next_bucket
from photon_ml_tpu.telemetry import metrics as _metrics
from photon_ml_tpu.telemetry import profiling as _profiling

#: engine-side ranking latency per (user-bucket, k-bucket) dispatch
#: (pad + jit dispatch + D2H of the top-k ids/scores)
_RANK_LATENCY = _metrics.histogram(
    "photon_rank_engine_latency_seconds",
    "Engine ranking time per padded (user-bucket, k-bucket) dispatch",
    labels=("bucket", "k_bucket"))

#: the ranked path feeds the same request-path stage family as /score
#: (this module owns batch_assemble and execute for /rank)
_STAGE_SECONDS = _metrics.histogram(
    "photon_serving_stage_seconds",
    "Serving request time per request-path stage "
    "(parse | queue_wait | batch_assemble | execute | respond)",
    labels=("stage",))

#: the fn label ranking traces count under — same
#: ``photon_compiles_total{fn}`` family as training and ``serving.score``
#: (telemetry/profiling.py), so one scrape expression covers every
#: recompile contract in the system
RANKING_FN_LABEL = "serving.rank"


class RankingEngine:
    """Ranks user records against one model version's full item axis.

    Built next to (and from) the version's
    :class:`~photon_ml_tpu.serving.engine.ScoringEngine`: request packing
    and the device parameter pytree are the scoring engine's own, so the
    ranked path can never skew from the scored one. Thread-safe;
    hot-swapping installs a fresh engine per version, but patch-derived
    versions pass ``share_from=`` to reuse the parent's executables
    (parameters are jit arguments — the compiled programs are
    version-agnostic)."""

    def __init__(self, engine: ScoringEngine, index: ItemIndex, *,
                 max_k: int = 128, max_batch: int = 8,
                 share_from: Optional["RankingEngine"] = None):
        import jax
        import jax.numpy as jnp

        self.engine = engine
        self.model = engine.model
        self.index = index
        self.max_k = next_bucket(max_k)
        self.max_batch = next_bucket(max_batch)
        cm = self.model.coordinates.get(index.coordinate_id)
        if cm is None or isinstance(cm, FixedEffectModel):
            raise ValueError(
                f"rank coordinate {index.coordinate_id!r} is not a "
                f"random-effect coordinate of this model "
                f"(have {sorted(self.model.coordinates)})")
        if cm.random_effect_type != index.random_effect_type:
            raise ValueError(
                f"index entity type {index.random_effect_type!r} != "
                f"coordinate's {cm.random_effect_type!r}")
        self._coords = list(self.model.coordinates.items())
        self._shard_order = [c.shard_id for c in engine.shard_configs]
        self._re_order = [cid for cid, m in self._coords
                          if not isinstance(m, FixedEffectModel)]
        #: RE coordinates whose rows the trace consumes (the item
        #: coordinate's row comes from the item axis, not the request)
        self._rank_re_order = [cid for cid in self._re_order
                               if cid != index.coordinate_id]
        self._re_pick = [self._re_order.index(cid)
                         for cid in self._rank_re_order]
        #: entity types a bare ``/rank?user=`` id is applied to (every
        #: non-item coordinate — the single-user-entity GLMix case; mixed
        #: entity universes POST a full record instead)
        self.user_entity_types = tuple(dict.fromkeys(
            self.model.coordinates[cid].random_effect_type
            for cid in self._rank_re_order))
        # the scoring engine's device parameters, shared by reference —
        # same tables, same (table, scales) pairs, same fe vectors — the
        # ranked and scored paths cannot drift apart. The item
        # coordinate's STORE is deliberately excluded: its rows reach the
        # trace through the index matrix, and its dense table's leading
        # dim grows when a patch appends entities — keeping it out of the
        # argument pytree keeps patch activations signature-stable
        self._params = {
            "fe": engine._params["fe"],
            "re": {cid: engine._params["re"][cid]
                   for cid in self._rank_re_order},
        }
        self._lock = threading.Lock()
        self._n_ranked = 0  # guarded-by: _lock
        root = (share_from._root if share_from is not None
                and self._trace_compatible(share_from) else None)
        if root is not None:
            # patch-derived version: the parent's executables fit this
            # version exactly (parameters are arguments), so activation
            # compiles NOTHING — compile accounting stays on the root
            self._root = root
            self._rank_jit = root._rank_jit
            return
        self._root = self
        #: bumped from inside the traced body (trace time only — jit
        #: serializes traces), so it is deliberately NOT lock-annotated
        self._compile_count = 0
        accum = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
        item_cid = index.coordinate_id

        def _rank_padded(params, item_params, static, offsets, xs, rows,
                         n_items, k):
            # body runs at TRACE time only — one increment per compiled
            # (user bucket, k bucket, item bucket) shape
            self._compile_count += 1
            _profiling.record_compile(RANKING_FN_LABEL)
            i_x = {sid: i for i, sid in enumerate(self._shard_order)}
            i_r = {cid: i for i, cid in enumerate(self._rank_re_order)}
            item_rows = jnp.arange(item_params[0].shape[0])
            margins = []
            for cid, m_ in self._coords:
                x = xs[i_x[m_.feature_shard_id]].astype(accum)
                if isinstance(m_, FixedEffectModel):
                    m = (x @ params["fe"][cid].astype(accum))[:, None]
                elif cid == item_cid:
                    # the retrieval matmul: every item's (possibly
                    # quantized) row dequantizes through the one numeric
                    # home and contracts against the user's features
                    tab = _store.gather_rows(item_params, item_rows, accum)
                    m = jnp.sum(x[:, None, :] * tab[None, :, :], axis=2)
                else:
                    tab = _store.gather_rows(params["re"][cid],
                                             rows[i_r[cid]], accum)
                    m = jnp.sum(x * tab, axis=1)[:, None]
                margins.append(m.astype(jnp.float32))
            # the one score-summation contract, broadcast over the item
            # axis; the static vector rides as a trailing f64 term (all
            # zeros without an item-feature source — then x + 0.0 leaves
            # the brute-force pair scores bit-identical)
            total = sum_coordinate_margins(
                offsets[:, None], margins + [static[None, :]], xp=jnp)
            masked = jnp.where(item_rows[None, :] < n_items, total,
                               -jnp.inf)
            return jax.lax.top_k(masked, k)

        self._rank_jit = jax.jit(_rank_padded, static_argnums=(7,))

    def _trace_compatible(self, other: "RankingEngine") -> bool:
        """May this version reuse ``other``'s jit? True when every trace-
        time CONSTANT matches — coordinate ids/kinds in order, shard
        order, the item coordinate — i.e. for any patch of the same
        model structure. Shapes need not match: a grown item bucket is
        just a new signature in the shared cache."""
        return (
            [(cid, isinstance(m, FixedEffectModel))
             for cid, m in self._coords]
            == [(cid, isinstance(m, FixedEffectModel))
                for cid, m in other._coords]
            and self._shard_order == other._shard_order
            and self.index.coordinate_id == other.index.coordinate_id
            and self._rank_re_order == other._rank_re_order)

    # --- stats ------------------------------------------------------------
    @property
    def compile_count(self) -> int:
        """Distinct ranking traces of this engine's (possibly shared)
        executable cache. Constant after :meth:`warmup`; a patch-derived
        engine reports its root's counter — activation adds zero."""
        return self._root._compile_count

    @property
    def n_ranked(self) -> int:
        with self._lock:
            return self._n_ranked

    @property
    def user_re_coordinates(self) -> tuple:
        """Random-effect coordinates consumed from the REQUEST side (all
        but the item coordinate). Surfaced through ``/healthz`` because
        they gate fleet rank fan-out: on an entity-sharded host such a
        coordinate's store holds only its shard's users, so a foreign
        host would silently rank with the user's margin zeroed — the
        routing tier refuses that configuration instead of mis-ranking
        (SERVING.md "Fleet serving")."""
        return tuple(self._rank_re_order)

    # --- ranking ----------------------------------------------------------
    def rank(self, records: Sequence[dict], ks: Sequence[int]):
        """Top-k per record: ``[(ids, scores), ...]`` with ``ids`` raw
        item ids (best first) and ``scores`` their f32 totals. ``ks``
        aligns with ``records`` (a coalesced batch may mix k's — the
        program runs at the batch's max k bucket and each request slices
        its own k)."""
        # the same serving-side chaos site /score visits: an injected
        # fault fails this rank batch only (its Futures get the error,
        # the batcher worker survives, the incumbent keeps serving)
        fault_point("serving.execute", n=len(records), kind="rank")
        with _STAGE_SECONDS.labels(stage="batch_assemble").time():
            batch = self.engine.pack(records)
        return self.rank_batch(batch, ks)

    def rank_batch(self, batch, ks: Sequence[int]):
        ks = [int(k) for k in ks]
        if len(ks) != batch.n:
            raise ValueError(f"{len(ks)} k values for {batch.n} records")
        for k in ks:
            if not 1 <= k <= self.max_k:
                raise ValueError(f"k must be in [1, {self.max_k}], got {k}")
        out = []
        with _STAGE_SECONDS.labels(stage="execute").time():
            for lo in range(0, batch.n, self.max_batch):
                hi = min(lo + self.max_batch, batch.n)
                out.extend(self._rank_chunk(batch, ks[lo:hi], lo, hi))
        with self._lock:
            self._n_ranked += batch.n
        return out

    def _rank_chunk(self, batch, ks, lo, hi):
        n = hi - lo
        b = next_bucket(n)
        index = self.index
        k_b = min(next_bucket(max(ks)), self.max_k, index.bucket)
        offsets = np.zeros(b, np.float32)
        offsets[:n] = batch.offsets[lo:hi]
        xs = []
        for x in batch.xs:
            xp = np.zeros((b, x.shape[1]), np.float32)
            xp[:n] = x[lo:hi]
            xs.append(xp)
        rows = []
        for cid, i in zip(self._rank_re_order, self._re_pick):
            rp = np.full(b, self.engine.stores[cid].fallback_row, np.int32)
            rp[:n] = batch.rows[i][lo:hi]
            rows.append(rp)
        n_items = np.asarray(index.n_items, np.int32)
        # the D2H pulls belong inside the timed region: dispatch is async
        with _RANK_LATENCY.labels(bucket=str(b), k_bucket=str(k_b)).time():
            vals, idx = self._rank_jit(
                self._params, index.device_params, index.static, offsets,
                tuple(xs), tuple(rows), n_items, k_b)
            vals = np.asarray(vals)
            idx = np.asarray(idx)
        out = []
        for i in range(n):
            # k may exceed the vocabulary; the padding beyond n_items is
            # -inf-masked so the first n_items slots are always the real
            # items in rank order
            k_i = min(ks[i], index.n_items)
            take = idx[i, :k_i]
            out.append(([index.item_ids[j] for j in take],
                        vals[i, :k_i].astype(np.float32)))
        return out

    def warmup(self) -> int:
        """Pre-trace the whole (user bucket × k bucket) grid over the
        current item axis so live traffic never waits on a compile.
        Returns the number of compiles performed (0 for a patch-derived
        engine whose shapes the shared cache has already seen)."""
        from photon_ml_tpu.serving.engine import RequestBatch

        before = self.compile_count
        b = 1
        while b <= self.max_batch:
            empty = RequestBatch(
                n=b, offsets=np.zeros(b, np.float32),
                xs=tuple(np.zeros(
                    (b, len(self.engine.index_maps[c.shard_id])),
                    np.float32) for c in self.engine.shard_configs),
                rows=tuple(np.full(b, self.engine.stores[cid].fallback_row,
                                   np.int32) for cid in self._re_order))
            k = 1
            while k <= min(self.max_k, self.index.bucket):
                self._rank_chunk(empty, [k] * b, 0, b)
                k <<= 1
            b <<= 1
        return self.compile_count - before
