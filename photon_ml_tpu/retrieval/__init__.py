"""On-device top-k retrieval: the serving store turned into a recommender.

The scoring stack answers the offline-shaped question "score these
(user, item) pairs"; real recommendation traffic asks "best k items for
this user". The dense ``(n_entities + 1, dim)`` device tables the serving
store already pays for make that one device matmul plus ``jax.lax.top_k``
(ROADMAP "On-device top-k retrieval"), and this package is that workload:

- :mod:`~photon_ml_tpu.retrieval.index` — :class:`ItemIndex`: one
  random-effect coordinate's store re-packed item-major — a padded
  per-item coefficient matrix (any ``--table-dtype``, dequantized
  in-trace through the store's one numeric home
  :func:`~photon_ml_tpu.serving.store.gather_rows`), a precomputed
  request-independent static-margin vector, optional sharding over the
  mesh item axis, and O(touched) incremental rebuild on ``apply_patch``;
- :mod:`~photon_ml_tpu.retrieval.engine` — :class:`RankingEngine`: one
  jitted program scoring a user's margins against *every* item row, then
  ``jax.lax.top_k`` — bucketed (power-of-two user batches × k buckets ×
  the padded item axis) under the same zero-recompile contract as
  ``/score``, with compile accounting under
  ``photon_compiles_total{fn="serving.rank"}``.

The HTTP surface (``GET /rank?user=...&k=...``), admission control,
request logging and quality monitoring ride the existing serving stack —
see SERVING.md "Ranked retrieval".
"""

from photon_ml_tpu.retrieval.index import ItemIndex, item_bucket  # noqa: F401
from photon_ml_tpu.retrieval.engine import (  # noqa: F401
    RANKING_FN_LABEL,
    RankingEngine,
)
