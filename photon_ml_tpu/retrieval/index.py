"""Item-major retrieval index over one random-effect coordinate's store.

The serving :class:`~photon_ml_tpu.serving.store.EntityCoefficientStore`
is request-major: a request names an entity, the engine gathers that one
row. Ranking inverts the access pattern — one request touches EVERY
item's row — so the index re-packs the store item-major once per model
version:

- ``matrix`` is a ``(bucket, dim)`` device array of per-item coefficient
  rows in the store's storage dtype (float32 / bfloat16 / int8 with the
  matching per-row ``scales`` vector). Rows stay in storage format; the
  ranking trace dequantizes through the store's one numeric home
  (:func:`~photon_ml_tpu.serving.store.gather_rows`), so a 10M-item int8
  axis is held at a quarter of the f32 bytes and the full-precision
  matrix never exists in HBM.
- The item axis is padded to ``bucket`` (power of two, rounded up to the
  mesh item-axis size when sharded) so ``apply_patch`` growth does not
  change the ranking program's input shapes — the zero-recompile
  contract's item-axis half. Padding rows alias the store's zero
  fallback row and are masked to ``-inf`` before ``top_k``.
- ``static`` is a per-item f32 margin vector of request-INDEPENDENT
  score terms. The per-item intercept needs no entry here — the request
  vector's intercept cell is 1, so it already rides the coefficient
  matmul; the vector carries only terms a user record cannot produce
  (the fixed effect on per-item feature records, an item-side offset),
  and is all zeros when no item feature source is configured — exactly
  the brute-force all-pairs contract ``/rank`` is parity-locked against.
- :meth:`apply_patch` derives the NEXT version's index from a patched
  store by re-gathering ONLY the touched item rows (new items append
  inside the padding headroom) — O(touched), mirroring
  ``EntityCoefficientStore.apply_patch``; overflowing the bucket falls
  back to a full rebuild (one re-trace, at activation time, not in
  steady state).

Item order is load-bearing: ``item_ids`` fixes the axis enumeration and
therefore the tie-break order of ``top_k`` (lower item position first),
which the brute-force parity contract pins (SERVING.md "Ranked
retrieval").
"""

from __future__ import annotations

import dataclasses
from typing import Mapping, Optional, Sequence

import numpy as np

from photon_ml_tpu.serving.store import EntityCoefficientStore


def item_bucket(n: int, multiple: int = 1) -> int:
    """Padded item-axis length: smallest power of two >= max(n, 1),
    rounded up to ``multiple`` (the mesh item-axis size when sharded) so
    every shard holds an equal slice."""
    b = 1 << max(int(n) - 1, 0).bit_length()
    if multiple > 1:
        b += (-b) % int(multiple)
    return b


@dataclasses.dataclass(frozen=True)
class ItemIndex:
    """Immutable per-version retrieval index (one per rank coordinate).

    ``matrix``/``scales`` mirror the store's storage format
    (``device_params`` feeds :func:`serving.store.gather_rows` exactly
    like a store's table does); ``static`` is the f32 request-independent
    margin vector; ``item_ids[i]`` is the raw id at item-axis position
    ``i`` and ``pos_of`` its inverse.
    """

    coordinate_id: str
    random_effect_type: str
    dim: int
    table_dtype: str
    item_ids: tuple
    bucket: int
    matrix: object  # jax.Array (bucket, dim) in table_dtype
    scales: object  # jax.Array (bucket,) f32 — int8 only, else None
    static: object  # jax.Array (bucket,) f32
    static_host: np.ndarray = dataclasses.field(repr=False, compare=False,
                                                default=None)
    pos_of: Mapping[str, int] = dataclasses.field(repr=False, compare=False,
                                                  default_factory=dict)
    #: NamedSharding over the mesh item axis, None when unsharded
    sharding: object = dataclasses.field(repr=False, compare=False,
                                         default=None)

    @property
    def n_items(self) -> int:
        return len(self.item_ids)

    @property
    def device_params(self):
        """``(matrix, scales)`` — consumed through ``store.gather_rows``
        with ``rows = arange(bucket)``, the same dequantize-in-trace path
        the scoring engine uses."""
        return (self.matrix, self.scales)

    @property
    def matrix_bytes(self) -> int:
        """Resident device bytes of the item matrix (+ scales + static) —
        the ranked twin of ``EntityCoefficientStore.table_bytes``."""
        n = int(np.prod(self.matrix.shape)) * self.matrix.dtype.itemsize
        if self.scales is not None:
            n += int(self.scales.shape[0]) * 4
        return n + self.bucket * 4  # static vector

    # --- construction -----------------------------------------------------
    @staticmethod
    def build(store: EntityCoefficientStore, coordinate_id: str, *,
              static_margins: Optional[Mapping[str, float]] = None,
              mesh=None, bucket: Optional[int] = None) -> "ItemIndex":
        """Pack ``store`` item-major. ``static_margins`` maps raw item id
        to its precomputed request-independent margin (absent ids take
        0.0 — the no-item-features default); ``mesh`` shards the item
        axis over :data:`parallel.mesh.ENTITY_AXIS` for vocabularies one
        device cannot hold."""
        import jax.numpy as jnp

        item_ids = tuple(store.row_of_id)
        n = len(item_ids)
        sharding = None
        multiple = 1
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            from photon_ml_tpu.parallel.mesh import ENTITY_AXIS

            axis = (ENTITY_AXIS if ENTITY_AXIS in mesh.shape
                    else next(iter(mesh.shape)))
            multiple = int(mesh.shape[axis])
            sharding = NamedSharding(mesh, PartitionSpec(axis))
        b = item_bucket(n, multiple) if bucket is None else int(bucket)
        if b < max(n, 1):
            raise ValueError(f"bucket {b} < {n} items")
        rows = np.full(b, store.fallback_row, np.int32)
        if n:
            rows[:n] = store.rows_for(list(item_ids))
        # one device gather in STORAGE dtype — no cast, no scale math
        # (that happens in-trace through store.gather_rows); padding rows
        # alias the store's zero fallback row
        rows_d = jnp.asarray(rows)
        matrix = store.table[rows_d]
        scales = None if store.scales is None else store.scales[rows_d]
        static_host = np.zeros(b, np.float32)
        pos_of = {raw: i for i, raw in enumerate(item_ids)}
        for raw, v in (static_margins or {}).items():
            i = pos_of.get(raw)
            if i is not None:
                static_host[i] = np.float32(v)
        static = jnp.asarray(static_host)
        if sharding is not None:
            import jax

            matrix = jax.device_put(matrix, sharding)
            if scales is not None:
                scales = jax.device_put(scales, sharding)
            static = jax.device_put(static, sharding)
        return ItemIndex(
            coordinate_id=coordinate_id,
            random_effect_type=store.random_effect_type, dim=store.dim,
            table_dtype=store.table_dtype, item_ids=item_ids, bucket=b,
            matrix=matrix, scales=scales, static=static,
            static_host=static_host, pos_of=pos_of, sharding=sharding)

    def apply_patch(self, store: EntityCoefficientStore,
                    touched: Sequence[str], *,
                    static_margins: Optional[Mapping[str, float]] = None,
                    ) -> "ItemIndex":
        """Derive the next version's index from the PATCHED store by
        re-gathering only the ``touched`` raw ids' rows (updated, removed
        — their store rows are already zeroed — and new items, which
        append inside the padding headroom). O(touched) like the store's
        own ``apply_patch``; functional — this index's device arrays are
        never mutated. Overflowing the bucket rebuilds from scratch (the
        item axis shape changes, so the next ranking call re-traces once
        at activation time)."""
        if store.random_effect_type != self.random_effect_type:
            raise ValueError(
                f"patch store random-effect type "
                f"{store.random_effect_type!r} != index "
                f"{self.random_effect_type!r}")
        if store.dim != self.dim or store.table_dtype != self.table_dtype:
            raise ValueError(
                f"patch store (dim={store.dim}, dtype="
                f"{store.table_dtype!r}) does not match index (dim="
                f"{self.dim}, dtype={self.table_dtype!r})")
        touched = list(dict.fromkeys(str(t) for t in touched))
        if not touched:
            return self
        new = [raw for raw in touched if raw not in self.pos_of]
        if self.n_items + len(new) > self.bucket:
            carried = dict(zip(self.item_ids,
                               self.static_host[:self.n_items].tolist()))
            carried.update(static_margins or {})
            mesh = None if self.sharding is None else self.sharding.mesh
            return ItemIndex.build(store, self.coordinate_id,
                                   static_margins=carried, mesh=mesh)
        import jax.numpy as jnp

        item_ids = self.item_ids + tuple(new)
        pos_of = dict(self.pos_of)
        for raw in new:
            pos_of[raw] = len(pos_of)
        pos = np.fromiter((pos_of[raw] for raw in touched), np.int32,
                          count=len(touched))
        rows = store.rows_for(touched)
        rows_d = jnp.asarray(rows)
        pos_d = jnp.asarray(pos)
        matrix = self.matrix.at[pos_d].set(store.table[rows_d])
        scales = self.scales
        if store.scales is not None:
            if scales is None:
                raise ValueError("patch store carries scales but the "
                                 "index has none (dtype drift)")
            scales = scales.at[pos_d].set(store.scales[rows_d])
        # touched items keep their prior static margin unless the caller
        # supplies a fresh one (new items start at the padding's 0.0; a
        # removed item's margin is zeroed by passing {raw: 0.0})
        static_host = self.static_host.copy()
        for raw, v in (static_margins or {}).items():
            i = pos_of.get(raw)
            if i is not None:
                static_host[i] = np.float32(v)
        static = jnp.asarray(static_host)
        if self.sharding is not None:
            import jax

            static = jax.device_put(static, self.sharding)
        return dataclasses.replace(
            self, item_ids=item_ids, matrix=matrix, scales=scales,
            static=static, static_host=static_host, pos_of=pos_of)

    # --- static margins ---------------------------------------------------
    @staticmethod
    def static_margins_from_records(engine, records_by_id: Mapping[str, dict],
                                    ) -> dict:
        """Precompute each item's request-independent margin from a
        per-item feature record: the FIXED-effect contribution on the
        item's own features plus the record's offset — the GLMix terms a
        user-side request vector cannot produce. Host numpy over the
        engine's own packing (no online/batch skew); returns
        ``{raw item id: float}`` for :meth:`build`."""
        from photon_ml_tpu.game.model import FixedEffectModel

        if not records_by_id:
            return {}
        raws = list(records_by_id)
        batch = engine.pack([records_by_id[r] for r in raws])
        shard_x = {cfg.shard_id: x
                   for cfg, x in zip(engine.shard_configs, batch.xs)}
        total = np.asarray(batch.offsets, np.float64)
        for cid, cm in engine.model.coordinates.items():
            if not isinstance(cm, FixedEffectModel):
                continue
            w = np.asarray(cm.model.coefficients.means, np.float64)
            m = shard_x[cm.feature_shard_id].astype(np.float64) @ w
            total = total + m.astype(np.float32).astype(np.float64)
        return {raw: float(np.float32(t)) for raw, t in zip(raws, total)}
