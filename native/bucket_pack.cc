// Native random-effect bucket packer: entity-grouped CSR rows -> fixed-shape
// (E, S, D) bucket tensors, exposed through a C ABI consumed via ctypes
// (photon_ml_tpu/native.py).
//
// Role: the build-side hot path of the random-effect dataset
// (photon_ml_tpu/game/data.py::RandomEffectDataset.build).  The reference
// builds RDD[(REId, LocalDataset)] by a cluster-wide shuffle
// (photon-api/.../data/RandomEffectDatasetPartitioner.scala,
// data/RandomEffectDataset.scala); here one host packs buckets for the
// vmapped on-device solves, and the numpy formulation pays for several full
// sorts of the nnz stream (np.unique over 8e7 pair keys measured ~35 s at
// 1e7 rows).  This packer is two linear passes with O(dim) scratch:
//
//   pass A (photon_re_feature_counts): per-entity distinct-feature counts —
//     the input the bucket-shape choice (histogram DP / geometric padding,
//     in Python) needs;
//   pass B (photon_re_bucket_fill): per bucket, re-derive each entity's
//     local feature map (stamp-array dedup + optional top-k support
//     pruning) and scatter rows/values into the caller-allocated tensors.
//
// Semantics match the numpy path bit-for-bit: local feature indices are
// assigned in ascending feature-id order among kept features; pruning keeps
// the top max_active_features by (support desc, feature id asc); duplicate
// (row, col) entries accumulate into x exactly like np.add.at.
//
// Build: see photon_ml_tpu/native.py (g++ -O2 -shared -fPIC ... -lz).

#include <algorithm>
#include <cstdint>
#include <vector>

namespace {

// Shared per-entity feature scan: walks entity e's rows
// [ent_starts[e], ent_starts[e+1]) over the global CSR, collecting distinct
// columns into `observed` (insertion order) with per-column support counts.
// `stamp`/`support` are dim-sized scratch; stamp[c] == e marks c as seen for
// the current entity, so the arrays need no clearing between entities.
// Prefetch distance over the active-row stream.  The walk is
// latency-bound: each row costs ~4 dependent cache misses into GB-scale
// arrays (indptr, then cols/vals at the fetched offset, labels/weights)
// and the single-core box overlaps none of them without help.  Stage 1
// prefetches row r+PF's indptr/labels/weights; stage 2 (at r+PF/2, when
// indptr[g] is usually resident) prefetches its cols/vals span.
constexpr int64_t kPrefetch = 16;

inline void prefetch_row_stage1(const int64_t* indptr, const float* a,
                                const float* b, int64_t g) {
  __builtin_prefetch(indptr + g);
  if (a) __builtin_prefetch(a + g);
  if (b) __builtin_prefetch(b + g);
}

inline void prefetch_row_stage2(const int64_t* indptr, const int32_t* cols,
                                const float* vals, int64_t g) {
  const int64_t k = indptr[g];
  __builtin_prefetch(cols + k);
  if (vals) __builtin_prefetch(vals + k);
}

// `prefetch_end` bounds the lookahead: the global row count in pass A
// (the walk is sequential over all entities), the entity's own row end in
// pass B (bucket entities are not adjacent in the row stream, so
// cross-entity lookahead would fetch rows of some other bucket).
inline void scan_entity(const int64_t* indptr, const int32_t* cols,
                        const int64_t* all_active, const int64_t* ent_starts,
                        int64_t e, int64_t* stamp, int64_t* support,
                        std::vector<int32_t>& observed, int64_t prefetch_end) {
  observed.clear();
  for (int64_t r = ent_starts[e]; r < ent_starts[e + 1]; ++r) {
    if (r + kPrefetch < prefetch_end)
      prefetch_row_stage1(indptr, nullptr, nullptr, all_active[r + kPrefetch]);
    if (r + kPrefetch / 2 < prefetch_end)
      prefetch_row_stage2(indptr, cols, nullptr,
                          all_active[r + kPrefetch / 2]);
    const int64_t g = all_active[r];
    for (int64_t k = indptr[g]; k < indptr[g + 1]; ++k) {
      const int32_t c = cols[k];
      if (stamp[c] != e) {
        stamp[c] = e;
        support[c] = 1;
        observed.push_back(c);
      } else {
        ++support[c];
      }
    }
  }
}

// Prune `observed` to the top `max_features` by (support desc, id asc),
// then sort ascending by feature id (the local-index order).
inline void select_features(std::vector<int32_t>& observed,
                            const int64_t* support, int64_t max_features) {
  if (max_features >= 0 &&
      static_cast<int64_t>(observed.size()) > max_features) {
    std::nth_element(observed.begin(), observed.begin() + max_features,
                     observed.end(), [&](int32_t a, int32_t b) {
                       if (support[a] != support[b])
                         return support[a] > support[b];
                       return a < b;
                     });
    observed.resize(max_features);
  }
  std::sort(observed.begin(), observed.end());
}

}  // namespace

extern "C" {

// Pass A: out_counts[e] = number of features entity e keeps (post-pruning).
// `stamp` is caller-allocated dim-sized scratch initialized to -1 (allocated
// once per dataset build — at dim ~1e7 a per-call allocation+memset would be
// a fixed cost independent of nnz); `support` is dim-sized, no init needed.
void photon_re_feature_counts(const int64_t* indptr, const int32_t* cols,
                              const int64_t* all_active,
                              const int64_t* ent_starts, int64_t n_entities,
                              int64_t dim, int64_t max_active_features,
                              int64_t* stamp, int64_t* support,
                              int64_t* out_counts) {
  (void)dim;
  std::vector<int32_t> observed;
  const int64_t n_rows_total = ent_starts[n_entities];
  for (int64_t e = 0; e < n_entities; ++e) {
    scan_entity(indptr, cols, all_active, ent_starts, e, stamp, support,
                observed, n_rows_total);
    int64_t cnt = static_cast<int64_t>(observed.size());
    if (max_active_features >= 0 && cnt > max_active_features)
      cnt = max_active_features;
    out_counts[e] = cnt;
  }
}

// Pass B: fill one bucket's tensors.  Caller allocates x/labels/weights
// zeroed and sample_idx/feature_index filled with -1.
//   sel: (E,) dense entity ids of this bucket.
//   x: (E, S, D) f32; labels/weights: (E, S) f32; sample_idx: (E, S) i64;
//   feature_index: (E, D) i64.
// Scratch contract: stamp/kept_stamp are dim-sized, -1-initialized ONCE per
// dataset build and shared across all bucket calls — each dense entity id is
// visited by exactly one bucket, so stamps never collide across calls.  The
// stamp arrays must be DISTINCT from pass A's (its stamps would alias).
// support/local are dim-sized, no init needed.
void photon_re_bucket_fill(const int64_t* indptr, const int32_t* cols,
                           const float* vals, const int64_t* all_active,
                           const int64_t* ent_starts, const float* labels_all,
                           const float* weights_all, const int64_t* sel,
                           int64_t E, int64_t S, int64_t D, int64_t dim,
                           int64_t max_active_features, int64_t* stamp,
                           int64_t* support, int64_t* kept_stamp,
                           int64_t* local, float* x, float* labels,
                           float* weights, int64_t* sample_idx,
                           int64_t* feature_index) {
  (void)dim;
  std::vector<int32_t> observed;
  for (int64_t ei = 0; ei < E; ++ei) {
    const int64_t e = sel[ei];
    scan_entity(indptr, cols, all_active, ent_starts, e, stamp, support,
                observed, ent_starts[e + 1]);
    select_features(observed, support, max_active_features);
    int64_t* fi = feature_index + ei * D;
    for (size_t l = 0; l < observed.size(); ++l) {
      const int32_t c = observed[l];
      kept_stamp[c] = e;
      local[c] = static_cast<int64_t>(l);
      fi[l] = c;
    }
    float* xe = x + ei * S * D;
    float* le = labels + ei * S;
    float* we = weights + ei * S;
    int64_t* se = sample_idx + ei * S;
    int64_t s = 0;
    for (int64_t r = ent_starts[e]; r < ent_starts[e + 1]; ++r, ++s) {
      if (r + kPrefetch < ent_starts[e + 1])
        prefetch_row_stage1(indptr, labels_all, weights_all,
                            all_active[r + kPrefetch]);
      if (r + kPrefetch / 2 < ent_starts[e + 1])
        prefetch_row_stage2(indptr, cols, vals,
                            all_active[r + kPrefetch / 2]);
      const int64_t g = all_active[r];
      le[s] = labels_all[g];
      we[s] = weights_all[g];
      se[s] = g;
      float* xr = xe + s * D;
      for (int64_t k = indptr[g]; k < indptr[g + 1]; ++k) {
        const int32_t c = cols[k];
        if (kept_stamp[c] == e) xr[local[c]] += vals[k];
      }
    }
  }
}

// Pass B' (indices only): sample_idx + feature_index without filling the
// (E, S, D) tensors.  The device-side compact path reconstructs
// x/labels/weights by gathers through these index maps, so the fat fill —
// the dominant host cost of a bucket build (a ~3-4x-padded memset+scatter)
// — is skipped entirely unless some host path later materializes it.
// Same scratch contract as pass B for `stamp`/`support`.
void photon_re_bucket_indices(const int64_t* indptr, const int32_t* cols,
                              const int64_t* all_active,
                              const int64_t* ent_starts, const int64_t* sel,
                              int64_t E, int64_t S, int64_t D,
                              int64_t max_active_features, int64_t* stamp,
                              int64_t* support, int64_t* sample_idx,
                              int64_t* feature_index) {
  std::vector<int32_t> observed;
  for (int64_t ei = 0; ei < E; ++ei) {
    const int64_t e = sel[ei];
    scan_entity(indptr, cols, all_active, ent_starts, e, stamp, support,
                observed, ent_starts[e + 1]);
    select_features(observed, support, max_active_features);
    int64_t* fi = feature_index + ei * D;
    for (size_t l = 0; l < observed.size(); ++l)
      fi[l] = static_cast<int64_t>(observed[l]);
    int64_t* se = sample_idx + ei * S;
    int64_t s = 0;
    for (int64_t r = ent_starts[e]; r < ent_starts[e + 1]; ++r, ++s)
      se[s] = all_active[r];
  }
}

}  // extern "C"

extern "C" {

// Stable counting sort of DENSE non-negative ids (entity columns are
// pre-indexed into [0, n_entities) by ingest): order receives row indices
// grouped by id, original order preserved within an id. cursors holds the
// exclusive prefix sum of the id histogram on entry and is consumed.
// Replaces the O(n log n) numpy stable argsort in the random-effect
// dataset build (~0.25 s per coordinate at 1M rows -> ~10 ms).
void photon_counting_sort(const int64_t* ids, int64_t n, int64_t* cursors,
                          int64_t* order) {
  for (int64_t i = 0; i < n; ++i) order[cursors[ids[i]]++] = i;
}

}  // extern "C"
