// Native Avro output writers: flat numpy columns -> Avro container files,
// exposed through a C ABI consumed via ctypes (photon_ml_tpu/native.py).
//
// Role: the output half of the native IO path.  Two writers:
//
//   photon_write_scoring_results — ScoringResultAvro (the reference writes
//   these across Spark executors,
//   photon-client/.../cli/game/scoring/GameScoringDriver.scala); here one
//   host drains the device's score vector, and the pure-Python record
//   encoder (~100k records/s) becomes the wall on 10^7+-row batch scoring.
//
//   photon_write_re_models — per-entity BayesianLinearModelAvro records
//   (the reference's random-effect model part-files,
//   photon-client/.../data/avro/ModelProcessingUtils.scala); a GAME save
//   writes one record per entity and the Python encoder made "Save models"
//   cost ~4 s for 11k entities — measured as the single largest stage of a
//   warm end-to-end driver run.
//
// Both emit the exact schemas of photon_ml_tpu/io/schemas.py from columnar
// buffers.  Codec: null (uncompressed) — callers wanting compression use
// the Python writer.
//
// Build: compiled into libphoton_native.so next to avro_reader.cc
// (photon_ml_tpu/native.py).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <random>
#include <string>
#include <vector>

namespace {

void append_long(std::vector<uint8_t>& out, int64_t v) {
  // zigzag + varint (Avro long)
  uint64_t u = (static_cast<uint64_t>(v) << 1) ^
               static_cast<uint64_t>(v >> 63);
  while (u >= 0x80) {
    out.push_back(static_cast<uint8_t>(u) | 0x80);
    u >>= 7;
  }
  out.push_back(static_cast<uint8_t>(u));
}

void append_double(std::vector<uint8_t>& out, double v) {
  // Avro double: 8 bytes little-endian IEEE 754
  uint64_t bits;
  std::memcpy(&bits, &v, 8);
  for (int i = 0; i < 8; ++i) {
    out.push_back(static_cast<uint8_t>(bits >> (8 * i)));
  }
}

void append_bytes(std::vector<uint8_t>& out, const char* s, size_t len) {
  append_long(out, static_cast<int64_t>(len));
  out.insert(out.end(), reinterpret_cast<const uint8_t*>(s),
             reinterpret_cast<const uint8_t*>(s) + len);
}

// Random 16-byte sync marker, as the Avro spec requires (split readers
// locate block boundaries by scanning for these bytes — a fixed marker
// could collide with record payload and mis-split the file).
void fill_sync(uint8_t sync[16]) {
  std::random_device rd;
  for (int i = 0; i < 16; i += 4) {
    uint32_t w = rd();
    std::memcpy(sync + i, &w, 4);
  }
}

// Container header: magic, {avro.schema, avro.codec=null} metadata, sync.
bool write_header(std::FILE* f, const char* schema_json, int64_t schema_len,
                  const uint8_t sync[16]) {
  std::vector<uint8_t> buf;
  buf.reserve(1 << 16);
  const uint8_t magic[4] = {'O', 'b', 'j', 1};
  buf.insert(buf.end(), magic, magic + 4);
  append_long(buf, 2);  // metadata map: one block of 2 entries
  append_bytes(buf, "avro.schema", 11);
  append_bytes(buf, schema_json, static_cast<size_t>(schema_len));
  append_bytes(buf, "avro.codec", 10);
  append_bytes(buf, "null", 4);
  append_long(buf, 0);  // end of map
  buf.insert(buf.end(), sync, sync + 16);
  return std::fwrite(buf.data(), 1, buf.size(), f) == buf.size();
}

// One null-codec block: count, byte length, payload, sync.
bool write_block(std::FILE* f, int64_t count,
                 const std::vector<uint8_t>& block, const uint8_t sync[16]) {
  std::vector<uint8_t> head;
  append_long(head, count);
  append_long(head, static_cast<int64_t>(block.size()));
  return std::fwrite(head.data(), 1, head.size(), f) == head.size() &&
         std::fwrite(block.data(), 1, block.size(), f) == block.size() &&
         std::fwrite(sync, 1, 16, f) == 16;
}

}  // namespace

extern "C" {

// Writes a ScoringResultAvro container.  Arguments:
//   path: output file
//   schema_json/schema_len: the writer schema (Python passes
//     io/schemas.py::SCORING_RESULT_AVRO so the two cannot drift)
//   scores[n]: predictionScore column
//   labels[n]: label column, or NULL (labels written as union-null)
//   uid_bytes/uid_offsets: concatenated utf-8 uids with n+1 offsets, or
//     NULL -> uids are the decimal record indices
//   block_records: records per Avro block (sync marker between blocks)
// Returns n on success, -1 on IO failure.
int64_t photon_write_scoring_results(const char* path,
                                     const char* schema_json,
                                     int64_t schema_len,
                                     const double* scores,
                                     const double* labels,
                                     const char* uid_bytes,
                                     const int64_t* uid_offsets, int64_t n,
                                     int64_t block_records) {
  std::FILE* f = std::fopen(path, "wb");
  if (!f) return -1;
  uint8_t sync[16];
  fill_sync(sync);
  if (!write_header(f, schema_json, schema_len, sync)) {
    std::fclose(f);
    return -1;
  }

  if (block_records <= 0) block_records = 65536;
  std::vector<uint8_t> block;
  block.reserve(static_cast<size_t>(block_records) * 24);
  char uid_scratch[24];
  for (int64_t start = 0; start < n; start += block_records) {
    int64_t count = n - start < block_records ? n - start : block_records;
    block.clear();
    for (int64_t i = start; i < start + count; ++i) {
      append_long(block, 1);  // uid union: branch 1 = string
      if (uid_bytes) {
        int64_t lo = uid_offsets[i], hi = uid_offsets[i + 1];
        append_bytes(block, uid_bytes + lo, static_cast<size_t>(hi - lo));
      } else {
        int len = std::snprintf(uid_scratch, sizeof uid_scratch, "%lld",
                                static_cast<long long>(i));
        append_bytes(block, uid_scratch, static_cast<size_t>(len));
      }
      append_double(block, scores[i]);
      if (labels) {
        append_long(block, 1);  // label union: branch 1 = double
        append_double(block, labels[i]);
      } else {
        append_long(block, 0);  // null
      }
      append_long(block, 0);  // metadataMap union: null
    }
    if (!write_block(f, count, block, sync)) {
      std::fclose(f);
      return -1;
    }
  }
  if (std::fclose(f) != 0) return -1;
  return n;
}

// Writes per-entity BayesianLinearModelAvro records from columnar buffers.
// Arguments:
//   path, schema_json/schema_len: as above (Python passes
//     io/schemas.py::BAYESIAN_LINEAR_MODEL_AVRO)
//   n_models: record count
//   id_bytes/id_offsets: concatenated utf-8 modelIds with n_models+1 offsets
//   model_class_bytes/model_class_len: one shared string written as both
//     modelClass and lossFunction (union branch 1)
//   rec_indptr: (n_models+1) coefficient ranges per record
//   name_ids: (n_coeffs) indices into the name/term tables
//   values: (n_coeffs) coefficient means
//   variances: (n_coeffs) or NULL -> variances written as union-null
//   name_bytes/name_offsets, term_bytes/term_offsets: feature-name and
//     term tables (index aligned), n_names+1 offsets
//   block_records: records per Avro block
// Returns n_models on success, -1 on IO failure.
int64_t photon_write_re_models(
    const char* path, const char* schema_json, int64_t schema_len,
    int64_t n_models, const char* id_bytes, const int64_t* id_offsets,
    const char* model_class_bytes, int64_t model_class_len,
    const int64_t* rec_indptr, const int32_t* name_ids, const double* values,
    const double* variances, const char* name_bytes,
    const int64_t* name_offsets, const char* term_bytes,
    const int64_t* term_offsets, int64_t block_records) {
  std::FILE* f = std::fopen(path, "wb");
  if (!f) return -1;
  uint8_t sync[16];
  fill_sync(sync);
  if (!write_header(f, schema_json, schema_len, sync)) {
    std::fclose(f);
    return -1;
  }

  if (block_records <= 0) block_records = 4096;
  std::vector<uint8_t> block;
  auto append_ntv_array = [&](int64_t lo, int64_t hi, const double* vals) {
    // Avro array: one count block of items, then the 0 terminator
    if (hi > lo) {
      append_long(block, hi - lo);
      for (int64_t k = lo; k < hi; ++k) {
        const int32_t j = name_ids[k];
        append_bytes(block, name_bytes + name_offsets[j],
                     static_cast<size_t>(name_offsets[j + 1] -
                                         name_offsets[j]));
        append_bytes(block, term_bytes + term_offsets[j],
                     static_cast<size_t>(term_offsets[j + 1] -
                                         term_offsets[j]));
        append_double(block, vals[k]);
      }
    }
    append_long(block, 0);
  };
  for (int64_t start = 0; start < n_models; start += block_records) {
    int64_t count =
        n_models - start < block_records ? n_models - start : block_records;
    block.clear();
    for (int64_t i = start; i < start + count; ++i) {
      append_bytes(block, id_bytes + id_offsets[i],
                   static_cast<size_t>(id_offsets[i + 1] - id_offsets[i]));
      for (int rep = 0; rep < 2; ++rep) {  // modelClass, lossFunction
        append_long(block, 1);  // union branch 1 = string
        append_bytes(block, model_class_bytes,
                     static_cast<size_t>(model_class_len));
      }
      append_ntv_array(rec_indptr[i], rec_indptr[i + 1], values);
      if (variances) {
        append_long(block, 1);  // union branch 1 = array
        append_ntv_array(rec_indptr[i], rec_indptr[i + 1], variances);
      } else {
        append_long(block, 0);  // null
      }
    }
    if (!write_block(f, count, block, sync)) {
      std::fclose(f);
      return -1;
    }
  }
  if (std::fclose(f) != 0) return -1;
  return n_models;
}

}  // extern "C"
