// Native Avro scoring-output writer: flat numpy columns ->
// ScoringResultAvro container file, exposed through a C ABI consumed via
// ctypes (photon_ml_tpu/native.py).
//
// Role: the output half of the native IO path.  The reference writes
// ScoringResultAvro across Spark executors
// (photon-client/.../cli/game/scoring/GameScoringDriver.scala); here one
// host drains the device's score vector, and the pure-Python record
// encoder (~100k records/s) becomes the wall on 10^7+-row batch scoring.
// This writer emits the exact SCORING_RESULT_AVRO shape
// (photon_ml_tpu/io/schemas.py) from columnar buffers.
//
// Scope: uid (union null|string; generated decimal indices when the caller
// passes no uid buffer), predictionScore double, label union null|double,
// metadataMap always null.  Codec: null (uncompressed) — scoring output is
// typically consumed immediately; callers wanting compression use the
// Python writer.
//
// Build: compiled into libphoton_native.so next to avro_reader.cc
// (photon_ml_tpu/native.py).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

void append_long(std::vector<uint8_t>& out, int64_t v) {
  // zigzag + varint (Avro long)
  uint64_t u = (static_cast<uint64_t>(v) << 1) ^
               static_cast<uint64_t>(v >> 63);
  while (u >= 0x80) {
    out.push_back(static_cast<uint8_t>(u) | 0x80);
    u >>= 7;
  }
  out.push_back(static_cast<uint8_t>(u));
}

void append_double(std::vector<uint8_t>& out, double v) {
  // Avro double: 8 bytes little-endian IEEE 754
  uint64_t bits;
  std::memcpy(&bits, &v, 8);
  for (int i = 0; i < 8; ++i) {
    out.push_back(static_cast<uint8_t>(bits >> (8 * i)));
  }
}

void append_bytes(std::vector<uint8_t>& out, const char* s, size_t len) {
  append_long(out, static_cast<int64_t>(len));
  out.insert(out.end(), reinterpret_cast<const uint8_t*>(s),
             reinterpret_cast<const uint8_t*>(s) + len);
}

}  // namespace

extern "C" {

// Writes a ScoringResultAvro container.  Arguments:
//   path: output file
//   schema_json/schema_len: the writer schema (Python passes
//     io/schemas.py::SCORING_RESULT_AVRO so the two cannot drift)
//   scores[n]: predictionScore column
//   labels[n]: label column, or NULL (labels written as union-null)
//   uid_bytes/uid_offsets: concatenated utf-8 uids with n+1 offsets, or
//     NULL -> uids are the decimal record indices
//   block_records: records per Avro block (sync marker between blocks)
// Returns n on success, -1 on IO failure.
int64_t photon_write_scoring_results(const char* path,
                                     const char* schema_json,
                                     int64_t schema_len,
                                     const double* scores,
                                     const double* labels,
                                     const char* uid_bytes,
                                     const int64_t* uid_offsets, int64_t n,
                                     int64_t block_records) {
  std::FILE* f = std::fopen(path, "wb");
  if (!f) return -1;
  // deterministic sync marker (the spec wants 16 bytes, not entropy)
  static const uint8_t sync[16] = {'p', 'h', 'o', 't', 'o', 'n', '-', 't',
                                   'p', 'u', '-', 's', 'c', 'o', 'r', 'e'};

  std::vector<uint8_t> buf;
  buf.reserve(1 << 16);
  // header: magic, metadata map {avro.schema, avro.codec}, sync
  const uint8_t magic[4] = {'O', 'b', 'j', 1};
  buf.insert(buf.end(), magic, magic + 4);
  append_long(buf, 2);  // metadata map: one block of 2 entries
  append_bytes(buf, "avro.schema", 11);
  append_bytes(buf, schema_json, static_cast<size_t>(schema_len));
  append_bytes(buf, "avro.codec", 10);
  append_bytes(buf, "null", 4);
  append_long(buf, 0);  // end of map
  buf.insert(buf.end(), sync, sync + 16);
  if (std::fwrite(buf.data(), 1, buf.size(), f) != buf.size()) {
    std::fclose(f);
    return -1;
  }

  if (block_records <= 0) block_records = 65536;
  std::vector<uint8_t> block;
  block.reserve(static_cast<size_t>(block_records) * 24);
  char uid_scratch[24];
  for (int64_t start = 0; start < n; start += block_records) {
    int64_t count = n - start < block_records ? n - start : block_records;
    block.clear();
    for (int64_t i = start; i < start + count; ++i) {
      append_long(block, 1);  // uid union: branch 1 = string
      if (uid_bytes) {
        int64_t lo = uid_offsets[i], hi = uid_offsets[i + 1];
        append_bytes(block, uid_bytes + lo, static_cast<size_t>(hi - lo));
      } else {
        int len = std::snprintf(uid_scratch, sizeof uid_scratch, "%lld",
                                static_cast<long long>(i));
        append_bytes(block, uid_scratch, static_cast<size_t>(len));
      }
      append_double(block, scores[i]);
      if (labels) {
        append_long(block, 1);  // label union: branch 1 = double
        append_double(block, labels[i]);
      } else {
        append_long(block, 0);  // null
      }
      append_long(block, 0);  // metadataMap union: null
    }
    buf.clear();
    append_long(buf, count);
    append_long(buf, static_cast<int64_t>(block.size()));
    bool ok = std::fwrite(buf.data(), 1, buf.size(), f) == buf.size() &&
              std::fwrite(block.data(), 1, block.size(), f) == block.size() &&
              std::fwrite(sync, 1, 16, f) == 16;
    if (!ok) {
      std::fclose(f);
      return -1;
    }
  }
  if (std::fclose(f) != 0) return -1;
  return n;
}

}  // extern "C"
