// Native Avro ingest: TrainingExampleAvro container files -> flat columnar
// buffers, exposed through a C ABI consumed via ctypes
// (photon_ml_tpu/native.py).
//
// Role: the TPU-native equivalent of the reference's ingest hot path.  The
// reference leans on Spark's JVM Avro decoding across executors
// (photon-client/.../data/avro/AvroDataReader.scala); here one host feeds
// the chips, so record decoding is the single-threaded bottleneck — a
// pure-Python decode of (name, term, value) feature lists runs ~50k
// records/s, this decoder runs the same schema orders of magnitude faster
// and interns feature keys / entity ids into dense integer tables on the
// fly (subsuming the PalDB feature-store lookup of
// photon-client/.../index/PalDBIndexMap.scala).
//
// Scope: exactly the TrainingExampleAvro shape this framework writes
// (photon_ml_tpu/io/schemas.py).  Python verifies the container schema
// matches before calling in, and falls back to the pure-Python codec
// otherwise.  Codecs: null + deflate (raw zlib).
//
// Build: see photon_ml_tpu/native.py (g++ -O2 -shared -fPIC ... -lz).

#include <zlib.h>

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Reader {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  bool need(size_t n) {
    if (static_cast<size_t>(end - p) < n) {
      ok = false;
      return false;
    }
    return true;
  }

  int64_t read_long() {
    uint64_t acc = 0;
    int shift = 0;
    while (true) {
      if (!need(1)) return 0;
      uint8_t b = *p++;
      acc |= static_cast<uint64_t>(b & 0x7F) << shift;
      if (!(b & 0x80)) break;
      shift += 7;
      if (shift > 63) {
        ok = false;
        return 0;
      }
    }
    return static_cast<int64_t>(acc >> 1) ^ -static_cast<int64_t>(acc & 1);
  }

  double read_double() {
    if (!need(8)) return 0.0;
    double v;
    std::memcpy(&v, p, 8);
    p += 8;
    return v;
  }

  // Zero-copy variant: the returned span aliases the block buffer, which
  // outlives the record decode — callers must consume it before the next
  // block. Saves one heap string per call in the per-feature hot loop.
  bool read_string_view(const char** s, size_t* len) {
    int64_t n = read_long();
    if (n < 0 || !need(static_cast<size_t>(n))) {
      ok = false;
      return false;
    }
    *s = reinterpret_cast<const char*>(p);
    *len = static_cast<size_t>(n);
    p += n;
    return true;
  }

  bool skip_string() {
    int64_t n = read_long();
    if (n < 0 || !need(static_cast<size_t>(n))) {
      ok = false;
      return false;
    }
    p += n;
    return true;
  }
};

// String interner: key -> dense id, plus the flat byte table for export.
// Open-addressing (linear probe, power-of-two capacity) keyed by an FNV-1a
// hash computed straight off the block-buffer string views: the
// unordered_map<string> version paid a heap std::string assembly plus a
// chained-bucket walk per feature (~12 probes/record) and was the decode
// hot spot once zlib was out of the way.
struct Interner {
  struct Slot {
    uint64_t h;
    int32_t id;  // -1 = empty
  };
  std::string bytes;                // concatenated keys
  std::vector<int64_t> offsets{0};  // len+1 prefix offsets into bytes
  std::vector<Slot> slots = std::vector<Slot>(1024, Slot{0, -1});
  size_t count = 0;

  // FNV-1a over a, then (when b is non-null) a 0x01 separator byte and b —
  // byte-identical to hashing the stored key `a + '\x01' + b`.
  static uint64_t hash_parts(const char* a, size_t la, const char* b,
                             size_t lb) {
    uint64_t h = 1469598103934665603ULL;
    for (size_t i = 0; i < la; ++i) {
      h ^= static_cast<uint8_t>(a[i]);
      h *= 1099511628211ULL;
    }
    if (b) {
      h ^= 1u;
      h *= 1099511628211ULL;
      for (size_t i = 0; i < lb; ++i) {
        h ^= static_cast<uint8_t>(b[i]);
        h *= 1099511628211ULL;
      }
    }
    return h;
  }

  bool equals(int32_t id, const char* a, size_t la, const char* b,
              size_t lb) const {
    const int64_t off = offsets[id];
    const int64_t len = offsets[id + 1] - off;
    const int64_t want = static_cast<int64_t>(la + (b ? lb + 1 : 0));
    if (len != want) return false;
    const char* p = bytes.data() + off;
    if (std::memcmp(p, a, la) != 0) return false;
    if (b) {
      if (p[la] != '\x01') return false;
      if (std::memcmp(p + la + 1, b, lb) != 0) return false;
    }
    return true;
  }

  void grow() {
    std::vector<Slot> ns(slots.size() * 2, Slot{0, -1});
    const size_t mask = ns.size() - 1;
    for (const Slot& s : slots) {
      if (s.id < 0) continue;
      size_t i = s.h & mask;
      while (ns[i].id >= 0) i = (i + 1) & mask;
      ns[i] = s;
    }
    slots.swap(ns);
  }

  // Intern `a + '\x01' + b` (b non-null) or just `a` (b null).
  int32_t intern_parts(const char* a, size_t la, const char* b, size_t lb) {
    const uint64_t h = hash_parts(a, la, b, lb);
    const size_t mask = slots.size() - 1;
    size_t i = h & mask;
    while (slots[i].id >= 0) {
      if (slots[i].h == h && equals(slots[i].id, a, la, b, lb))
        return slots[i].id;
      i = (i + 1) & mask;
    }
    const int32_t id = static_cast<int32_t>(count++);
    slots[i] = Slot{h, id};
    bytes.append(a, la);
    if (b) {
      bytes.push_back('\x01');
      bytes.append(b, lb);
    }
    offsets.push_back(static_cast<int64_t>(bytes.size()));
    if (count * 10 >= slots.size() * 7) grow();
    return id;
  }
};

struct Result {
  std::vector<double> response, offset, weight;  // NaN = null
  std::vector<int64_t> feat_indptr{0};  // per-record feature counts (prefix)
  std::vector<int32_t> feat_key;        // interned feature-key id per nnz
  std::vector<double> feat_val;
  Interner feat_keys;
  // id columns: per requested metadata key, one int32 per record (-1 missing)
  std::vector<std::vector<int32_t>> id_cols;
  std::vector<Interner> id_vocabs;
  std::string error;
};

constexpr double kNaN = __builtin_nan("");

// Decode one TrainingExampleAvro record.  field_order: permutation of
// {0:uid, 1:response, 2:offset, 3:weight, 4:features, 5:metadataMap} in the
// file's schema order.  null_first[f]: whether that field's union lists
// null first (branch 0 = null).
bool decode_record(Reader& r, const int* field_order, const uint8_t* null_first,
                   const std::vector<std::string>& id_keys, Result* out,
                   std::vector<int32_t>* ids_scratch) {
  double response = kNaN, offs = kNaN, weight = kNaN;
  // caller-owned scratch: a per-record heap vector was 1 allocation/record
  std::vector<int32_t>& ids = *ids_scratch;
  ids.assign(id_keys.size(), -1);
  for (int f = 0; f < 6; ++f) {
    switch (field_order[f]) {
      case 0: {  // uid: [null, string]
        int64_t branch = r.read_long();
        if (!r.ok) return false;
        bool is_null = (branch == 0) == (null_first[0] != 0);
        if (!is_null && !r.skip_string()) return false;
        break;
      }
      case 1:
        response = r.read_double();
        break;
      case 2:
      case 3: {  // [null, double]
        int fi = field_order[f];
        int64_t branch = r.read_long();
        if (!r.ok) return false;
        bool is_null = (branch == 0) == (null_first[fi] != 0);
        double v = is_null ? kNaN : r.read_double();
        (fi == 2 ? offs : weight) = v;
        break;
      }
      case 4: {  // features: array of {name, term, value}
        while (true) {
          int64_t count = r.read_long();
          if (!r.ok) return false;
          if (count == 0) break;
          if (count < 0) {
            count = -count;
            r.read_long();  // byte size, unused
          }
          for (int64_t i = 0; i < count; ++i) {
            // name + term interned straight from the block-buffer views —
            // no per-feature key assembly at all
            const char* s1;
            size_t l1;
            const char* s2;
            size_t l2;
            if (!r.read_string_view(&s1, &l1)) return false;
            if (!r.read_string_view(&s2, &l2)) return false;
            double v = r.read_double();
            if (!r.ok) return false;
            out->feat_key.push_back(
                out->feat_keys.intern_parts(s1, l1, s2, l2));
            out->feat_val.push_back(v);
          }
        }
        break;
      }
      case 5: {  // metadataMap: [null, map<string>]
        int64_t branch = r.read_long();
        if (!r.ok) return false;
        bool is_null = (branch == 0) == (null_first[5] != 0);
        if (is_null) break;
        while (true) {
          int64_t count = r.read_long();
          if (!r.ok) return false;
          if (count == 0) break;
          if (count < 0) {
            count = -count;
            r.read_long();
          }
          for (int64_t i = 0; i < count; ++i) {
            const char* ks;
            size_t kl;
            const char* vs;
            size_t vl;
            if (!r.read_string_view(&ks, &kl)) return false;
            if (!r.read_string_view(&vs, &vl)) return false;
            for (size_t c = 0; c < id_keys.size(); ++c) {
              if (id_keys[c].size() == kl
                  && std::memcmp(id_keys[c].data(), ks, kl) == 0) {
                ids[c] = out->id_vocabs[c].intern_parts(vs, vl, nullptr, 0);
              }
            }
          }
        }
        break;
      }
      default:
        return false;
    }
    if (!r.ok) return false;
  }
  out->response.push_back(response);
  out->offset.push_back(offs);
  out->weight.push_back(weight);
  out->feat_indptr.push_back(static_cast<int64_t>(out->feat_key.size()));
  for (size_t c = 0; c < id_keys.size(); ++c) out->id_cols[c].push_back(ids[c]);
  return true;
}

bool inflate_raw(const uint8_t* src, size_t n, std::vector<uint8_t>* out) {
  z_stream zs{};
  if (inflateInit2(&zs, -15) != Z_OK) return false;
  out->clear();
  out->resize(n * 4 + 1024);
  zs.next_in = const_cast<Bytef*>(src);
  zs.avail_in = static_cast<uInt>(n);
  size_t written = 0;
  int rc = Z_OK;
  while (rc != Z_STREAM_END) {
    if (written == out->size()) out->resize(out->size() * 2);
    zs.next_out = out->data() + written;
    zs.avail_out = static_cast<uInt>(out->size() - written);
    rc = inflate(&zs, Z_NO_FLUSH);
    if (rc != Z_OK && rc != Z_STREAM_END) {
      inflateEnd(&zs);
      return false;
    }
    written = out->size() - zs.avail_out;
    if (rc == Z_OK && zs.avail_in == 0 && zs.avail_out != 0) break;
  }
  out->resize(written);
  inflateEnd(&zs);
  return true;
}

}  // namespace

extern "C" {

// Parses the container's data blocks (after the header, which Python reads
// to verify the schema).  Arguments:
//   blocks/blocks_len: the file bytes from the first data block to EOF
//   sync: 16-byte sync marker from the header
//   deflate_codec: 1 if avro.codec == deflate
//   field_order[6], null_first[6]: schema layout (see decode_record)
//   id_keys_blob/id_keys_n: '\n'-joined metadata keys to extract
// Returns an opaque Result* (NULL on allocation failure); check
// photon_result_error for decode errors.
void* photon_decode_blocks(const uint8_t* blocks, int64_t blocks_len,
                           const uint8_t* sync, int deflate_codec,
                           const int* field_order, const uint8_t* null_first,
                           const char* id_keys_blob) {
  auto* out = new (std::nothrow) Result();
  if (!out) return nullptr;
  std::vector<std::string> id_keys;
  {
    const char* s = id_keys_blob;
    while (s && *s) {
      const char* nl = std::strchr(s, '\n');
      if (!nl) {
        id_keys.emplace_back(s);
        break;
      }
      id_keys.emplace_back(s, nl - s);
      s = nl + 1;
    }
  }
  out->id_cols.resize(id_keys.size());
  out->id_vocabs.resize(id_keys.size());

  Reader file{blocks, blocks + blocks_len};
  std::vector<uint8_t> scratch_block;
  std::vector<int32_t> ids_scratch;
  while (file.p < file.end) {
    int64_t n_records = file.read_long();
    int64_t size = file.read_long();
    if (!file.ok || size < 0 || !file.need(static_cast<size_t>(size) + 16)) {
      out->error = "truncated block header";
      return out;
    }
    const uint8_t* payload = file.p;
    size_t payload_len = static_cast<size_t>(size);
    file.p += size;
    if (std::memcmp(file.p, sync, 16) != 0) {
      out->error = "sync marker mismatch";
      return out;
    }
    file.p += 16;

    Reader rec{payload, payload + payload_len};
    if (deflate_codec) {
      if (!inflate_raw(payload, payload_len, &scratch_block)) {
        out->error = "deflate error";
        return out;
      }
      rec = Reader{scratch_block.data(),
                   scratch_block.data() + scratch_block.size()};
    }
    for (int64_t i = 0; i < n_records; ++i) {
      if (!decode_record(rec, field_order, null_first, id_keys, out,
                         &ids_scratch)) {
        out->error = "record decode error";
        return out;
      }
    }
  }
  return out;
}

const char* photon_result_error(void* rp) {
  auto* r = static_cast<Result*>(rp);
  return r->error.empty() ? nullptr : r->error.c_str();
}

int64_t photon_result_n_records(void* rp) {
  return static_cast<int64_t>(static_cast<Result*>(rp)->response.size());
}

int64_t photon_result_nnz(void* rp) {
  return static_cast<int64_t>(static_cast<Result*>(rp)->feat_key.size());
}

int32_t photon_result_n_feature_keys(void* rp) {
  return static_cast<int32_t>(static_cast<Result*>(rp)->feat_keys.count);
}

int64_t photon_result_feature_bytes_len(void* rp) {
  return static_cast<int64_t>(static_cast<Result*>(rp)->feat_keys.bytes.size());
}

// Bulk copies into caller-allocated buffers (numpy arrays via ctypes).
void photon_result_copy_core(void* rp, double* response, double* offset,
                             double* weight, int64_t* feat_indptr,
                             int32_t* feat_key, double* feat_val) {
  auto* r = static_cast<Result*>(rp);
  std::memcpy(response, r->response.data(), r->response.size() * 8);
  std::memcpy(offset, r->offset.data(), r->offset.size() * 8);
  std::memcpy(weight, r->weight.data(), r->weight.size() * 8);
  std::memcpy(feat_indptr, r->feat_indptr.data(), r->feat_indptr.size() * 8);
  std::memcpy(feat_key, r->feat_key.data(), r->feat_key.size() * 4);
  std::memcpy(feat_val, r->feat_val.data(), r->feat_val.size() * 8);
}

void photon_result_copy_feature_keys(void* rp, char* bytes,
                                     int64_t* offsets) {
  auto* r = static_cast<Result*>(rp);
  std::memcpy(bytes, r->feat_keys.bytes.data(), r->feat_keys.bytes.size());
  std::memcpy(offsets, r->feat_keys.offsets.data(),
              r->feat_keys.offsets.size() * 8);
}

int32_t photon_result_id_vocab_size(void* rp, int32_t col) {
  auto* r = static_cast<Result*>(rp);
  return static_cast<int32_t>(r->id_vocabs[col].count);
}

int64_t photon_result_id_vocab_bytes_len(void* rp, int32_t col) {
  auto* r = static_cast<Result*>(rp);
  return static_cast<int64_t>(r->id_vocabs[col].bytes.size());
}

void photon_result_copy_id_col(void* rp, int32_t col, int32_t* ids,
                               char* vocab_bytes, int64_t* vocab_offsets) {
  auto* r = static_cast<Result*>(rp);
  std::memcpy(ids, r->id_cols[col].data(), r->id_cols[col].size() * 4);
  std::memcpy(vocab_bytes, r->id_vocabs[col].bytes.data(),
              r->id_vocabs[col].bytes.size());
  std::memcpy(vocab_offsets, r->id_vocabs[col].offsets.data(),
              r->id_vocabs[col].offsets.size() * 8);
}

void photon_result_free(void* rp) { delete static_cast<Result*>(rp); }

}  // extern "C"

// ---------------------------------------------------------------------------
// Per-shard CSR split of the decoded flat feature stream (record order
// preserved).  The Python assembly previously materialized per-nnz row ids,
// a global-key remap gather, a column-map gather, a keep mask and three
// masked gathers per shard, plus an intercept concatenation — ~1 s of numpy
// on a 1M-record / 7M-nnz file.  These two passes replace all of it:
//   key_to_col: per interned feature-key id, the shard's column or -1 (drop)
//   intercept_col >= 0 appends one (intercept_col, 1.0) entry per record
// Pass 1 (count) fills per-record kept counts; the caller prefix-sums into
// the CSR indptr, allocates cols/vals, and runs pass 2 (fill).
extern "C" {

void photon_shard_split_count(const int64_t* feat_indptr,
                              const int32_t* feat_key, int64_t n_records,
                              const int32_t* key_to_col,
                              int32_t intercept_col, int64_t* out_counts) {
  const int64_t extra = intercept_col >= 0 ? 1 : 0;
  for (int64_t r = 0; r < n_records; ++r) {
    int64_t kept = extra;
    for (int64_t i = feat_indptr[r]; i < feat_indptr[r + 1]; ++i)
      kept += key_to_col[feat_key[i]] >= 0;
    out_counts[r] = kept;
  }
}

void photon_shard_split_fill(const int64_t* feat_indptr,
                             const int32_t* feat_key, const double* feat_val,
                             int64_t n_records, const int32_t* key_to_col,
                             int32_t intercept_col, const int64_t* out_indptr,
                             int32_t* out_cols, float* out_vals) {
  for (int64_t r = 0; r < n_records; ++r) {
    int64_t w = out_indptr[r];
    for (int64_t i = feat_indptr[r]; i < feat_indptr[r + 1]; ++i) {
      const int32_t col = key_to_col[feat_key[i]];
      if (col >= 0) {
        out_cols[w] = col;
        out_vals[w] = static_cast<float>(feat_val[i]);
        ++w;
      }
    }
    if (intercept_col >= 0) {
      out_cols[w] = intercept_col;
      out_vals[w] = 1.0f;
    }
  }
}

}  // extern "C"
