"""Sharded fixed-effect tests (SURVEY.md §7 stage 4): the shard_map/psum
objective must agree with the single-device objective to float64 precision on
a simulated 8-device CPU mesh — the moral equivalent of the reference's
Spark local[*] integration tests of ``DistributedGLMLossFunction``."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.sparse as sp

from photon_ml_tpu.glm import GLMOptimizationConfiguration
from photon_ml_tpu.ops.design import CsrDesign, DenseDesign
from photon_ml_tpu.ops.losses import LogisticLoss
from photon_ml_tpu.ops.objective import GLMData, GLMObjective
from photon_ml_tpu.optimize import OptimizerConfig, minimize_lbfgs, minimize_tron
from photon_ml_tpu.parallel import (
    DistributedGLMObjective,
    make_mesh,
    shard_glm_data,
)


def make_data(n=203, d=17, seed=0, sparse=False):
    """n deliberately NOT divisible by 8 to exercise tail padding."""
    rng = np.random.default_rng(seed)
    if sparse:
        m = sp.random(n, d, density=0.3, random_state=int(seed), format="csr")
        design = CsrDesign.from_scipy(m)
        x = m.toarray()
    else:
        x = rng.normal(size=(n, d))
        design = DenseDesign(x=jnp.asarray(x))
    labels = (rng.uniform(size=n) < 0.5).astype(np.float64)
    offsets = rng.normal(size=n) * 0.1
    weights = rng.uniform(0.5, 2.0, size=n)
    return GLMData(design=design, labels=jnp.asarray(labels),
                   offsets=jnp.asarray(offsets), weights=jnp.asarray(weights)), x


@pytest.fixture(scope="module")
def mesh():
    assert jax.device_count() >= 8, "conftest must provide 8 virtual devices"
    return make_mesh({"data": 8})


@pytest.mark.parametrize("sparse", [False, True], ids=["dense", "csr"])
class TestDistributedObjective:
    def test_value_grad_hvp_match_local(self, mesh, sparse):
        data, _ = make_data(sparse=sparse)
        obj = GLMObjective(loss=LogisticLoss)
        dist = DistributedGLMObjective(obj, mesh)
        sharded = shard_glm_data(data, 8, device_put_mesh=mesh)

        rng = np.random.default_rng(1)
        w = jnp.asarray(rng.normal(size=data.dim))
        v = jnp.asarray(rng.normal(size=data.dim))
        l2 = 0.7

        f_local, g_local = obj.value_and_grad(w, data, l2)
        f_dist, g_dist = dist.value_and_grad(w, sharded, l2)
        np.testing.assert_allclose(float(f_dist), float(f_local), rtol=1e-12)
        np.testing.assert_allclose(np.asarray(g_dist), np.asarray(g_local),
                                   rtol=1e-10, atol=1e-12)

        hv_local = obj.hvp(w, v, data, l2)
        hv_dist = dist.hvp(w, v, sharded, l2)
        np.testing.assert_allclose(np.asarray(hv_dist), np.asarray(hv_local),
                                   rtol=1e-10, atol=1e-12)

    def test_reg_mask_counted_once(self, mesh, sparse):
        data, _ = make_data(sparse=sparse)
        mask = jnp.ones(data.dim).at[0].set(0.0)
        obj = GLMObjective(loss=LogisticLoss, reg_mask=mask)
        dist = DistributedGLMObjective(obj, mesh)
        sharded = shard_glm_data(data, 8, device_put_mesh=mesh)
        w = jnp.asarray(np.random.default_rng(2).normal(size=data.dim))
        f_local, g_local = obj.value_and_grad(w, data, 2.0)
        f_dist, g_dist = dist.value_and_grad(w, sharded, 2.0)
        np.testing.assert_allclose(float(f_dist), float(f_local), rtol=1e-12)
        np.testing.assert_allclose(np.asarray(g_dist), np.asarray(g_local),
                                   rtol=1e-10, atol=1e-12)


class TestDistributedSolve:
    def test_lbfgs_solution_matches_single_device(self, mesh):
        data, _ = make_data(seed=3)
        obj = GLMObjective(loss=LogisticLoss)
        dist = DistributedGLMObjective(obj, mesh)
        sharded = shard_glm_data(data, 8, device_put_mesh=mesh)
        cfg = OptimizerConfig(max_iterations=200, tolerance=1e-10)
        w0 = jnp.zeros(data.dim)
        l2 = 0.5

        res_local = jax.jit(lambda w: minimize_lbfgs(
            lambda wv: obj.value_and_grad(wv, data, l2), w, cfg))(w0)
        res_dist = jax.jit(lambda w: minimize_lbfgs(
            lambda wv: dist.value_and_grad(wv, sharded, l2), w, cfg))(w0)
        np.testing.assert_allclose(np.asarray(res_dist.w), np.asarray(res_local.w),
                                   atol=1e-8)

    def test_tron_whole_pod_single_program(self, mesh):
        """TRON's nested TR/CG loops with psum'd Hvp compile into one XLA
        program over the mesh — the reference's per-CG-step treeAggregate
        round-trips collapse into on-device collectives."""
        data, _ = make_data(seed=4)
        obj = GLMObjective(loss=LogisticLoss)
        dist = DistributedGLMObjective(obj, mesh)
        sharded = shard_glm_data(data, 8, device_put_mesh=mesh)
        cfg = OptimizerConfig(max_iterations=100, tolerance=1e-10)
        l2 = 0.5
        res_local = jax.jit(lambda w: minimize_tron(
            lambda wv: obj.value_and_grad(wv, data, l2),
            lambda wv, v: obj.hvp(wv, v, data, l2), w, cfg))(jnp.zeros(data.dim))
        res_dist = jax.jit(lambda w: minimize_tron(
            lambda wv: dist.value_and_grad(wv, sharded, l2),
            lambda wv, v: dist.hvp(wv, v, sharded, l2), w, cfg))(jnp.zeros(data.dim))
        np.testing.assert_allclose(np.asarray(res_dist.w), np.asarray(res_local.w),
                                   atol=1e-8)

    def test_owlqn_elastic_net_matches_single_device(self, mesh):
        """OWL-QN (L1) over the psum'd objective == unsharded: the orthant
        projection happens on the replicated w, so sharding must not change
        the sparsity pattern (BASELINE config 2, distributed)."""
        from photon_ml_tpu.optimize import minimize_owlqn

        data, _ = make_data(seed=9)
        obj = GLMObjective(loss=LogisticLoss)
        dist = DistributedGLMObjective(obj, mesh)
        sharded = shard_glm_data(data, 8, device_put_mesh=mesh)
        cfg = OptimizerConfig(max_iterations=200, tolerance=1e-10)
        l1, l2 = 0.4, 0.2
        res_local = jax.jit(lambda w: minimize_owlqn(
            lambda wv: obj.value_and_grad(wv, data, l2), w, l1, cfg))(
                jnp.zeros(data.dim))
        res_dist = jax.jit(lambda w: minimize_owlqn(
            lambda wv: dist.value_and_grad(wv, sharded, l2), w, l1, cfg))(
                jnp.zeros(data.dim))
        np.testing.assert_allclose(np.asarray(res_dist.w),
                                   np.asarray(res_local.w), atol=1e-6)
        # identical support (L1 zero pattern)
        np.testing.assert_array_equal(np.asarray(res_dist.w) == 0.0,
                                      np.asarray(res_local.w) == 0.0)

    def test_variance_matches_single_device(self, mesh):
        """SIMPLE/FULL variance through the psum'd Hessian contractions."""
        data, _ = make_data(seed=10)
        obj = GLMObjective(loss=LogisticLoss)
        dist = DistributedGLMObjective(obj, mesh)
        sharded = shard_glm_data(data, 8, device_put_mesh=mesh)
        w = jnp.asarray(np.random.default_rng(11).normal(size=data.dim))
        np.testing.assert_allclose(
            np.asarray(dist.hessian_diagonal(w, sharded, 0.3)),
            np.asarray(obj.hessian_diagonal(w, data, 0.3)), rtol=1e-10)
        np.testing.assert_allclose(
            np.asarray(dist.hessian_matrix(w, sharded, 0.3)),
            np.asarray(obj.hessian_matrix(w, data, 0.3)), rtol=1e-10)

    def test_deterministic_across_runs(self, mesh):
        """SURVEY §5.2: the psum reduction is bitwise deterministic —
        repeated evaluation of the same sharded objective produces identical
        bits (the reproducibility property Spark's treeAggregate also has
        for a fixed partitioning)."""
        data, _ = make_data(seed=12)
        obj = GLMObjective(loss=LogisticLoss)
        dist = DistributedGLMObjective(obj, mesh)
        sharded = shard_glm_data(data, 8, device_put_mesh=mesh)
        w = jnp.asarray(np.random.default_rng(13).normal(size=data.dim))
        f1, g1 = dist.value_and_grad(w, sharded, 0.5)
        f2, g2 = dist.value_and_grad(w, sharded, 0.5)
        assert float(f1) == float(f2)
        np.testing.assert_array_equal(np.asarray(g1), np.asarray(g2))

    def test_margins_roundtrip(self, mesh):
        data, x = make_data(seed=5)
        obj = GLMObjective(loss=LogisticLoss)
        dist = DistributedGLMObjective(obj, mesh)
        sharded = shard_glm_data(data, 8, device_put_mesh=mesh)
        w = jnp.asarray(np.random.default_rng(6).normal(size=data.dim))
        m = np.asarray(dist.margins(w, sharded)).reshape(-1)[:data.n_samples]
        np.testing.assert_allclose(m, np.asarray(obj.margins(w, data)), rtol=1e-10)


@pytest.fixture(scope="module")
def feature_mesh():
    from photon_ml_tpu.parallel import FEATURE_AXIS, make_mesh

    assert jax.device_count() >= 8
    return make_mesh({FEATURE_AXIS: 8})


@pytest.mark.parametrize("sparse", [False, True], ids=["dense", "csr"])
class TestFeatureShardedObjective:
    """TP sharding of the coefficient dim (SURVEY.md §2.10 TP row): every
    quantity must match the unsharded objective. d=17 over 8 devices
    exercises feature-dim padding (d_pad=24, 7 dead columns)."""

    def test_value_grad_hvp_match_local(self, feature_mesh, sparse):
        from photon_ml_tpu.parallel import (
            FeatureShardedGLMObjective,
            shard_glm_data_features,
        )

        data, _ = make_data(sparse=sparse)
        obj = GLMObjective(loss=LogisticLoss)
        tp = FeatureShardedGLMObjective(obj, feature_mesh)
        sharded, d_pad = shard_glm_data_features(
            data, 8, device_put_mesh=feature_mesh)
        assert d_pad == 24

        rng = np.random.default_rng(7)
        w = jnp.asarray(np.concatenate(
            [rng.normal(size=data.dim), np.zeros(d_pad - data.dim)]))
        v = jnp.asarray(np.concatenate(
            [rng.normal(size=data.dim), np.zeros(d_pad - data.dim)]))
        l2 = 0.7

        f_local, g_local = obj.value_and_grad(w[:data.dim], data, l2)
        f_tp, g_tp = tp.value_and_grad(w, sharded, l2)
        np.testing.assert_allclose(float(f_tp), float(f_local), rtol=1e-12)
        np.testing.assert_allclose(np.asarray(g_tp)[:data.dim],
                                   np.asarray(g_local), rtol=1e-10, atol=1e-12)
        # padded columns: zero data, zero w → gradient exactly 0
        np.testing.assert_array_equal(np.asarray(g_tp)[data.dim:], 0.0)

        hv_local = obj.hvp(w[:data.dim], v[:data.dim], data, l2)
        hv_tp = tp.hvp(w, v, sharded, l2)
        np.testing.assert_allclose(np.asarray(hv_tp)[:data.dim],
                                   np.asarray(hv_local), rtol=1e-10, atol=1e-12)

        m_tp = np.asarray(tp.margins(w, sharded))
        np.testing.assert_allclose(m_tp, np.asarray(obj.margins(w[:data.dim], data)),
                                   rtol=1e-10)

    def test_tron_solve_matches_single_device(self, feature_mesh, sparse):
        """TRON's TR/CG loops over the feature-sharded objective: the
        closed-form block Hvp must drive the same solution as unsharded."""
        from photon_ml_tpu.parallel import (
            FeatureShardedGLMObjective,
            shard_glm_data_features,
        )

        data, _ = make_data(seed=21, sparse=sparse)
        obj = GLMObjective(loss=LogisticLoss)
        tp = FeatureShardedGLMObjective(obj, feature_mesh)
        sharded, d_pad = shard_glm_data_features(
            data, 8, device_put_mesh=feature_mesh)
        cfg = OptimizerConfig(max_iterations=100, tolerance=1e-10)
        l2 = 0.5
        res_local = jax.jit(lambda w: minimize_tron(
            lambda wv: obj.value_and_grad(wv, data, l2),
            lambda wv, v: obj.hvp(wv, v, data, l2), w, cfg))(
                jnp.zeros(data.dim))
        res_tp = jax.jit(lambda w: minimize_tron(
            lambda wv: tp.value_and_grad(wv, sharded, l2),
            lambda wv, v: tp.hvp(wv, v, sharded, l2), w, cfg))(
                jnp.zeros(d_pad))
        np.testing.assert_allclose(np.asarray(res_tp.w)[:data.dim],
                                   np.asarray(res_local.w), atol=1e-6)

    def test_lbfgs_solve_matches_single_device(self, feature_mesh, sparse):
        from photon_ml_tpu.parallel import (
            FeatureShardedGLMObjective,
            shard_glm_data_features,
        )

        data, _ = make_data(seed=8, sparse=sparse)
        obj = GLMObjective(loss=LogisticLoss)
        tp = FeatureShardedGLMObjective(obj, feature_mesh)
        sharded, d_pad = shard_glm_data_features(
            data, 8, device_put_mesh=feature_mesh)
        cfg = OptimizerConfig(max_iterations=200, tolerance=1e-10)
        l2 = 0.5
        res_local = jax.jit(lambda w: minimize_lbfgs(
            lambda wv: obj.value_and_grad(wv, data, l2), w, cfg))(
                jnp.zeros(data.dim))
        res_tp = jax.jit(lambda w: minimize_lbfgs(
            lambda wv: tp.value_and_grad(wv, sharded, l2), w, cfg))(
                jnp.zeros(d_pad))
        # both runs stop at the shared optimum, but stall termination may
        # trigger an iteration apart — compare at solver, not fp, precision
        np.testing.assert_allclose(np.asarray(res_tp.w)[:data.dim],
                                   np.asarray(res_local.w), atol=1e-6)
        np.testing.assert_array_equal(np.asarray(res_tp.w)[data.dim:], 0.0)


def test_fused_kernel_under_shard_map_interpret():
    """The fused Pallas value+grad kernel must run inside a shard_map body
    (its out_shapes carry the block's vma) and match the closed form — the
    dp fixed-effect path now enables it on TPU."""
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.ops.design import DenseDesign
    from photon_ml_tpu.ops.losses import LogisticLoss
    from photon_ml_tpu.ops.objective import GLMData, GLMObjective
    from photon_ml_tpu.parallel.distributed import (
        DistributedGLMObjective,
        shard_glm_data,
    )
    from photon_ml_tpu.parallel.mesh import DATA_AXIS, make_mesh

    rng = np.random.default_rng(0)
    n, d = 128, 16
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    host = GLMData(design=DenseDesign(x=jnp.asarray(x)),
                   labels=jnp.asarray(y),
                   offsets=jnp.zeros(n, jnp.float32),
                   weights=jnp.ones(n, jnp.float32))
    mesh = make_mesh({DATA_AXIS: 8})
    sharded = shard_glm_data(host, 8, device_put_mesh=mesh)
    w = jnp.asarray(rng.normal(size=d), jnp.float32)

    ref = DistributedGLMObjective(
        objective=GLMObjective(LogisticLoss), mesh=mesh)
    v0, g0 = ref.value_and_grad(w, sharded, 0.3)

    # The Pallas HLO *interpreter* can't propagate vma through its internal
    # dynamic_slices (the real Mosaic lowering on TPU can — validated
    # on-chip through a mesh), so the interpret-mode check wraps its own
    # shard_map with check_vma=False around the fused objective.
    from photon_ml_tpu.compat import shard_map
    from jax.sharding import PartitionSpec as P

    fused_obj = GLMObjective(LogisticLoss, fused=True, fused_interpret=True)

    def body(wv, blk):
        data = jax.tree.map(lambda a: a[0], blk)
        val, grad = fused_obj.value_and_grad(wv, data, 0.0)
        return (jax.lax.psum(val, DATA_AXIS) + 0.5 * 0.3 * jnp.vdot(wv, wv),
                jax.lax.psum(grad, DATA_AXIS) + 0.3 * wv)

    v1, g1 = shard_map(body, mesh=mesh, in_specs=(P(), P(DATA_AXIS)),
                       out_specs=(P(), P()), check_vma=False)(w, sharded)
    np.testing.assert_allclose(float(v1), float(v0), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g0),
                               rtol=1e-4, atol=1e-5)
