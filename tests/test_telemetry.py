"""Telemetry subsystem tests (photon_ml_tpu/telemetry/ + integrations).

The load-bearing contracts:

- **registry correctness under threads**: N threads x M increments lands
  exactly N*M (the whole point of owning locks instead of hoping);
- **histogram semantics**: cumulative bucket counts, sum/count, and
  bucket-interpolated quantiles are exact on known inputs;
- **exposition**: the Prometheus text format is golden-tested and
  round-trips through the in-repo parser;
- **span tracing**: nested spans record correct parentage AND interval
  enclosure in ``trace.jsonl``;
- **bridge**: existing bus events (``serving_request``, ``retry_*``,
  ``stage_finished``, registry lifecycle) translate to metrics without
  call-site changes, idempotently;
- **end-to-end**: a ``train_game --telemetry-dir`` run yields a
  well-formed span tree plus per-coordinate loss/grad-norm metrics for
  every CD iteration, and a live ``serve_game`` server exposes
  ``/metrics`` whose recompile counter stays flat across varying batch
  sizes (the zero-recompile contract, now scrape-visible).
"""

import json
import math
import os
import threading
import urllib.request

import numpy as np
import pytest

from photon_ml_tpu.telemetry import metrics as tmetrics
from photon_ml_tpu.telemetry import prometheus as tprom
from photon_ml_tpu.telemetry.metrics import (
    MetricsRegistry,
    quantile_from_buckets,
)
from photon_ml_tpu.telemetry.tracing import Tracer


class TestRegistry:
    def test_counter_concurrency(self):
        reg = MetricsRegistry()
        child = reg.counter("c_total", "x", labels=("t",)).labels(t="a")
        n_threads, n_incs = 8, 5000

        def work():
            for _ in range(n_incs):
                child.inc()

        threads = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert child.value == n_threads * n_incs

    def test_get_or_create_idempotent_and_conflict_loud(self):
        reg = MetricsRegistry()
        a = reg.counter("x_total", "first", labels=("op",))
        b = reg.counter("x_total", "second declaration ignored",
                        labels=("op",))
        assert a is b
        with pytest.raises(ValueError):
            reg.gauge("x_total")  # type conflict
        with pytest.raises(ValueError):
            reg.counter("x_total", labels=("other",))  # label conflict

    def test_label_validation(self):
        reg = MetricsRegistry()
        fam = reg.counter("y_total", labels=("op",))
        with pytest.raises(ValueError):
            fam.labels(wrong="x")
        with pytest.raises(ValueError):
            fam.labels()  # missing label
        fam.labels(op="a").inc()
        assert fam.labels(op="a").value == 1
        assert fam.labels(op="b").value == 0  # distinct series

    def test_counter_rejects_decrease_gauge_allows(self):
        reg = MetricsRegistry()
        with pytest.raises(ValueError):
            reg.counter("c_total").inc(-1)
        g = reg.gauge("g")
        g.set(5)
        g.dec(2)
        assert g.value == 3


class TestHistogram:
    def test_bucket_counts_and_sum(self):
        reg = MetricsRegistry()
        h = reg.histogram("h_seconds", buckets=(0.1, 1.0, 10.0)).labels()
        for v in (0.05, 0.1, 0.5, 5.0, 50.0):
            h.observe(v)
        cum, total, count = h.snapshot()
        # le-semantics: 0.1 falls IN the le=0.1 bucket
        assert cum == [2, 3, 4, 5]
        assert count == 5
        assert total == pytest.approx(55.65)

    def test_quantiles_interpolated(self):
        # 2 obs in (0, 1], 2 obs in (1, 2] -> p50 = 1.0 exactly, p75
        # halfway through the second bucket
        uppers = (1.0, 2.0)
        cum = [2, 4, 4]  # le=1, le=2, +Inf
        assert quantile_from_buckets(uppers, cum, 0.5) == pytest.approx(1.0)
        assert quantile_from_buckets(uppers, cum, 0.75) == pytest.approx(1.5)
        assert quantile_from_buckets(uppers, cum, 1.0) == pytest.approx(2.0)
        assert math.isnan(quantile_from_buckets(uppers, [0, 0, 0], 0.5))

    def test_timer_observes_and_exposes_seconds(self):
        reg = MetricsRegistry()
        h = reg.histogram("t_seconds").labels()
        with h.time() as t:
            pass
        assert t.seconds >= 0
        assert h.count == 1

    def test_timer_observes_on_exception(self):
        reg = MetricsRegistry()
        h = reg.histogram("t_seconds").labels()
        with pytest.raises(RuntimeError):
            with h.time():
                raise RuntimeError("boom")
        assert h.count == 1  # failed requests are latency too


class TestPrometheus:
    def test_golden_exposition(self):
        reg = MetricsRegistry()
        reg.counter("photon_x_total", "things done",
                    labels=("op",)).labels(op="read").inc(3)
        reg.gauge("photon_v", "a version").set(2)
        h = reg.histogram("photon_lat_seconds", "latency",
                          buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.5)
        h.observe(5.0)
        assert tprom.render(reg) == (
            "# HELP photon_x_total things done\n"
            "# TYPE photon_x_total counter\n"
            'photon_x_total{op="read"} 3\n'
            "# HELP photon_v a version\n"
            "# TYPE photon_v gauge\n"
            "photon_v 2\n"
            "# HELP photon_lat_seconds latency\n"
            "# TYPE photon_lat_seconds histogram\n"
            'photon_lat_seconds_bucket{le="0.1"} 1\n'
            'photon_lat_seconds_bucket{le="1"} 2\n'
            'photon_lat_seconds_bucket{le="+Inf"} 3\n'
            "photon_lat_seconds_sum 5.55\n"
            "photon_lat_seconds_count 3\n")

    def test_parse_roundtrip(self):
        reg = MetricsRegistry()
        reg.counter("a_total", labels=("k",)).labels(k="v1").inc(7)
        reg.histogram("b_seconds", buckets=(1.0,)).observe(0.5)
        parsed = tprom.parse_text(tprom.render(reg))
        assert tprom.series_value(parsed, "a_total", {"k": "v1"}) == 7
        assert tprom.series_value(parsed, "b_seconds_bucket",
                                  {"le": "1"}) == 1
        assert tprom.series_value(parsed, "b_seconds_bucket",
                                  {"le": "+Inf"}) == 1
        assert tprom.series_value(parsed, "b_seconds_count") == 1

    def test_label_escaping_roundtrip(self):
        reg = MetricsRegistry()
        nasty = 'a"b\\c\nd'
        reg.counter("e_total", labels=("p",)).labels(p=nasty).inc()
        parsed = tprom.parse_text(tprom.render(reg))
        (labels, value), = parsed["e_total"]
        assert labels["p"] == nasty
        assert value == 1


class TestTracing:
    def test_nested_spans_parent_and_enclosure(self, tmp_path):
        tracer = Tracer()
        path = str(tmp_path / "trace.jsonl")
        tracer.configure(path)
        try:
            with tracer.span("root", run="r1"):
                with tracer.span("child_a") as a:
                    a.set(loss=0.5)
                with tracer.span("child_b"):
                    with tracer.span("grandchild"):
                        pass
        finally:
            tracer.close()
        recs = [json.loads(line) for line in open(path)]
        by_name = {r["name"]: r for r in recs}
        assert by_name["root"]["parent_id"] is None
        assert by_name["child_a"]["parent_id"] == by_name["root"]["span_id"]
        assert by_name["child_b"]["parent_id"] == by_name["root"]["span_id"]
        assert (by_name["grandchild"]["parent_id"]
                == by_name["child_b"]["span_id"])
        assert by_name["child_a"]["loss"] == 0.5
        assert by_name["root"]["run"] == "r1"
        by_id = {r["span_id"]: r for r in recs}
        for r in recs:
            if r["parent_id"] is not None:
                parent = by_id[r["parent_id"]]
                assert parent["t0"] <= r["t0"] and r["t1"] <= parent["t1"]

    def test_unconfigured_spans_are_cheap_noops(self, tmp_path):
        tracer = Tracer()
        assert not tracer.enabled
        with tracer.span("a") as sp:
            assert sp.parent_id is None
            with tracer.span("b") as child:
                assert child.parent_id == sp.span_id  # parentage still live
        tracer.annotate("note", k=1)  # no sink -> silently dropped

    def test_span_finished_bridged_onto_bus(self, tmp_path):
        from photon_ml_tpu.events import EventBus

        bus = EventBus()
        seen = []
        bus.subscribe(seen.append)
        tracer = Tracer()
        tracer.configure(str(tmp_path / "t.jsonl"), bus=bus)
        try:
            with tracer.span("outer"):
                with tracer.span("inner"):
                    pass
        finally:
            tracer.close()
        assert [e.name for e in seen] == ["span_finished"] * 2
        assert seen[0].payload["span"] == "inner"  # completion order
        assert seen[1].payload["span"] == "outer"
        assert seen[0].payload["parent_id"] == seen[1].payload["span_id"]

    def test_annotate_records_current_parent(self, tmp_path):
        tracer = Tracer()
        path = str(tmp_path / "t.jsonl")
        tracer.configure(path)
        try:
            with tracer.span("work") as sp:
                tracer.annotate("optimizer_trace", values=[1.0, 0.5])
        finally:
            tracer.close()
        recs = [json.loads(line) for line in open(path)]
        note = next(r for r in recs if r["span_id"] is None)
        assert note["parent_id"] == sp.span_id
        assert note["values"] == [1.0, 0.5]


class TestBridge:
    def _fresh(self):
        from photon_ml_tpu.events import EventBus
        from photon_ml_tpu.telemetry import bridge

        bus = EventBus()
        reg = MetricsRegistry()
        unbind = bridge.bind(bus=bus, registry=reg)
        return bus, reg, unbind

    def test_serving_request_translation(self):
        bus, reg, _ = self._fresh()
        bus.post("serving_request", batch=4, latency_ms=1.2, version=1)
        bus.post("serving_request", batch=1, latency_ms=0.4, version=1)
        assert reg.get("photon_serving_requests_total").value == 2
        assert reg.get("photon_serving_scored_rows_total").value == 5

    def test_retry_translation_bounds_op_cardinality(self):
        bus, reg, _ = self._fresh()
        bus.post("retry_attempt", op="avro.read:part-00001.avro",
                 attempt=1, delay_s=0.1, elapsed_s=0.0, error="E")
        bus.post("retry_attempt", op="avro.read:part-00099.avro",
                 attempt=1, delay_s=0.1, elapsed_s=0.0, error="E")
        bus.post("retry_succeeded", op="avro.read:part-00099.avro",
                 attempt=2, elapsed_s=0.2)
        bus.post("retry_exhausted", op="ckpt.save:step-3", attempts=3,
                 elapsed_s=1.0, deadline_hit=False, error="E")
        fam = reg.get("photon_retry_attempts_total")
        assert fam.labels(op="avro.read").value == 2  # one bounded series
        assert reg.get("photon_retry_recoveries_total").labels(
            op="avro.read").value == 1
        assert reg.get("photon_retry_exhausted_total").labels(
            op="ckpt.save").value == 1

    def test_stage_and_lifecycle_translation(self):
        bus, reg, _ = self._fresh()
        bus.post("stage_finished", stage="Train", seconds=2.0)
        bus.post("model_loaded", version=1, path="/x", n_entities={})
        bus.post("model_activated", version=3, previous=1)
        bus.post("model_reload_rejected", path="/bad", error="boom")
        bus.post("divergence_detected", coordinate="global", sweep=0,
                 failures=1)
        bus.post("coordinate_rollback", coordinate="global", sweep=0,
                 attempt=1, reg_backoff=10.0)
        bus.post("coordinate_frozen", coordinate="global", sweep=0,
                 failures=3)
        assert reg.get("photon_stage_seconds").labels(
            stage="Train").count == 1
        assert reg.get("photon_model_reloads_total").value == 1
        assert reg.get("photon_model_active_version").value == 3
        assert reg.get("photon_model_reload_rejects_total").value == 1
        assert reg.get("photon_divergence_detected_total").labels(
            coordinate="global").value == 1
        assert reg.get("photon_coordinate_rollbacks_total").labels(
            coordinate="global").value == 1
        assert reg.get("photon_coordinate_freezes_total").labels(
            coordinate="global").value == 1

    def test_bind_idempotent_and_unbind(self):
        from photon_ml_tpu.telemetry import bridge

        bus, reg, unbind = self._fresh()
        again = bridge.bind(bus=bus, registry=reg)  # no-op second bind
        bus.post("serving_request", batch=1, latency_ms=0.1, version=1)
        assert reg.get("photon_serving_requests_total").value == 1
        again()
        unbind()
        bus.post("serving_request", batch=1, latency_ms=0.1, version=1)
        assert reg.get("photon_serving_requests_total").value == 1
        # a REAL re-bind after unbind translates again
        bridge.bind(bus=bus, registry=reg)
        bus.post("serving_request", batch=1, latency_ms=0.1, version=1)
        assert reg.get("photon_serving_requests_total").value == 2


class TestEventBusThreadSafety:
    def test_concurrent_post_and_subscribe_churn(self):
        from photon_ml_tpu.events import EventBus

        bus = EventBus()
        seen = []
        bus.subscribe(seen.append)  # stable listener sees every post
        n_threads, n_posts = 6, 400
        failures = []

        def poster(k):
            try:
                for i in range(n_posts):
                    # churn the listener list mid-post from many threads:
                    # the pre-fix bus raced list mutation against iteration
                    unsub = bus.subscribe(lambda e: None)
                    bus.post("tick", thread=k, i=i)
                    unsub()
            except Exception as e:  # pragma: no cover - failure path
                failures.append(e)

        threads = [threading.Thread(target=poster, args=(k,))
                   for k in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not failures
        assert len(seen) == n_threads * n_posts
        assert len(bus) == 1  # every churn listener unsubscribed


class TestRunLoggerMetricsFile:
    def test_single_handle_flush_and_close(self, tmp_path):
        from photon_ml_tpu.logging_util import RunLogger

        rl = RunLogger(str(tmp_path))
        try:
            rl.metric(stage="a", v=1)
            # the handle flushes per line: visible BEFORE close
            with open(tmp_path / "metrics.jsonl") as f:
                assert len(f.readlines()) == 1
            fh = rl._metrics_fh
            rl.metric(stage="b", v=2)
            assert rl._metrics_fh is fh  # no reopen per call
        finally:
            rl.close()
        assert rl._metrics_fh is None
        lines = [json.loads(line)
                 for line in open(tmp_path / "metrics.jsonl")]
        assert [ln["stage"] for ln in lines] == ["a", "b"]

    def test_concurrent_metric_writes_do_not_shear(self, tmp_path):
        from photon_ml_tpu.logging_util import RunLogger

        rl = RunLogger(str(tmp_path))
        n_threads, n_lines = 8, 200
        try:
            threads = [
                threading.Thread(
                    target=lambda k=k: [rl.metric(t=k, i=i)
                                        for i in range(n_lines)])
                for k in range(n_threads)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        finally:
            rl.close()
        lines = open(tmp_path / "metrics.jsonl").readlines()
        assert len(lines) == n_threads * n_lines
        for line in lines:  # every line is intact JSON — no interleaving
            json.loads(line)

    def test_metric_after_close_is_log_only(self, tmp_path):
        from photon_ml_tpu.logging_util import RunLogger

        rl = RunLogger(str(tmp_path))
        rl.metric(v=1)
        rl.close()
        rl.metric(v=2)  # must not raise, must not write
        assert len(open(tmp_path / "metrics.jsonl").readlines()) == 1


class TestProfiledConfirmation:
    def test_confirmation_survives_body_exception(self, tmp_path, caplog):
        import logging

        from photon_ml_tpu.logging_util import profiled

        out = str(tmp_path / "profile")
        with caplog.at_level(logging.INFO, logger="photon_ml_tpu"):
            with pytest.raises(RuntimeError):
                with profiled(out):
                    raise RuntimeError("mid-stage failure")
        assert any("profiler trace written to" in r.message
                   for r in caplog.records)
        assert os.path.isdir(out)  # the trace the message points at


# ---------------------------------------------------------------------------
# End-to-end: train_game --telemetry-dir and a live serve_game /metrics
# ---------------------------------------------------------------------------

SHARDS = "global=fixed|intercept,user=user|noIntercept"
COORDS = [
    "global=fixed,shard=global,reg=L2",
    "perUser=random,entity=userId,shard=user,reg=L2",
]
N_SWEEPS = 2
UPDATE_SEQUENCE = ["global", "perUser"]


def _records(n, seed=3):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        feats = [{"name": f"fixed.x{j}", "term": "",
                  "value": float(rng.normal())} for j in range(4)]
        feats += [{"name": f"user.z{j}", "term": "",
                   "value": float(rng.normal())} for j in range(2)]
        out.append({
            "uid": str(i),
            "response": float(rng.integers(0, 2)),
            "offset": None, "weight": None, "features": feats,
            "metadataMap": {"userId": f"u{rng.integers(0, 6)}"},
        })
    return out


@pytest.fixture(scope="module")
def telemetry_run(tmp_path_factory):
    """One tiny train_game run WITH --telemetry-dir; the output model also
    backs the serving /metrics test."""
    from photon_ml_tpu.cli import train_game as train_game_cli
    from photon_ml_tpu.io.data_reader import write_training_examples

    tmp = str(tmp_path_factory.mktemp("telemetry"))
    train_path = os.path.join(tmp, "train.avro")
    write_training_examples(train_path, _records(150))
    out = os.path.join(tmp, "run")
    tdir = os.path.join(tmp, "telemetry")
    train_game_cli.run([
        "--training-data", train_path,
        "--output-dir", out,
        "--feature-shards", SHARDS,
        "--coordinates", *COORDS,
        "--update-sequence", ",".join(UPDATE_SEQUENCE),
        "--cd-iterations", str(N_SWEEPS),
        "--grid", "global=0.1", "perUser=1",
        "--evaluators", "",
        "--telemetry-dir", tdir,
    ])
    spans, notes = [], []
    for line in open(os.path.join(tdir, "trace.jsonl")):
        rec = json.loads(line)
        (spans if rec.get("span_id") is not None else notes).append(rec)
    return {"tmp": tmp, "model_dir": out, "telemetry_dir": tdir,
            "spans": spans, "notes": notes}


class TestTrainGameTelemetry:
    def test_spans_nest_correctly(self, telemetry_run):
        """Every non-root span's parent exists and encloses it — the
        acceptance contract for trace.jsonl."""
        spans = telemetry_run["spans"]
        assert spans, "trace.jsonl holds no spans"
        by_id = {s["span_id"]: s for s in spans}
        roots = [s for s in spans if s["parent_id"] is None]
        assert [r["name"] for r in roots] == ["train_game"]
        for s in spans:
            if s["parent_id"] is None:
                continue
            assert s["parent_id"] in by_id, \
                f"span {s['name']} orphaned (parent {s['parent_id']})"
            parent = by_id[s["parent_id"]]
            assert parent["t0"] <= s["t0"] and s["t1"] <= parent["t1"], \
                f"span {s['name']} leaks outside parent {parent['name']}"

    def test_stages_and_sweeps_in_tree(self, telemetry_run):
        names = [s["name"] for s in telemetry_run["spans"]]
        assert "Read training data" in names  # timed() rides spans now
        assert sum(1 for s in telemetry_run["spans"]
                   if s["name"] == "cd.sweep") == N_SWEEPS

    def test_per_coordinate_loss_and_grad_every_iteration(
            self, telemetry_run):
        steps = [s for s in telemetry_run["spans"] if s["name"] == "cd.step"]
        got = {(s["sweep"], s["coordinate"]) for s in steps}
        want = {(sw, cid) for sw in range(N_SWEEPS)
                for cid in UPDATE_SEQUENCE}
        assert got == want
        for s in steps:
            assert math.isfinite(s["loss"]), s
            assert math.isfinite(s["grad_norm"]), s
        # the objective CD minimizes must not increase along the walk
        ordered = sorted(steps, key=lambda s: s["span_id"])
        losses = [s["loss"] for s in ordered]
        assert losses[-1] <= losses[0] + 1e-6

    def test_optimizer_trace_annotations(self, telemetry_run):
        notes = [n for n in telemetry_run["notes"]
                 if n["name"] == "optimizer_trace"]
        # the fixed effect records its per-iteration table every sweep
        assert {n["sweep"] for n in notes
                if n["coordinate"] == "global"} == set(range(N_SWEEPS))
        for n in notes:
            assert len(n["values"]) == len(n["grad_norms"]) >= 1
            assert all(math.isfinite(v) for v in n["values"])

    def test_metrics_prom_snapshot(self, telemetry_run):
        path = os.path.join(telemetry_run["telemetry_dir"], "metrics.prom")
        parsed = tprom.parse_text(open(path).read())
        for cid in UPDATE_SEQUENCE:
            assert math.isfinite(tprom.series_value(
                parsed, "photon_game_coordinate_loss",
                {"coordinate": cid}, default=math.nan))
            assert tprom.series_value(
                parsed, "photon_game_coordinate_steps_total",
                {"coordinate": cid}) >= N_SWEEPS
        assert tprom.series_value(
            parsed, "photon_optimizer_iterations_total",
            {"coordinate": "global"}) >= 1
        # stage timings arrived through the bridge
        assert tprom.series_value(
            parsed, "photon_stage_seconds_count",
            {"stage": "Read training data"}) >= 1

    def test_tracer_released_after_run(self, telemetry_run):
        from photon_ml_tpu.telemetry import tracing

        assert not tracing.enabled()  # session closed its sink


class TestTrainGameProfiling:
    """The PR-5 acceptance contract: a --telemetry-dir train_game run
    exposes the compile/cost accounting, the compile counter goes flat
    after sweep 1, and perf_report renders the run's artifacts."""

    def _parsed(self, telemetry_run):
        path = os.path.join(telemetry_run["telemetry_dir"], "metrics.prom")
        return tprom.parse_text(open(path).read())

    def test_compile_and_cost_families_exposed(self, telemetry_run):
        parsed = self._parsed(telemetry_run)
        for fn in ("game.fixed_effect", "game.re.sweep_fused"):
            assert tprom.series_value(
                parsed, "photon_compiles_total", {"fn": fn}) >= 1, fn
            assert tprom.series_value(
                parsed, "photon_compile_seconds_total", {"fn": fn}) > 0, fn
            # XLA's CPU cost model prices both solve programs
            assert tprom.series_value(
                parsed, "photon_flops_total", {"fn": fn}) > 0, fn
            assert tprom.series_value(
                parsed, "photon_bytes_accessed_total", {"fn": fn}) > 0, fn
        # the process-wide XLA pipeline listener saw the backend compiles
        assert tprom.series_value(
            parsed, "photon_xla_compile_seconds_total",
            {"phase": "backend"}) > 0
        # dispatch timing flows through the registry histogram (rule 5)
        assert tprom.series_value(
            parsed, "photon_game_step_dispatch_seconds_count",
            {"coordinate": "global"}) >= N_SWEEPS

    def test_compile_counter_flat_after_first_sweep(self, telemetry_run):
        """The training flat-recompile contract, trace-visible: every
        cd.sweep span past the first carries compiles == 0."""
        sweeps = sorted((s for s in telemetry_run["spans"]
                         if s["name"] == "cd.sweep"),
                        key=lambda s: s["sweep"])
        assert len(sweeps) == N_SWEEPS
        assert all("compiles" in s for s in sweeps)
        assert sweeps[0]["compiles"] >= 1  # the cold sweep pays them all
        for s in sweeps[1:]:
            assert s["compiles"] == 0, \
                f"sweep {s['sweep']} recompiled {s['compiles']} programs"

    def test_perf_report_renders_run_artifacts(self, telemetry_run):
        import sys as _sys

        _sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools"))
        import perf_report

        trace_path, prom_path = perf_report.resolve_inputs(
            telemetry_run["telemetry_dir"])
        spans = perf_report.load_spans(trace_path)
        report = perf_report.build_report(spans, open(prom_path).read())
        assert "critical path" in report
        assert "cd.step{coordinate=global}" in report
        assert "game.fixed_effect" in report
        assert "per-coordinate" in report
        # the report is a pure function of the artifacts
        assert report == perf_report.build_report(
            spans, open(prom_path).read())


class TestServeGameMetricsEndpoint:
    def _get(self, url):
        with urllib.request.urlopen(url, timeout=60) as resp:
            return resp.read().decode()

    def _post(self, url, payload):
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as resp:
            return json.loads(resp.read())

    def test_metrics_endpoint_live_server(self, telemetry_run):
        """curl /metrics on a running serve_game: valid Prometheus text
        with the acceptance families, and a recompile counter that stays
        flat across varying batch sizes."""
        from photon_ml_tpu.cli import serve_game as serve_game_cli

        server = serve_game_cli.build_server([
            "--model-dir", telemetry_run["model_dir"],
            "--feature-shards", SHARDS,
            "--port", "0", "--max-batch", "8", "--max-wait-ms", "1",
        ]).start()
        try:
            base = server.url
            m0 = tprom.parse_text(self._get(base + "/metrics"))
            assert tprom.series_value(
                m0, "photon_model_active_version") >= 1
            # serving traces count under the system-wide compile family
            assert tprom.series_value(
                m0, "photon_compiles_total", {"fn": "serving.score"}) >= 1
            assert "photon_serving_request_latency_seconds_bucket" in m0

            recs = _records(8, seed=11)
            for size in (1, 2, 3, 5, 8):
                out = self._post(base + "/score", {"records": recs[:size]})
                assert len(out["scores"]) == size
            m1 = tprom.parse_text(self._get(base + "/metrics"))

            def delta(name, labels=None):
                return (tprom.series_value(m1, name, labels)
                        - tprom.series_value(m0, name, labels))

            # zero-recompile contract, scrape-visible: warmup pre-traced
            # every bucket, so varied request sizes move nothing
            assert delta("photon_compiles_total",
                         {"fn": "serving.score"}) == 0
            assert delta("photon_serving_requests_total") == 5
            assert delta("photon_serving_scored_rows_total") == 1 + 2 + 3 + 5 + 8
            assert delta(
                "photon_serving_request_latency_seconds_count") == 5
            # per-bucket engine histogram populated for the padded shapes
            assert delta("photon_serving_score_latency_seconds_count",
                         {"bucket": "8"}) >= 2  # sizes 5 and 8 pad to 8
            # microbatcher gauges/histograms registered and sane
            assert tprom.series_value(
                m1, "photon_serving_batch_size_count") >= 1
        finally:
            server.stop()
            server.telemetry.close()


class TestServingStageHistograms:
    _get = TestServeGameMetricsEndpoint._get
    _post = TestServeGameMetricsEndpoint._post

    def test_every_stage_lands_and_perf_report_renders_section(
            self, telemetry_run):
        """The request-path critical path: one live request populates all
        five photon_serving_stage_seconds stages (parse and respond from
        the HTTP layer, queue_wait from the microbatcher, batch_assemble
        and execute from the engine), and perf_report renders the serving
        section from the scrape alone."""
        from photon_ml_tpu.cli import serve_game as serve_game_cli

        server = serve_game_cli.build_server([
            "--model-dir", telemetry_run["model_dir"],
            "--feature-shards", SHARDS,
            "--port", "0", "--max-batch", "8", "--max-wait-ms", "1",
        ]).start()
        try:
            base = server.url
            recs = _records(4, seed=31)
            # a single record rides the microbatcher (queue_wait); the
            # batch goes straight to the engine (batch_assemble/execute)
            self._post(base + "/score", {"record": recs[0]})
            self._post(base + "/score", {"records": recs})
            text = self._get(base + "/metrics")
        finally:
            server.stop()
            server.telemetry.close()
        parsed = tprom.parse_text(text)
        for stage in ("parse", "queue_wait", "batch_assemble", "execute",
                      "respond"):
            assert tprom.series_value(
                parsed, "photon_serving_stage_seconds_count",
                {"stage": stage}) >= 1, stage
        import sys as _sys

        _sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools"))
        import perf_report

        report = perf_report.build_report([], text)
        assert "serving request path" in report
        for stage in ("parse", "queue_wait", "batch_assemble", "execute",
                      "respond"):
            assert stage in report
        assert "requests " in report  # the end-to-end histogram line


class TestTelemetryOverheadGuard:
    def test_scores_bit_identical_and_zero_recompiles_with_tracing(
            self, telemetry_run, tmp_path):
        """The overhead guard: turning the tracer ON changes nothing the
        engine computes — scores stay bit-identical and warmup's
        executables still cover every request size."""
        from photon_ml_tpu.cli.config import parse_feature_shard_config
        from photon_ml_tpu.serving import ModelRegistry
        from photon_ml_tpu.telemetry import tracing

        shard_configs = tuple(parse_feature_shard_config(s)
                              for s in SHARDS.split(","))
        registry = ModelRegistry(shard_configs, max_batch=8)
        sm = registry.load(telemetry_run["model_dir"])
        sm.engine.warmup()
        recs = _records(8, seed=23)
        baseline = sm.score(recs)
        frozen = sm.engine.compile_count
        tracing.configure(str(tmp_path / "trace.jsonl"))
        try:
            for size in (1, 3, 5, 8):
                got = sm.score(recs[:size])
                assert np.array_equal(got, baseline[:size])
        finally:
            tracing.close()
        assert sm.engine.compile_count == frozen


class TestDeviceSampler:
    def test_sample_once_populates_gauges(self):
        from photon_ml_tpu.telemetry.device import DeviceStatsSampler

        reg = MetricsRegistry()
        sampler = DeviceStatsSampler(60.0, registry=reg)
        sampler.sample_once()
        assert reg.get("photon_host_rss_bytes").value > 0
        assert reg.get("photon_device_samples_total").value == 1

    def test_start_close_lifecycle(self):
        from photon_ml_tpu.telemetry.device import DeviceStatsSampler

        reg = MetricsRegistry()
        sampler = DeviceStatsSampler(30.0, registry=reg).start()
        sampler.close()  # immediate: the wait is an Event, not a sleep
        assert reg.get("photon_device_samples_total").value >= 1

    def test_rejects_nonpositive_interval(self):
        from photon_ml_tpu.telemetry.device import DeviceStatsSampler

        with pytest.raises(ValueError):
            DeviceStatsSampler(0.0)
