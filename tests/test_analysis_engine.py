"""Unit tests for the unified static-analysis engine
(``photon_ml_tpu/analysis/``): per-rule fixtures with known violations,
suppression semantics (positive + suppressed + justified cases), the
machine-readable JSON report, and shim message compatibility.

Tree-wide zero-finding runs and CLI exit codes live in
``tests/test_photon_lint.py``; the legacy hygiene subsets keep their own
tier-1 wrappers (``test_resilience_hygiene.py`` /
``test_telemetry_hygiene.py``)."""

import json
import os
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from photon_ml_tpu.analysis import engine  # noqa: E402

PKG = os.path.join("photon_ml_tpu", "x.py")


def check(source, rules, rel=PKG):
    return engine.check_source(textwrap.dedent(source), rel, rules)


def rule_ids(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


def test_all_rules_catalog():
    rules = engine.all_rules()
    # the 12 legacy hygiene rules...
    legacy = {"res-bare-except", "res-sleep", "res-part-write",
              "res-process", "res-table-home", "tel-print",
              "tel-perf-counter", "tel-metric-name", "tel-registry",
              "tel-wall-clock", "tel-drift-home", "tel-request-identity"}
    # ...the two new passes...
    new = {"trace-print", "trace-clock", "trace-random", "trace-host-sync",
           "trace-mutable-global", "lock-guarded-write",
           "lock-missing-guard"}
    # ...and the whole-tree consistency rules
    project = {"obs-metric-catalog", "res-fault-coverage"}
    assert legacy | new | project <= set(rules)
    assert all(r.summary for r in rules.values())
    # legacy rules stay scoped to the package; the new passes cover tools/
    assert all(rules[r].scope == "package" for r in legacy)
    assert all(rules[r].scope == "all" for r in new)
    assert all(rules[r].scope == "project" for r in project)


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------


def test_justified_suppression_silences_the_finding():
    src = """
    import time
    time.sleep(1)  # photon-lint: disable=res-sleep -- chaos fixture needs a raw stall
    """
    assert check(src, ["res-sleep"]) == []


def test_suppression_without_reason_is_itself_a_finding():
    src = """
    import time
    time.sleep(1)  # photon-lint: disable=res-sleep
    """
    out = check(src, ["res-sleep"])
    assert sorted(rule_ids(out)) == ["lint-suppression", "res-sleep"]


def test_suppression_with_unknown_rule_id_is_flagged():
    src = "x = 1  # photon-lint: disable=no-such-rule -- because\n"
    out = check(src, ["res-sleep"])
    assert rule_ids(out) == ["lint-suppression"]
    assert "no-such-rule" in out[0].message


def test_suppression_only_covers_its_rule():
    src = """
    import time
    time.sleep(1)  # photon-lint: disable=res-bare-except -- wrong id
    """
    out = check(src, ["res-sleep", "res-bare-except"])
    assert rule_ids(out) == ["res-sleep"]


def test_def_line_suppression_covers_the_whole_body():
    src = """
    import time

    def stall_helper():  # photon-lint: disable=res-sleep -- test-only stall helper
        time.sleep(1)
        time.sleep(2)

    time.sleep(3)
    """
    out = check(src, ["res-sleep"])
    assert [f.line for f in out] == [8]


def test_class_line_suppression_covers_methods():
    src = """
    import threading

    class W:  # photon-lint: disable=lock-missing-guard -- single-writer by construction
        def __init__(self):
            self.n = 0
            threading.Thread(target=self.run).start()

        def run(self):
            self.n += 1
    """
    assert check(src, ["lock-missing-guard"]) == []


def test_multi_rule_suppression():
    src = """
    import time
    d = time.time() - time.perf_counter()  # photon-lint: disable=tel-wall-clock,tel-perf-counter -- fixture
    """
    assert check(src, ["tel-wall-clock", "tel-perf-counter"]) == []


# ---------------------------------------------------------------------------
# trace-safety fixtures
# ---------------------------------------------------------------------------

TRACE_RULES = ["trace-print", "trace-clock", "trace-random",
               "trace-host-sync", "trace-mutable-global"]


def test_trace_decorated_jit_function_flags_side_effects():
    src = """
    import time
    import random
    import numpy as np
    import jax

    @jax.jit
    def bad(x):
        print("tracing")
        t = time.time()
        r = random.random()
        h = np.asarray(x)
        return x + t + r
    """
    out = check(src, TRACE_RULES)
    assert rule_ids(out) == ["trace-print", "trace-clock", "trace-random",
                             "trace-host-sync"]


def test_trace_partial_jit_decorator_and_item_and_float_param():
    src = """
    import functools
    import jax

    @functools.partial(jax.jit, static_argnames=("n",))
    def bad(x, n):
        v = x.mean().item()
        f = float(x)
        return v + f
    """
    out = check(src, TRACE_RULES)
    assert rule_ids(out) == ["trace-host-sync", "trace-host-sync"]


def test_trace_callsite_registration_and_reachability():
    src = """
    import numpy as np
    import jax

    def helper(x):
        return np.asarray(x)

    def entry(x):
        return helper(x) + 1

    entry_jit = jax.jit(entry)

    def never_traced(x):
        return np.asarray(x)  # fine: not reachable from a jit site
    """
    out = check(src, TRACE_RULES)
    assert [(f.rule, f.line) for f in out] == [("trace-host-sync", 6)]


def test_trace_jit_vmap_nesting_and_lambda():
    src = """
    import time
    import jax

    def solve_one(w):
        time.monotonic()
        return w

    ws = jax.jit(jax.vmap(solve_one))
    f = jax.jit(lambda x: time.time() + x)
    """
    out = check(src, TRACE_RULES)
    assert rule_ids(out) == ["trace-clock", "trace-clock"]


def test_trace_profile_jit_and_pallas_call():
    src = """
    import numpy as np
    from photon_ml_tpu.telemetry.profiling import profile_jit
    import jax.experimental.pallas as pl

    def train(x):
        print("side effect")
        return x

    train_fn = profile_jit(train, "game.fixed_effect")

    def kernel(x_ref, o_ref):
        np.random.rand()
        o_ref[...] = x_ref[...]

    def launch(x):
        return pl.pallas_call(kernel, out_shape=None)(x)
    """
    out = check(src, TRACE_RULES)
    assert rule_ids(out) == ["trace-print", "trace-random"]


def test_trace_mutable_global_capture_and_global_stmt():
    src = """
    import jax

    _CACHE = {}
    _LIMITS = (1, 2)  # immutable: fine to close over

    @jax.jit
    def bad(x):
        global _TOTAL
        _TOTAL = x
        return x + _CACHE.get("k", 0) + _LIMITS[0]
    """
    out = check(src, TRACE_RULES)
    assert rule_ids(out) == ["trace-mutable-global", "trace-mutable-global"]


def test_trace_method_name_collision_is_not_dragged_in():
    # a *method* named train must not be conflated with a traced local
    # function of the same name (lexical scope resolution)
    src = """
    import numpy as np
    from photon_ml_tpu.telemetry.profiling import profile_jit

    def make():
        def train(x):
            return x

        return profile_jit(train, "x")

    class Coordinate:
        def train(self, offsets):
            return np.asarray(offsets)  # host code, not traced
    """
    assert check(src, TRACE_RULES) == []


# ---------------------------------------------------------------------------
# lock-discipline fixtures
# ---------------------------------------------------------------------------

LOCK_RULES = ["lock-guarded-write", "lock-missing-guard"]


def test_lock_guarded_write_outside_lock_is_flagged():
    src = """
    import threading

    class Q:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []  # guarded-by: _lock

        def put(self, x):
            self._items.append(x)
    """
    out = check(src, LOCK_RULES)
    assert rule_ids(out) == ["lock-guarded-write"]
    assert "self._items" in out[0].message


def test_lock_guarded_write_inside_lock_is_clean():
    src = """
    import threading

    class Q:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []  # guarded-by: _lock
            self._n = 0       # guarded-by: _lock

        def put(self, x):
            with self._lock:
                self._items.append(x)
                self._n += 1
    """
    assert check(src, LOCK_RULES) == []


def test_lock_condition_variable_counts_as_a_lock():
    src = """
    import threading

    class Q:
        def __init__(self):
            self._cond = threading.Condition()
            self._queue = []  # guarded-by: _cond

        def put(self, x):
            with self._cond:
                self._queue.append(x)
                self._cond.notify()

        def bad_put(self, x):
            self._queue.append(x)
    """
    out = check(src, LOCK_RULES)
    assert [(f.rule, f.line) for f in out] == [("lock-guarded-write", 15)]


def test_lock_threaded_class_must_annotate_mutations():
    src = """
    import threading

    class W:
        def __init__(self):
            self.jobs = 0
            threading.Thread(target=self.run, daemon=True).start()

        def run(self):
            self.jobs += 1
    """
    out = check(src, LOCK_RULES)
    assert rule_ids(out) == ["lock-missing-guard"]


def test_lock_unthreaded_class_needs_no_annotations():
    src = """
    class Plain:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
    """
    assert check(src, LOCK_RULES) == []


def test_lock_executor_submit_makes_a_class_threaded():
    src = """
    class S:
        def __init__(self, pool):
            self._pool = pool
            self.pending = []

        def kick(self, fn):
            self.pending.append(self._pool.submit(fn))
    """
    out = check(src, LOCK_RULES)
    assert rule_ids(out) == ["lock-missing-guard"]


def test_lock_locked_suffix_method_is_exempt():
    src = """
    import threading

    class Q:
        def __init__(self):
            self._lock = threading.Lock()
            self._buf = []  # guarded-by: _lock

        def _take_buffer_locked(self):
            batch, self._buf = self._buf, []
            return batch
    """
    assert check(src, LOCK_RULES) == []


def test_lock_caller_guard_satisfies_completeness():
    src = """
    import threading

    class Server:
        def __init__(self):
            self._thread = None  # guarded-by: caller

        def start(self):
            self._thread = threading.Thread(target=lambda: None)
            self._thread.start()

        def stop(self):
            self._thread = None
    """
    assert check(src, LOCK_RULES) == []


def test_lock_write_in_except_handler_is_seen():
    src = """
    import threading

    class W:
        def __init__(self):
            self._lock = threading.Lock()
            self.errors = 0  # guarded-by: _lock
            threading.Thread(target=self.run).start()

        def run(self):
            try:
                pass
            except Exception:
                self.errors += 1
    """
    out = check(src, LOCK_RULES)
    assert rule_ids(out) == ["lock-guarded-write"]


def test_lock_closure_does_not_inherit_the_with_block():
    # a nested def lexically under `with self._lock:` runs LATER, without
    # the lock — its writes must still be flagged
    src = """
    import threading

    class W:
        def __init__(self, pool):
            self._lock = threading.Lock()
            self._pool = pool
            self.done = 0  # guarded-by: _lock

        def kick(self):
            with self._lock:
                def job():
                    self.done += 1
                self._pool.submit(job)
    """
    out = check(src, LOCK_RULES)
    assert rule_ids(out) == ["lock-guarded-write"]


def test_lock_tuple_swap_target_is_seen():
    src = """
    import threading

    class W:
        def __init__(self):
            self._lock = threading.Lock()
            self._pending = []  # guarded-by: _lock
            threading.Thread(target=self.run).start()

        def run(self):
            pending, self._pending = self._pending, []
    """
    out = check(src, LOCK_RULES)
    assert rule_ids(out) == ["lock-guarded-write"]


# ---------------------------------------------------------------------------
# project rules (synthetic trees)
# ---------------------------------------------------------------------------


def _write(root, rel, text):
    path = os.path.join(root, rel)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        f.write(textwrap.dedent(text))


def test_metric_catalog_drift_both_directions(tmp_path):
    root = str(tmp_path)
    _write(root, "photon_ml_tpu/m.py", """
    from photon_ml_tpu.telemetry import metrics as _metrics
    _C = _metrics.counter("photon_undocumented_total", "help text")
    """)
    _write(root, "OBSERVABILITY.md", """
    | family | type | labels | meaning |
    |---|---|---|---|
    | `photon_ghost_total` | counter | — | documented but never registered |
    """)
    report = engine.run(root, rule_ids=["obs-metric-catalog"])
    got = {(f.path, f.rule): f.message for f in report.findings}
    assert len(report.findings) == 2
    assert any("photon_undocumented_total" in m for m in got.values())
    assert any("photon_ghost_total" in m for m in got.values())


def test_metric_catalog_clean_when_in_sync(tmp_path):
    root = str(tmp_path)
    _write(root, "photon_ml_tpu/m.py", """
    from photon_ml_tpu.telemetry import metrics as _metrics
    _C = _metrics.counter("photon_good_total", "help text")
    """)
    _write(root, "OBSERVABILITY.md", """
    | `photon_good_total` | counter | — | a documented family |
    """)
    report = engine.run(root, rule_ids=["obs-metric-catalog"])
    assert report.findings == []


def test_fault_site_coverage_rule(tmp_path):
    root = str(tmp_path)
    _write(root, "photon_ml_tpu/resilience/faults.py", """
    SITES = ("io.read", "never.injected")

    def fault_point(site, **kw):
        pass
    """)
    _write(root, "photon_ml_tpu/io/reader.py", """
    from photon_ml_tpu.resilience.faults import fault_point

    def read(path):
        fault_point("io.read", path=path)
    """)
    _write(root, "tests/test_chaos.py", """
    def test_read_fault():
        assert "io.read"
    """)
    report = engine.run(root, rule_ids=["res-fault-coverage"])
    msgs = [f.message for f in report.findings]
    # never.injected: no injection call site AND no test mentions it
    assert len(msgs) == 2
    assert all("never.injected" in m for m in msgs)
    assert any("injects" in m for m in msgs)
    assert any("tests/" in m for m in msgs)


# ---------------------------------------------------------------------------
# JSON report (golden)
# ---------------------------------------------------------------------------


def test_json_report_golden(tmp_path):
    root = str(tmp_path)
    _write(root, "photon_ml_tpu/x.py", """
    import time
    time.sleep(1)
    time.sleep(2)  # photon-lint: disable=res-sleep -- fixture: sanctioned stall
    """)
    report = engine.run(root, rule_ids=["res-sleep"])
    assert json.loads(report.to_json()) == {
        "version": 1,
        "rules": ["res-sleep"],
        "findings": [{
            "path": os.path.join("photon_ml_tpu", "x.py"),
            "line": 3,
            "rule": "res-sleep",
            "message": ("time.sleep outside resilience/retry.py — route "
                        "waits through the retry module so deadlines and "
                        "the watchdog see them"),
        }],
        "suppressed": [{
            "path": os.path.join("photon_ml_tpu", "x.py"),
            "line": 4,
            "rule": "res-sleep",
            "message": ("time.sleep outside resilience/retry.py — route "
                        "waits through the retry module so deadlines and "
                        "the watchdog see them"),
            "reason": "fixture: sanctioned stall",
        }],
        "counts": {"findings": 1, "suppressed": 1},
    }


# ---------------------------------------------------------------------------
# shim compatibility (message byte-parity with the pre-engine tools)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("snippet, expected", [
    ("try:\n    pass\nexcept:\n    pass\n",
     ["photon_ml_tpu/x.py:3: bare `except:` — catch a type (it swallows "
      "KeyboardInterrupt/SystemExit)"]),
    ("import time\ntime.sleep(1)\n",
     ["photon_ml_tpu/x.py:2: time.sleep outside resilience/retry.py — "
      "route waits through the retry module so deadlines and the "
      "watchdog see them"]),
])
def test_resilience_shim_messages_are_byte_identical(snippet, expected):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import check_resilience_hygiene as shim

    assert shim.check_source(snippet, "photon_ml_tpu/x.py") == expected


@pytest.mark.parametrize("snippet, expected", [
    ("print('x')\n",
     ["photon_ml_tpu/x.py:1: print() outside a CLI entry point — library "
      "code logs, counts (telemetry.metrics) or spans (telemetry."
      "tracing); stdout belongs to the drivers"]),
    ("import time\nd = time.time() - 1.0\n",
     ["photon_ml_tpu/x.py:2: duration computed from time.time() — the "
      "wall clock is for timestamps (it jumps); measure durations with a "
      "registry timer or a tracing span"]),
])
def test_telemetry_shim_messages_are_byte_identical(snippet, expected):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import check_telemetry_hygiene as shim

    assert shim.check_source(snippet, "photon_ml_tpu/x.py") == expected


# ---------------------------------------------------------------------------
# res-bounded-queue (serving/ only — the admission-control contract)
# ---------------------------------------------------------------------------

SERVING = os.path.join("photon_ml_tpu", "serving", "x.py")


def test_bounded_queue_flags_unbounded_deque_in_serving_only():
    src = """
        import collections

        class Batcher:
            def __init__(self):
                self.q = collections.deque()
    """
    assert rule_ids(check(src, ["res-bounded-queue"], rel=SERVING)) == \
        ["res-bounded-queue"]
    # the same construction outside serving/ is not a request queue
    assert check(src, ["res-bounded-queue"]) == []


def test_bounded_queue_accepts_bounded_deque_and_from_import_alias():
    clean = """
        import collections
        from collections import deque

        class Batcher:
            def __init__(self):
                self.a = collections.deque(maxlen=128)
                self.b = deque((), 128)
    """
    assert check(clean, ["res-bounded-queue"], rel=SERVING) == []
    bad = """
        from collections import deque as dq

        class Batcher:
            def __init__(self):
                self.q = dq()
    """
    assert rule_ids(check(bad, ["res-bounded-queue"], rel=SERVING)) == \
        ["res-bounded-queue"]


def test_bounded_queue_flags_queue_constructions():
    src = """
        import queue
        from queue import Queue, SimpleQueue

        class Front:
            def __init__(self):
                self.a = queue.Queue()          # unbounded
                self.b = Queue(maxsize=0)       # explicit unbounded
                self.c = queue.Queue(64)        # bounded: fine
                self.d = Queue(maxsize=64)      # bounded: fine
                self.e = SimpleQueue()          # never boundable
    """
    got = check(src, ["res-bounded-queue"], rel=SERVING)
    assert rule_ids(got) == ["res-bounded-queue"] * 3
    assert [f.line for f in got] == [7, 8, 11]


def test_bounded_queue_flags_list_as_queue():
    src = """
        class Log:
            def __init__(self):
                self.segments = []
                self.plain = []

            def rotate(self):
                self.segments.pop(0)

            def note(self, x):
                self.plain.append(x)
    """
    got = check(src, ["res-bounded-queue"], rel=SERVING)
    # only the FIFO-drained attribute is a queue; the append-only list
    # is not flagged
    assert rule_ids(got) == ["res-bounded-queue"]
    assert "segments" in got[0].message


def test_bounded_queue_suppression_needs_justification():
    src = """
        import collections

        class Batcher:
            def __init__(self):
                self.q = collections.deque()  # photon-lint: disable=res-bounded-queue -- bounded by the admission check in submit()
    """
    assert check(src, ["res-bounded-queue"], rel=SERVING) == []


# ---------------------------------------------------------------------------
# res-shard-home (crc32 identity bucketing confined to fleet/sharding.py)
# ---------------------------------------------------------------------------

SHARD_HOME = os.path.join("photon_ml_tpu", "fleet", "sharding.py")
AVRO = os.path.join("photon_ml_tpu", "io", "avro.py")


def test_shard_home_flags_crc32_outside_the_home():
    src = """
        import zlib

        def shard(raw, n):
            return zlib.crc32(raw.encode()) % n
    """
    assert rule_ids(check(src, ["res-shard-home"])) == ["res-shard-home"]


def test_shard_home_allows_the_home_and_the_avro_checksum():
    src = """
        import zlib

        def crc_bucket(key, mod):
            return zlib.crc32(key.encode("utf-8")) % mod
    """
    assert check(src, ["res-shard-home"], rel=SHARD_HOME) == []
    # container checksums over raw bytes are integrity, not identity
    assert check(src, ["res-shard-home"], rel=AVRO) == []


def test_shard_home_sees_aliases_and_binascii():
    aliased = """
        import zlib as z

        def f(x):
            return z.crc32(x)
    """
    assert rule_ids(check(aliased, ["res-shard-home"])) == \
        ["res-shard-home"]
    from_import = """
        from binascii import crc32 as c

        def f(x):
            return c(x)
    """
    assert rule_ids(check(from_import, ["res-shard-home"])) == \
        ["res-shard-home"]


def test_shard_home_ignores_unrelated_crc32_names():
    src = """
        class Hasher:
            def crc32(self, x):
                return 7

        def f(h, x):
            return h.crc32(x)  # not zlib's — some object's method
    """
    assert check(src, ["res-shard-home"]) == []


def test_shard_home_clean_call_sites_pass():
    src = """
        from photon_ml_tpu.fleet.sharding import crc_bucket, shard_of_id

        def sample(request_id):
            return crc_bucket(str(request_id), 1 << 16) < 100

        def place(raw, n):
            return shard_of_id(raw, n)
    """
    assert check(src, ["res-shard-home"]) == []


def test_shard_home_flags_virtual_bucket_modulo():
    # the literal bucket count recomputed outside the home
    literal = """
        def bucket(h):
            return h % 4096
    """
    got = check(literal, ["res-shard-home"])
    assert rule_ids(got) == ["res-shard-home"]
    assert "ShardMap" in got[0].message
    # ...and via the imported constant, from-import or module-attribute
    from_import = """
        from photon_ml_tpu.fleet.sharding import N_BUCKETS

        def bucket(h):
            return h % N_BUCKETS
    """
    assert rule_ids(check(from_import, ["res-shard-home"])) == \
        ["res-shard-home"]
    via_module = """
        import photon_ml_tpu.fleet.sharding as sharding

        def bucket(h):
            return h % sharding.N_BUCKETS
    """
    assert rule_ids(check(via_module, ["res-shard-home"])) == \
        ["res-shard-home"]


def test_shard_home_bucket_modulo_allowed_in_the_home():
    src = """
        def bucket(h):
            return h % 4096
    """
    assert check(src, ["res-shard-home"], rel=SHARD_HOME) == []


def test_shard_home_ignores_unrelated_modulo():
    src = """
        def wrap(i, n):
            return i % n

        def page(off):
            return off % 1024
    """
    assert check(src, ["res-shard-home"]) == []


# ---------------------------------------------------------------------------
# tel-span-attr-cardinality (ISSUE 18): span attributes / metric label
# values derived from unbounded request fields
# ---------------------------------------------------------------------------

CARD = ["tel-span-attr-cardinality"]


def test_span_attr_from_payload_subscript_is_flagged():
    src = """
        from photon_ml_tpu.telemetry import tracing

        def handle(payload):
            with tracing.span("serving.score", user=payload["userId"]):
                pass
    """
    got = check(src, CARD)
    assert rule_ids(got) == ["tel-span-attr-cardinality"]
    assert "unbounded" in got[0].message


def test_span_attr_from_metadata_get_and_entity_name_are_flagged():
    # .get() off a metadata map; a bare entity-id-named local; an
    # f-string wrapping one — each is a distinct unbounded tag value
    src = """
        from photon_ml_tpu.telemetry import tracing

        def handle(meta, user_id):
            sp = tracing.record_span("x", seconds=0.1,
                                     member=meta.get("memberId"))
            sp2 = tracing.record_span("y", seconds=0.1, who=user_id)
            with tracing.span("z", tag=f"u:{user_id}"):
                pass
    """
    assert rule_ids(check(src, CARD)) == ["tel-span-attr-cardinality"] * 3


def test_metric_label_from_payload_field_is_flagged():
    src = """
        from photon_ml_tpu.telemetry import metrics

        C = metrics.counter("photon_x_total", "help", labels=("who",))

        def bump(record):
            C.labels(who=str(record["userId"])).inc()
    """
    got = check(src, CARD)
    assert rule_ids(got) == ["tel-span-attr-cardinality"]
    assert "metric label" in got[0].message


def test_sanctioned_request_id_and_bounded_values_pass():
    # the request id is the designed per-request join key; bounded
    # values (literals, counts, closed-vocabulary stage names from
    # parse_leg_summary) are what tags are FOR
    src = """
        from photon_ml_tpu.telemetry import tracing
        from photon_ml_tpu.serving.http import parse_leg_summary

        def handle(records, request_id, header):
            with tracing.span("serving.score", request_id=request_id,
                              batch=len(records)) as sp:
                sp.set(version=3)
                for stage, seconds in parse_leg_summary(header).items():
                    tracing.record_span("host." + stage, seconds=seconds,
                                        parent_id=sp.span_id)
    """
    assert check(src, CARD) == []


def test_span_attr_cardinality_is_clean_on_the_tree():
    # the rule must hold tree-wide from day one (the router's
    # leg-summary parser is the motivating call site: its closed stage
    # vocabulary is what keeps host.* span names bounded)
    report = engine.run(REPO, rule_ids=["tel-span-attr-cardinality"])
    assert report.findings == [], report.findings


# ---------------------------------------------------------------------------
# tel-conn-home: connection accounting confined to serving/http.py,
# saturation probes name closed-vocabulary resources
# ---------------------------------------------------------------------------

CONN = ["tel-conn-home"]
CONN_HOME = os.path.join("photon_ml_tpu", "serving", "http.py")


def test_conn_home_flags_connection_metric_outside_http():
    src = """
        from photon_ml_tpu.telemetry import metrics

        OPEN = metrics.gauge("photon_connections_open", "a fork")
        LIFE = metrics.histogram("photon_connection_lifetime_seconds",
                                 "another fork")
    """
    got = check(src, CONN)
    assert rule_ids(got) == ["tel-conn-home"] * 2
    assert "ONE writer" in got[0].message


def test_conn_home_flags_tracker_redefinition_outside_http():
    src = """
        class ConnectionTracker:
            def connect(self):
                pass
    """
    got = check(src, CONN)
    assert rule_ids(got) == ["tel-conn-home"]
    assert "accepted == closed + open" in got[0].message


def test_conn_home_allows_the_home_itself():
    src = """
        from photon_ml_tpu.telemetry import metrics

        OPEN = metrics.gauge("photon_connections_open", "host gauge")

        class ConnectionTracker:
            pass
    """
    assert check(src, CONN, rel=CONN_HOME) == []


def test_conn_home_importing_the_tracker_is_fine():
    # instantiation is the sanctioned use — only DEFINITION forks it
    src = """
        from photon_ml_tpu.serving.http import ConnectionTracker

        tracker = ConnectionTracker(max_connections=8)
    """
    assert check(src, CONN) == []


def test_conn_home_add_probe_vocabulary():
    bad_name = """
        sampler.add_probe("gpu_fans", lambda: {})
    """
    got = check(bad_name, CONN)
    assert rule_ids(got) == ["tel-conn-home"]
    assert "closed vocabulary" in got[0].message

    computed = """
        sampler.add_probe("pool_" + str(i), lambda: {})
    """
    got = check(computed, CONN)
    assert rule_ids(got) == ["tel-conn-home"]
    assert "computed at runtime" in got[0].message

    good = """
        sampler.add_probe("batcher_queue", probe)
        sampler.add_probe("router_pool", other)
    """
    assert check(good, CONN) == []


def test_conn_home_vocab_copy_matches_saturation_resources():
    # the rule's static twin must track the runtime vocabulary — the
    # same copy-sync contract as RETAINED_NAME_RE vs SERIES_NAME_RE
    from photon_ml_tpu.analysis.rules_telemetry import SATURATION_RESOURCES
    from photon_ml_tpu.telemetry.saturation import RESOURCES
    assert SATURATION_RESOURCES == frozenset(RESOURCES)


def test_conn_home_is_clean_on_the_tree():
    report = engine.run(REPO, rule_ids=["tel-conn-home"])
    assert report.findings == [], report.findings
