"""Tests for the public photon_ml_tpu.testing module (photon-test-utils)."""

import numpy as np

from photon_ml_tpu import testing as ptu


class TestGenerators:
    def test_make_classification(self):
        data, x, labels = ptu.make_classification(n=100, d=5, intercept=True,
                                                  weights=True)
        assert data.n_samples == 100 and data.dim == 6
        assert x.shape == (100, 6) and (x[:, -1] == 1.0).all()
        assert set(np.unique(labels)) <= {0.0, 1.0}
        assert (np.asarray(data.weights) > 0).all()

    def test_make_mixed_effect(self):
        data, (xf, xr, ent, w, u) = ptu.make_mixed_effect(
            n=300, n_entities=7, entity_column="userId")
        assert data.n_samples == 300
        assert set(data.shards) == {"fixed", "re"}
        assert data.id_columns["userId"].max() < 7

    def test_finite_difference_matches_autodiff(self):
        import jax

        from photon_ml_tpu.ops.losses import LogisticLoss
        from photon_ml_tpu.ops.objective import GLMObjective

        data, _, _ = ptu.make_classification(n=50, d=4, seed=3)
        obj = GLMObjective(loss=LogisticLoss)
        w = np.random.default_rng(0).normal(size=4)
        fd = ptu.finite_difference_gradient(
            lambda wv: obj.value(wv, data, 0.5), w)
        ad = np.asarray(jax.grad(lambda wv: obj.value(wv, data, 0.5))(w))
        ptu.assert_allclose_coefficients(ad, fd, atol=1e-5)
